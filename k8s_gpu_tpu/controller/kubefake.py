"""In-memory Kubernetes API server — the envtest analogue (SURVEY §4 item 2).

Provides the apimachinery semantics the reconcilers depend on:

- typed object store keyed by (kind, namespace, name), deep-copied on every
  read/write boundary (no shared mutable state with clients);
- optimistic concurrency via resourceVersion (Conflict on stale writes);
- a **status subresource** (``update_status`` bumps resourceVersion but not
  generation; spec updates bump generation — matching
  ``//+kubebuilder:subresource:status``, reference README.md:130-131);
- finalizer-aware deletion (delete sets deletionTimestamp and waits for
  finalizers to clear — the graceful-deletion mechanism the reference lists
  as hardening, README.md:309);
- label-selector list, and watch fan-out to subscribers (the event source
  feeding controller work queues, reference README.md:170).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable

from ..api.types import CustomResource, ValidationError


class NotFound(Exception):
    pass


class Conflict(Exception):
    """Stale resourceVersion — the optimistic-concurrency failure mode the
    reference's status-update retry guards against (README.md:224-230)."""


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    obj: CustomResource


class FakeKube:
    def __init__(self):
        self._lock = threading.RLock()
        self._store: dict[tuple[str, str, str], CustomResource] = {}
        self._rv = 0
        self._watchers: dict[str, list[Callable[[WatchEvent], None]]] = {}
        # Admission chain: callables (op, obj) invoked before a create/update
        # is stored; raising rejects the write (quota/limit-range seam,
        # auth/quota.py).  May mutate obj (defaulting webhook semantics).
        self.admission: list[Callable[[str, CustomResource], None]] = []

    # -- helpers -----------------------------------------------------------
    def _key(self, kind: str, namespace: str, name: str) -> tuple[str, str, str]:
        return (kind, namespace, name)

    def _next_rv(self) -> int:
        """Monotone resourceVersion.  Lock held by caller (every
        store-mutating verb)."""
        self._rv += 1
        return self._rv

    def _notify(self, etype: str, obj: CustomResource) -> None:
        for cb in self._watchers.get(obj.kind, []) + self._watchers.get("*", []):
            cb(WatchEvent(etype, obj.deepcopy()))

    # -- CRUD --------------------------------------------------------------
    def create(self, obj: CustomResource) -> CustomResource:
        obj.validate()
        with self._lock:
            k = self._key(obj.kind, obj.metadata.namespace, obj.metadata.name)
            if k in self._store:
                # Conflict wins over admission: operators rely on
                # create-if-absent (`except Conflict: requeue`), and a quota
                # error here would double-count the existing object.
                raise Conflict(f"{obj.kind} {k[1]}/{k[2]} already exists")
            for admit in self.admission:
                admit("create", obj)
            stored = obj.deepcopy()
            stored.metadata.uid = uuid.uuid4().hex
            stored.metadata.resource_version = self._next_rv()
            stored.metadata.generation = 1
            stored.metadata.creation_timestamp = time.time()
            self._store[k] = stored
            self._notify("ADDED", stored)
            return stored.deepcopy()

    def get(self, kind: str, name: str, namespace: str = "default") -> CustomResource:
        with self._lock:
            k = self._key(kind, namespace, name)
            if k not in self._store:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return self._store[k].deepcopy()

    def try_get(self, kind: str, name: str, namespace: str = "default"):
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def update(self, obj: CustomResource) -> CustomResource:
        """Spec/metadata update: bumps generation when spec changed."""
        obj.validate()
        with self._lock:
            k = self._key(obj.kind, obj.metadata.namespace, obj.metadata.name)
            cur = self._store.get(k)
            if cur is None:
                raise NotFound(f"{obj.kind} {k[1]}/{k[2]} not found")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"stale resourceVersion {obj.metadata.resource_version} "
                    f"(current {cur.metadata.resource_version})"
                )
            for admit in self.admission:
                admit("update", obj)
            stored = obj.deepcopy()
            stored.metadata.uid = cur.metadata.uid
            stored.metadata.creation_timestamp = cur.metadata.creation_timestamp
            spec_changed = getattr(obj, "spec", None) != getattr(cur, "spec", None)
            stored.metadata.generation = cur.metadata.generation + (
                1 if spec_changed else 0
            )
            # Status is a subresource: plain updates cannot change it.
            if hasattr(cur, "status"):
                stored.status = cur.deepcopy().status
            # No-op writes don't bump resourceVersion or fire watch events
            # (API-server semantics; also breaks status-write → watch →
            # reconcile → status-write hot loops).
            stored.metadata.resource_version = cur.metadata.resource_version
            if stored == cur:
                return stored
            stored.metadata.resource_version = self._next_rv()
            self._store[k] = stored
            self._notify("MODIFIED", stored)
            # Finalizer removal may complete a pending delete.
            self._maybe_finalize_delete(k)
            return stored.deepcopy() if k in self._store else stored

    def update_status(self, obj: CustomResource) -> CustomResource:
        """Status-subresource update: spec is untouched, generation frozen."""
        with self._lock:
            k = self._key(obj.kind, obj.metadata.namespace, obj.metadata.name)
            cur = self._store.get(k)
            if cur is None:
                raise NotFound(f"{obj.kind} {k[1]}/{k[2]} not found")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"stale resourceVersion {obj.metadata.resource_version} "
                    f"(current {cur.metadata.resource_version})"
                )
            stored = cur.deepcopy()
            stored.status = obj.deepcopy().status
            if stored == cur:  # no-op status write (see update())
                return stored
            stored.metadata.resource_version = self._next_rv()
            self._store[k] = stored
            self._notify("MODIFIED", stored)
            return stored.deepcopy()

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        with self._lock:
            k = self._key(kind, namespace, name)
            cur = self._store.get(k)
            if cur is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if cur.metadata.finalizers:
                if cur.metadata.deletion_timestamp is None:
                    cur.metadata.deletion_timestamp = time.time()
                    cur.metadata.resource_version = self._next_rv()
                    self._notify("MODIFIED", cur)
                return
            del self._store[k]
            self._notify("DELETED", cur)

    def _maybe_finalize_delete(self, k: tuple[str, str, str]) -> None:
        """Complete a finalizer-deferred delete.  Lock held by caller
        (``update``/``patch_status``)."""
        cur = self._store.get(k)
        if (
            cur is not None
            and cur.metadata.deletion_timestamp is not None
            and not cur.metadata.finalizers
        ):
            del self._store[k]
            self._notify("DELETED", cur)

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[CustomResource]:
        with self._lock:
            out = []
            for (knd, ns, _), obj in self._store.items():
                if knd != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if label_selector and not all(
                    obj.metadata.labels.get(lk) == lv
                    for lk, lv in label_selector.items()
                ):
                    continue
                out.append(obj.deepcopy())
            return sorted(out, key=lambda o: (o.metadata.namespace, o.metadata.name))

    # -- persistence (CLI-local platform state) ----------------------------
    def dump(self) -> dict:
        """Snapshot for pickling (locks/watchers excluded)."""
        with self._lock:
            import copy

            return {"store": copy.deepcopy(self._store), "rv": self._rv}

    def load(self, snapshot: dict) -> None:
        with self._lock:
            self._store = snapshot["store"]
            self._rv = snapshot["rv"]

    # -- watch -------------------------------------------------------------
    def watch(self, kind: str, callback: Callable[[WatchEvent], None]) -> None:
        """Subscribe to events for *kind* ('*' = all kinds).  Existing objects
        are replayed as ADDED (informer cache-sync semantics)."""
        with self._lock:
            self._watchers.setdefault(kind, []).append(callback)
            if kind == "*":
                existing = [o for o in self._store.values()]
            else:
                existing = [o for (k, _, _), o in self._store.items() if k == kind]
            for obj in existing:
                callback(WatchEvent("ADDED", obj.deepcopy()))
