"""k8s_gpu_tpu — a TPU-native accelerator-pool operator & training platform.

A brand-new framework with the capability surface of the reference
(`Andy-ckm/K8S-GPU-`, a documentation-only repo specifying an `AzureVmPool`
Kubernetes operator for GPU-VM pools plus the "GoHai" multi-tenant AI
platform; see /root/repo/SURVEY.md), re-designed TPU-first:

- ``api``        — typed custom-resource models (AzureVmPool parity per
                   reference README.md:83-156; TpuPodSlice, the TPU-native CRD).
- ``controller`` — a homegrown controller runtime: in-memory API server fake,
                   rate-limited work queues, reconciler manager with
                   RequeueAfter semantics (reference README.md:167-236).
- ``cloud``      — cloud backends behind one protocol: FakeAzure (envtest
                   parity), CloudTPU queued-resources + FakeCloudTPU with
                   scripted state transitions and fault injection.
- ``operators``  — the reconcilers (AzureVmPool, TpuPodSlice).
- ``scheduling`` — ICI-topology node labels, slice-correct placement,
                   multislice DCN-aware anti-affinity.
- ``parallel``   — jax.sharding mesh construction over ('dcn','ici') and
                   dp/fsdp/tp/sp logical axes, collectives, ring attention.
- ``models``     — flagship transformer LM + the reference's CNN workload
                   (GPU调度平台搭建.md:557-636 parity).
- ``ops``        — attention kernels (Pallas on TPU, jnp fallback).
- ``train``      — training-job runner: distributed init, train loop,
                   checkpointing.
- ``serve``      — inference engine: KV-cache prefill/decode for the
                   flagship LM (replaces the reference's Ollama delegation,
                   智能风控解决方案.md:196).
- ``platform``   — job-template expansion, instance-type catalog, assets,
                   quota (GPU调度平台搭建.md:512-552, 686-744).
- ``cli``        — GoHai-parity CLI verbs (GPU调度平台搭建.md:447-552).
- ``utils``      — structured logging, metrics registry, clocks.
"""

__version__ = "0.1.0"
