"""OIDC-style token service — the Keycloak role in the reference's SSO stack.

The reference deploys Keycloak with two OIDC clients, ``GoHai-portal`` (web)
and ``GoHai-cli`` (device/auth-code flow), backed by LDAP
(GPU调度平台搭建.md:241-270).  This module implements the same contract
in-process: registered clients, an authorization-code flow, HMAC-SHA256
signed JWT-shaped tokens with expiry, and verification that yields the
identity claims (sub, groups) the RBAC layer authorizes against.

No external crypto deps: tokens are ``b64(header).b64(payload).b64(hmac)``
— structurally a JWT with ``alg: HS256`` — signed with an issuer secret.
"""

from __future__ import annotations

import base64
import hmac
import json
import os
import secrets
from dataclasses import dataclass, field
from hashlib import sha256

from ..utils.clock import Clock, RealClock
from .directory import AuthError, User, UserDirectory

DEFAULT_TTL = 8 * 3600.0  # seconds; a working-day session
CODE_TTL = 120.0  # authorization codes are single-use and short-lived


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


@dataclass
class PendingCode:
    username: str
    client_id: str
    expires: float


@dataclass
class TokenIssuer:
    """Issues and verifies bearer tokens for registered OIDC clients."""

    directory: UserDirectory
    secret: bytes = field(default_factory=lambda: os.urandom(32))
    issuer: str = "tpu-platform"
    clients: set[str] = field(default_factory=lambda: {"tpu-portal", "tpu-cli"})
    _codes: dict[str, PendingCode] = field(default_factory=dict)
    # Injected time source: code/token expiry reads ``clock.wall()``
    # (epoch domain — ``exp``/``iat`` claims stay JWT-conventional), so
    # a FakeClock test can expire a token by advancing fake time
    # instead of sleeping through a TTL.
    clock: Clock = field(default_factory=RealClock)

    # -- auth-code flow ----------------------------------------------------
    def authorize(self, username: str, password: str, client_id: str) -> str:
        """Browser-side half of the code flow: authenticate against the
        directory, return a single-use authorization code."""
        if client_id not in self.clients:
            raise AuthError(f"unknown client {client_id!r}")
        self.directory.authenticate(username, password)
        # Purge abandoned codes so the dict is bounded by the flow rate.
        now = self.clock.wall()
        for stale in [c for c, p in self._codes.items() if now > p.expires]:
            del self._codes[stale]
        code = secrets.token_urlsafe(24)
        self._codes[code] = PendingCode(username, client_id, now + CODE_TTL)
        return code

    def exchange_code(self, code: str, client_id: str) -> str:
        """Token-endpoint half: swap the code for a signed access token."""
        pending = self._codes.pop(code, None)
        if pending is None or pending.client_id != client_id:
            raise AuthError("invalid authorization code")
        if self.clock.wall() > pending.expires:
            raise AuthError("authorization code expired")
        return self.issue(self.directory.get(pending.username), client_id)

    # -- tokens ------------------------------------------------------------
    def issue(self, user: User, client_id: str, ttl: float = DEFAULT_TTL) -> str:
        now = self.clock.wall()
        header = {"alg": "HS256", "typ": "JWT"}
        payload = {
            "iss": self.issuer,
            "aud": client_id,
            "sub": user.username,
            "email": user.email,
            "groups": sorted(user.groups),
            "iat": now,
            "exp": now + ttl,
        }
        signing_input = (
            _b64(json.dumps(header, sort_keys=True).encode())
            + "."
            + _b64(json.dumps(payload, sort_keys=True).encode())
        )
        sig = hmac.new(self.secret, signing_input.encode(), sha256).digest()
        return signing_input + "." + _b64(sig)

    def verify(self, token: str, audience: str | None = None) -> dict:
        """Validate signature + expiry (+ audience when given); return the
        claims dict."""
        try:
            signing_input, _, sig_part = token.rpartition(".")
            expected = hmac.new(self.secret, signing_input.encode(), sha256).digest()
            if not hmac.compare_digest(expected, _unb64(sig_part)):
                raise AuthError("bad signature")
            payload = json.loads(_unb64(signing_input.split(".")[1]))
        except AuthError:
            raise
        except Exception as e:
            raise AuthError(f"malformed token: {e}") from e
        if payload.get("iss") != self.issuer:
            raise AuthError("wrong issuer")
        if self.clock.wall() > float(payload.get("exp", 0)):
            raise AuthError("token expired")
        if audience is not None and payload.get("aud") != audience:
            raise AuthError(
                f"audience mismatch: token for {payload.get('aud')!r}, "
                f"expected {audience!r}"
            )
        return payload
