"""Identity, tenancy, and quota — the reference's Keycloak+LDAP SSO and
Namespace/RBAC/"Space" model (GPU调度平台搭建.md:241-270, 37, 43, 802;
SURVEY §2.3 C14-C15), in-process."""

from .directory import AuthError, User, UserDirectory
from .oidc import TokenIssuer
from .quota import QuotaEnforcer, QuotaReconciler, compute_usage
from .rbac import (
    AuthorizedKube,
    CLUSTER_ADMIN_GROUP,
    Forbidden,
    Identity,
    ROLE_RULES,
    SpaceManager,
)

__all__ = [
    "AuthError",
    "AuthorizedKube",
    "CLUSTER_ADMIN_GROUP",
    "Forbidden",
    "Identity",
    "QuotaEnforcer",
    "QuotaReconciler",
    "ROLE_RULES",
    "SpaceManager",
    "TokenIssuer",
    "User",
    "UserDirectory",
    "compute_usage",
]
