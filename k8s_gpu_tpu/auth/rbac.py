"""Spaces + RBAC — the reference's tenancy pattern made executable.

The reference's model: every tenant "Space" is a Namespace with per-space
RBAC, least-privilege by default (GPU调度平台搭建.md:37, 43).  Here a Space
materializes as Namespace + owner RoleBinding + optional ResourceQuota, and
``AuthorizedKube`` is the API-server admission seam that enforces the
bindings on every verb — the piece the reference delegates to the real
kube-apiserver.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..api.tenancy import Namespace, ResourceQuota, RoleBinding
from ..api.types import CustomResource
from ..controller.kubefake import FakeKube
from .directory import AuthError

READ_VERBS = frozenset({"get", "list", "watch"})
WRITE_VERBS = frozenset({"create", "update", "delete"})

# Least-privilege role table (fixed roles; the reference names the pattern,
# not custom Role objects).  Kind "*" = any kind.
ROLE_RULES: dict[str, dict[str, frozenset[str]]] = {
    "space-viewer": {"*": READ_VERBS},
    "space-user": {
        "*": READ_VERBS,
        "TrainJob": READ_VERBS | WRITE_VERBS,
        "DevEnv": READ_VERBS | WRITE_VERBS,
        "Secret": READ_VERBS | WRITE_VERBS,
    },
    "space-admin": {"*": READ_VERBS | WRITE_VERBS},
}

CLUSTER_ADMIN_GROUP = "platform-admins"


class Forbidden(AuthError):
    pass


@dataclass(frozen=True)
class Identity:
    """Verified identity (from TokenIssuer.verify claims)."""

    username: str
    groups: frozenset[str] = frozenset()

    @classmethod
    def from_claims(cls, claims: dict) -> "Identity":
        return cls(claims["sub"], frozenset(claims.get("groups", ())))

    @property
    def is_cluster_admin(self) -> bool:
        return CLUSTER_ADMIN_GROUP in self.groups


class SpaceManager:
    """Creates and administers Spaces (Namespace + RoleBindings + quota)."""

    def __init__(self, kube: FakeKube):
        self.kube = kube

    def create_space(
        self,
        name: str,
        owner: str,
        quota_hard: dict[str, int] | None = None,
    ) -> Namespace:
        ns = Namespace()
        ns.metadata.name = name
        ns.metadata.namespace = ""
        ns.metadata.labels["space"] = name
        created = self.kube.create(ns)
        self.grant(name, owner, "space-admin")
        if quota_hard:
            rq = ResourceQuota()
            rq.metadata.name = "space-quota"
            rq.metadata.namespace = name
            rq.spec.hard = dict(quota_hard)
            self.kube.create(rq)
        return created

    def grant(self, space: str, subject: str, role: str, group: bool = False) -> None:
        if role not in ROLE_RULES:
            raise ValueError(f"unknown role {role!r}")
        rb = RoleBinding()
        rb.metadata.name = f"{role}-{'g-' if group else ''}{subject}"
        rb.metadata.namespace = space
        rb.role = role
        if group:
            rb.subject_group = subject
        else:
            rb.subject_user = subject
        self.kube.create(rb)

    def spaces_for(self, ident: Identity) -> list[str]:
        out = set()
        for rb in self.kube.list("RoleBinding"):
            if rb.subject_user == ident.username or rb.subject_group in ident.groups:
                out.add(rb.metadata.namespace)
        return sorted(out)

    # -- authorization -----------------------------------------------------
    def allowed(self, ident: Identity, verb: str, kind: str, namespace: str) -> bool:
        if ident.is_cluster_admin:
            return True
        for rb in self.kube.list("RoleBinding", namespace=namespace):
            if not (
                rb.subject_user == ident.username
                or (rb.subject_group and rb.subject_group in ident.groups)
            ):
                continue
            rules = ROLE_RULES.get(rb.role, {})
            # Additive grants, like real RBAC: any matching rule allows.
            if verb in rules.get(kind, ()) or verb in rules.get("*", ()):
                return True
        return False


class AuthorizedKube:
    """A FakeKube facade that enforces RBAC for one verified identity —
    what the CLI/API service hands each request after token verification."""

    def __init__(self, kube: FakeKube, spaces: SpaceManager, ident: Identity):
        self._kube = kube
        self._spaces = spaces
        self.ident = ident

    def _check(self, verb: str, kind: str, namespace: str) -> None:
        if not self._spaces.allowed(self.ident, verb, kind, namespace):
            raise Forbidden(
                f"user {self.ident.username!r} cannot {verb} {kind} "
                f"in namespace {namespace!r}"
            )

    def create(self, obj: CustomResource) -> CustomResource:
        self._check("create", obj.kind, obj.metadata.namespace)
        return self._kube.create(obj)

    def get(self, kind: str, name: str, namespace: str = "default"):
        self._check("get", kind, namespace)
        return self._kube.get(kind, name, namespace)

    def update(self, obj: CustomResource) -> CustomResource:
        self._check("update", obj.kind, obj.metadata.namespace)
        return self._kube.update(obj)

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        self._check("delete", kind, namespace)
        self._kube.delete(kind, name, namespace)

    def list(self, kind: str, namespace: str | None = None, **kw):
        if namespace is None:
            # Cross-namespace list returns only namespaces the identity can
            # read (the UI's "my spaces" view).
            out = []
            for obj in self._kube.list(kind, **kw):
                if self._spaces.allowed(
                    self.ident, "list", kind, obj.metadata.namespace
                ):
                    out.append(obj)
            return out
        self._check("list", kind, namespace)
        return self._kube.list(kind, namespace=namespace, **kw)
