"""ResourceQuota enforcement + alerting (reference GPU调度平台搭建.md:802:
"ResourceQuota/LimitRange ... quota usage alerting"; SURVEY §2.3 C15).

Two halves, mirroring the real apiserver/controller split:

- ``QuotaEnforcer`` — synchronous admission: rejects a create that would
  push a namespace over any ``hard`` limit (TPU chips or object counts),
  and applies LimitRange defaulting/ceiling to pod chip requests.
  Registered into ``FakeKube.admission``.
- ``QuotaReconciler`` — asynchronous accounting: recomputes
  ``status.used``, and raises the ``AlertActive`` condition + a Warning
  Event when usage crosses ``spec.alertThreshold`` of a hard limit.
"""

from __future__ import annotations

from ..api.tenancy import LimitRange, ResourceQuota
from ..api.types import CustomResource, ValidationError, set_condition
from ..controller.events import EventRecorder
from ..controller.kubefake import Conflict, FakeKube, NotFound
from ..controller.manager import Reconciler, Request, Result

TPU_RESOURCE = "google.com/tpu"
RESYNC = 5.0

# Kinds metered by count/<plural> quota keys.
_COUNTED = {
    "Pod": "count/pods",
    "TrainJob": "count/trainjobs",
    "TpuPodSlice": "count/tpupodslices",
    "DevEnv": "count/devenvs",
}

_LIVE_POD_PHASES = ("Pending", "Running")


def compute_usage(kube: FakeKube, namespace: str) -> dict[str, int]:
    used: dict[str, int] = {}
    for kind, key in _COUNTED.items():
        objs = kube.list(kind, namespace=namespace)
        if kind == "Pod":
            objs = [p for p in objs if p.phase in _LIVE_POD_PHASES]
            used[TPU_RESOURCE] = sum(p.requests.get(TPU_RESOURCE, 0) for p in objs)
        used[key] = len(objs)
    return used


class QuotaEnforcer:
    """Admission callback: ``kube.admission.append(QuotaEnforcer(kube))``."""

    def __init__(self, kube: FakeKube):
        self.kube = kube

    def __call__(self, op: str, obj: CustomResource) -> None:
        ns = obj.metadata.namespace
        if obj.kind == "Pod":
            self._apply_limit_range(ns, obj)
        quotas = self.kube.list("ResourceQuota", namespace=ns)
        if not quotas:
            return
        # Project the usage the write would add on top of current usage.
        delta: dict[str, int] = {}
        if op == "create":
            if obj.kind in _COUNTED:
                delta[_COUNTED[obj.kind]] = 1
            if obj.kind == "Pod" and obj.phase in _LIVE_POD_PHASES:
                chips = obj.requests.get(TPU_RESOURCE, 0)
                if chips > 0:  # a zero delta must not gate on the chip limit
                    delta[TPU_RESOURCE] = chips
        elif obj.kind == "Pod":
            # Updates can't change counts, but can grow a pod's chip request
            # (or resurrect a finished pod); meter the increase over the
            # stored copy, which compute_usage already counted.
            cur = self.kube.try_get("Pod", obj.metadata.name, ns)
            old = (
                cur.requests.get(TPU_RESOURCE, 0)
                if cur is not None and cur.phase in _LIVE_POD_PHASES
                else 0
            )
            new = (
                obj.requests.get(TPU_RESOURCE, 0)
                if obj.phase in _LIVE_POD_PHASES
                else 0
            )
            if new > old:
                delta[TPU_RESOURCE] = new - old
            else:
                return
        else:
            return
        # Only writes that grow a tracked resource are gated — untracked
        # kinds (Events, Secrets, ...) must keep working even when a
        # namespace is already over a freshly-lowered hard limit.
        if not delta:
            return
        used = compute_usage(self.kube, ns)
        for rq in quotas:
            # Gate only the resources this write grows (apiserver semantics:
            # being over one limit doesn't block writes to other resources).
            for key, hard in rq.spec.hard.items():
                if key not in delta:
                    continue
                projected = used.get(key, 0) + delta[key]
                if projected > hard:
                    raise ValidationError(
                        f"exceeded quota {rq.metadata.name!r} in {ns!r}: "
                        f"{key} {projected} > hard {hard}"
                    )

    def _apply_limit_range(self, ns: str, pod) -> None:
        for lr in self.kube.list("LimitRange", namespace=ns):
            assert isinstance(lr, LimitRange)
            req = pod.requests.get(TPU_RESOURCE, 0)
            if req == 0 and lr.spec.default_tpu:
                pod.requests[TPU_RESOURCE] = lr.spec.default_tpu
            elif lr.spec.max_tpu and req > lr.spec.max_tpu:
                raise ValidationError(
                    f"pod chip request {req} exceeds LimitRange max "
                    f"{lr.spec.max_tpu} in {ns!r}"
                )


class QuotaReconciler(Reconciler):
    """Keeps ``status.used`` current and fires threshold alerts."""

    def __init__(self, kube: FakeKube, resync: float = RESYNC):
        self.kube = kube
        self.recorder = EventRecorder(kube, "quota-controller")
        self.resync = resync

    def reconcile(self, req: Request) -> Result:
        rq = self.kube.try_get("ResourceQuota", req.name, req.namespace)
        if rq is None or not isinstance(rq, ResourceQuota):
            return Result()
        used = compute_usage(self.kube, req.namespace)
        rq.status.hard = dict(rq.spec.hard)
        rq.status.used = {k: used.get(k, 0) for k in rq.spec.hard}
        hot = [
            f"{k}={rq.status.used[k]}/{h}"
            for k, h in rq.spec.hard.items()
            if h > 0 and rq.status.used[k] >= rq.spec.alert_threshold * h
        ]
        was_alerting = any(
            c.type == "AlertActive" and c.status == "True"
            for c in rq.status.conditions
        )
        if hot:
            set_condition(
                rq.status.conditions, "AlertActive", "True", "QuotaNearLimit",
                ", ".join(hot), observed_generation=rq.metadata.generation,
            )
        else:
            set_condition(
                rq.status.conditions, "AlertActive", "False", "WithinLimits", "",
                observed_generation=rq.metadata.generation,
            )
        try:
            self.kube.update_status(rq)
        except (Conflict, NotFound):
            return Result(requeue=True)
        if hot and not was_alerting:
            self.recorder.event(
                rq, "Warning", "QuotaNearLimit",
                f"usage at/above {rq.spec.alert_threshold:.0%}: {', '.join(hot)}",
            )
        return Result(requeue_after=self.resync)
