"""User directory — the LDAP/AD role in the reference's SSO stack.

The reference federates Keycloak to an enterprise LDAP/AD for accounts and
group sync (GPU调度平台搭建.md:241-266).  Here the directory is a small
salted-hash store with group membership — the same contract (authenticate,
look up groups) without the wire protocol.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from dataclasses import dataclass, field


class AuthError(Exception):
    pass


@dataclass
class User:
    username: str
    email: str = ""
    groups: list[str] = field(default_factory=list)
    password_salt: bytes = b""
    password_hash: bytes = b""


def _hash(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 10_000)


class UserDirectory:
    """In-process account store with LDAP-like semantics: bind (authenticate)
    and search (get user + groups)."""

    def __init__(self):
        self._users: dict[str, User] = {}

    def add_user(
        self,
        username: str,
        password: str,
        groups: list[str] | None = None,
        email: str = "",
    ) -> User:
        salt = os.urandom(16)
        user = User(
            username=username,
            email=email or f"{username}@example.com",
            groups=list(groups or []),
            password_salt=salt,
            password_hash=_hash(password, salt),
        )
        self._users[username] = user
        return user

    def authenticate(self, username: str, password: str) -> User:
        """The LDAP "bind" — constant-time compare on a salted PBKDF2 hash."""
        user = self._users.get(username)
        if user is None:
            raise AuthError(f"unknown user {username!r}")
        if not hmac.compare_digest(_hash(password, user.password_salt),
                                   user.password_hash):
            raise AuthError("invalid credentials")
        return user

    def get(self, username: str) -> User:
        user = self._users.get(username)
        if user is None:
            raise AuthError(f"unknown user {username!r}")
        return user

    def add_to_group(self, username: str, group: str) -> None:
        user = self.get(username)
        if group not in user.groups:
            user.groups.append(group)

    def users(self) -> list[User]:
        return sorted(self._users.values(), key=lambda u: u.username)
