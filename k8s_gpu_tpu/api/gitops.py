"""GitOps Application kind — the ArgoCD-style pull-based deployment
option the reference lists as the alternative to its push-mode GitLab-CI
flow (GPU调度平台搭建.md:792-794: "可选：改造成 ArgoCD 拉取式同步").

An Application points at a repository asset (the platform's git-ish
store, the same one the CI pipeline builds from) and a manifest
directory inside it; the GitOps reconciler (operators/gitops.py) keeps
the cluster converged to those manifests — apply on drift, prune on
removal — and records the synced revision in status.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .types import Condition, CustomResource, ValidationError


@dataclass
class ApplicationSpec:
    space: str = "default"          # asset space holding the repo
    repo: str = ""                  # repository asset id
    path: str = "manifests"         # manifest dir inside the repo
    target_namespace: str = "default"
    # auto_sync False = detect drift only (status OutOfSync), never
    # write — ArgoCD's manual-sync mode; sync happens via
    # GitOpsReconciler.sync_now or by flipping the flag.
    auto_sync: bool = True
    prune: bool = True              # delete managed objects not in git


@dataclass
class ApplicationStatus:
    phase: str = ""                 # Synced | OutOfSync | Error
    revision: str = ""              # repo asset version last examined
    synced_revision: str = ""       # revision last APPLIED
    applied: int = 0
    pruned: int = 0
    drifted: list = field(default_factory=list)  # ["Kind/name", ...]
    message: str = ""
    conditions: list[Condition] = field(default_factory=list)


@dataclass
class Application(CustomResource):
    kind: str = "Application"
    api_version: str = "gitops.k8sgpu.dev/v1alpha1"
    spec: ApplicationSpec = field(default_factory=ApplicationSpec)
    status: ApplicationStatus = field(default_factory=ApplicationStatus)

    def validate(self) -> None:
        super().validate()
        if not self.spec.repo:
            raise ValidationError("spec.repo is required")
        if ".." in self.spec.path or self.spec.path.startswith("/"):
            raise ValidationError(
                f"spec.path {self.spec.path!r} must be a relative path "
                "inside the repo"
            )
