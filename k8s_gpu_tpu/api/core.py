"""Built-in (non-custom) kinds the controllers interact with: Secret, Node,
Event, Pod — the minimal core-API subset the reference operator touches
(credential Secret, reference README.md:107-109, 244-252; Events README.md:311;
nodes joining with device-plugin resources GPU调度平台搭建.md:128-138)."""

from __future__ import annotations

from dataclasses import dataclass, field

from .types import CustomResource, ObjectMeta, Condition


@dataclass
class Secret(CustomResource):
    kind: str = "Secret"
    api_version: str = "v1"
    data: dict[str, str] = field(default_factory=dict)


@dataclass
class Node(CustomResource):
    """A cluster node.  TPU nodes carry the device-plugin extended resource
    ``google.com/tpu`` (the libtpu analogue of ``nvidia.com/gpu``,
    GPU调度平台搭建.md:128-138) and ICI-topology labels used for
    slice-correct placement (BASELINE.json config 3)."""

    kind: str = "Node"
    api_version: str = "v1"
    capacity: dict[str, int] = field(default_factory=dict)
    allocatable: dict[str, int] = field(default_factory=dict)
    conditions: list[Condition] = field(default_factory=list)
    ready: bool = False


@dataclass
class Event(CustomResource):
    """Kubernetes Event parity (reference README.md:311: emit Events on VM
    create/delete so ``kubectl describe`` shows operator activity)."""

    kind: str = "Event"
    api_version: str = "v1"
    involved_kind: str = ""
    involved_name: str = ""
    involved_namespace: str = ""
    type: str = "Normal"  # Normal | Warning
    reason: str = ""
    message: str = ""
    count: int = 1


@dataclass
class Pod(CustomResource):
    """Minimal pod model: enough for the scheduler/placement layer — resource
    requests, node selector/affinity, assigned node, phase."""

    kind: str = "Pod"
    api_version: str = "v1"
    image: str = ""
    command: str = ""
    env: dict[str, str] = field(default_factory=dict)
    requests: dict[str, int] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    node_name: str = ""
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    # Pod-group id for gang semantics / multislice spread (SURVEY §2.7).
    group: str = ""
    # mountPath → volume source ref ("pvc:<name>" | "secret:<name>"), the
    # minimal volumes model the devenv pod template needs
    # (GPU调度平台搭建.md:341-368: workspace PVC + SSH-key Secret mounts).
    mounts: dict[str, str] = field(default_factory=dict)


@dataclass
class DeploymentSpec:
    image: str = ""
    replicas: int = 1
    command: str = ""
    env: dict[str, str] = field(default_factory=dict)


@dataclass
class DeploymentStatus:
    ready_replicas: int = 0


@dataclass
class Deployment(CustomResource):
    """Minimal Deployment: what the platform Helm chart deploys (GoHai-api /
    GoHai-controller / devenv-controller, GPU调度平台搭建.md:853-865).  A
    small controller materializes ``spec.replicas`` Pods and mirrors
    readiness.  Spec/status are real subobjects so spec writes bump
    generation and pass the manager's generation-changed predicate (a flat
    kind would never re-trigger its controller on upgrade)."""

    kind: str = "Deployment"
    api_version: str = "apps/v1"
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)

    def validate(self) -> None:
        super().validate()
        if self.spec.replicas < 0:
            from .types import ValidationError

            raise ValidationError("replicas must be >= 0")


@dataclass
class PersistentVolumeClaim(CustomResource):
    """RWX workspace claim (reference C12: 200Gi ReadWriteMany /workspace,
    GPU调度平台搭建.md:181-224).

    Two provisioning modes:
    - ``storage_class == ""``: statically Bound on creation (the round-1
      behavior — identity + persistence semantics are what matter to
      devenv/GC flows);
    - ``storage_class`` set: dynamically provisioned by the
      StorageProvisioner against a replicated pool (the Rook-Ceph
      alternative, C13, GPU调度平台搭建.md:226-237) — phase runs
      Pending → Bound with ``volume_name`` pointing at the PV."""

    kind: str = "PersistentVolumeClaim"
    api_version: str = "v1"
    access_modes: list[str] = field(default_factory=lambda: ["ReadWriteMany"])
    capacity: str = "200Gi"
    phase: str = "Bound"
    storage_class: str = ""
    volume_name: str = ""


@dataclass
class PersistentVolume(CustomResource):
    """A provisioned volume backing one claim (the Ceph RBD image /
    CephFS subvolume analogue).  Cluster-scoped in k8s; namespaced here
    like everything else in the in-memory API server."""

    kind: str = "PersistentVolume"
    api_version: str = "v1"
    capacity: str = ""
    storage_class: str = ""
    access_modes: list[str] = field(default_factory=list)
    reclaim_policy: str = "Delete"  # Delete | Retain
    phase: str = "Available"        # Available | Bound | Released
    claim_namespace: str = ""
    claim_name: str = ""
    pool: str = ""                  # backing pool name
    replicas: int = 1               # replication factor charged to the pool
