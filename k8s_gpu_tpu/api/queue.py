"""SchedulingQueue CRD — the Volcano ``queue`` role (reference
GPU调度平台搭建.md:273-287: Volcano's batch scheduler with per-tenant queues;
the training Job template names ``queue: default`` at :650).

On TPU the *gang* half of Volcano is structural (a slice is an atomic
capacity unit, SURVEY §2.7), so what remains queue-shaped is *admission
ordering and capacity sharing*: jobs reference a queue; within a queue
admission is priority-then-FIFO; a queue may cap the TPU chips its running
jobs hold (the ResourceQuota-like share Volcano queues carry via
``spec.capability``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .types import CustomResource, ValidationError

DEFAULT_QUEUE = "default"


@dataclass
class SchedulingQueueSpec:
    # Max TPU chips running jobs in this queue may hold; 0 = uncapped.
    cap_tpu: int = 0
    # Relative weight, recorded for operators/dashboards (cross-queue
    # arbitration is by contention on cluster capacity, not enforced shares).
    weight: int = 1
    # A closed queue admits no new jobs (existing ones keep running).
    closed: bool = False


@dataclass
class SchedulingQueueStatus:
    pending: int = 0
    running: int = 0
    completed: int = 0
    chips_in_use: int = 0


@dataclass
class SchedulingQueue(CustomResource):
    kind: str = "SchedulingQueue"
    api_version: str = "scheduling.tpu.k8sgpu.dev/v1alpha1"
    spec: SchedulingQueueSpec = field(default_factory=SchedulingQueueSpec)
    status: SchedulingQueueStatus = field(default_factory=SchedulingQueueStatus)

    def validate(self) -> None:
        super().validate()
        if self.metadata.namespace != "":
            raise ValidationError(
                "SchedulingQueue is cluster-scoped (namespace must be '')"
            )
        if self.spec.cap_tpu < 0:
            raise ValidationError("capTpu must be >= 0")
        if self.spec.weight < 1:
            raise ValidationError("weight must be >= 1")
