"""DevEnv CRD — per-user persistent development environments (C21-C24).

The reference's devenv-controller materializes a pod ``devenv-<username>``
from a documented template (GPU调度平台搭建.md:341-372): micromamba base
image with sshd as PID 1 (:314-339), the shared RWX workspace PVC mounted
at ``/workspace``, and the user's SSH public key injected as Secret
``user-ssh-<username>`` mounted into ``/root/.ssh`` (:369-372, 417).
Access is SSH on a dedicated endpoint (:418) with VSCode Remote-SSH on
top (:419); conda environments persist across pod restarts because
micromamba's dirs are redirected into the workspace (:374-406).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .types import Condition, CustomResource, ValidationError

DEFAULT_IMAGE = "registry.local/tpu-platform/mamba-base:latest"
WORKSPACE_PVC = "workspace-pvc"
SSH_PORT = 2022


@dataclass
class DevEnvSpec:
    username: str = ""
    image: str = DEFAULT_IMAGE
    ssh_public_key: str = ""
    workspace_pvc: str = WORKSPACE_PVC
    # Chip-less by default: devenvs are CPU boxes next to the accelerators
    # (the reference's devenv template requests no GPU, :341-368); set > 0
    # for a debug env with attached chips.
    tpu_chips: int = 0


@dataclass
class DevEnvStatus:
    phase: str = "Pending"  # Pending | Ready | Terminating
    pod_name: str = ""
    ssh_endpoint: str = ""
    message: str = ""
    conditions: list[Condition] = field(default_factory=list)


@dataclass
class DevEnv(CustomResource):
    kind: str = "DevEnv"
    api_version: str = "tpu.k8sgpu.dev/v1alpha1"
    spec: DevEnvSpec = field(default_factory=DevEnvSpec)
    status: DevEnvStatus = field(default_factory=DevEnvStatus)

    def validate(self) -> None:
        super().validate()
        if not self.spec.username:
            raise ValidationError("spec.username is required")
        if not self.spec.ssh_public_key:
            raise ValidationError("spec.sshPublicKey is required")
        if self.spec.tpu_chips < 0:
            raise ValidationError("spec.tpuChips must be >= 0")
