"""AzureVmPool CRD — capability parity with the reference's core artifact.

Field-for-field parity with the reference's Go types (reference
README.md:83-156: spec 92-110, image 113-118, status 121-128, printer columns
130-133).  Group/version kept identical (``compute.my.domain/v1alpha1``,
reference README.md:76) so BASELINE config 1 ("AzureVmPool replicas=2
reconcile under envtest") is checked against the same schema.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .types import CustomResource, Condition, ValidationError


@dataclass
class ImageReference:
    """reference README.md:113-118."""

    publisher: str = "Canonical"
    offer: str = "0001-com-ubuntu-server-jammy"
    sku: str = "22_04-lts-gen2"
    version: str = "latest"


@dataclass
class AzureVmPoolSpec:
    """reference README.md:92-110."""

    replicas: int = 0
    resource_group_name: str = ""
    location: str = ""
    vm_size: str = ""
    vnet_name: str = ""
    subnet_name: str = ""
    image_reference: ImageReference = field(default_factory=ImageReference)
    # Name of the K8s Secret holding AZURE_CLIENT_ID/SECRET/TENANT_ID/
    # SUBSCRIPTION_ID (reference README.md:107-109, 244-252).
    azure_credential_secret: str = ""


@dataclass
class VmInfo:
    name: str = ""
    provisioning_state: str = ""


@dataclass
class AzureVmPoolStatus:
    """reference README.md:121-128."""

    ready_replicas: int = 0
    vms: list[VmInfo] = field(default_factory=list)
    conditions: list[Condition] = field(default_factory=list)


@dataclass
class AzureVmPool(CustomResource):
    kind: str = "AzureVmPool"
    api_version: str = "compute.my.domain/v1alpha1"
    spec: AzureVmPoolSpec = field(default_factory=AzureVmPoolSpec)
    status: AzureVmPoolStatus = field(default_factory=AzureVmPoolStatus)

    def validate(self) -> None:
        super().validate()
        # kubebuilder:validation:Minimum=0 (reference README.md:94).
        if self.spec.replicas < 0:
            raise ValidationError("spec.replicas must be >= 0")

    # Printer columns Desired/Ready (reference README.md:132-133).
    @property
    def printer_columns(self) -> dict[str, int]:
        return {"Desired": self.spec.replicas, "Ready": self.status.ready_replicas}
