"""YAML/dict manifests for every API kind — the ``kubectl apply -f`` wire
format (the reference's user surface: sample CRs applied as YAML, reference
README.md:265-289; the BASELINE north star is literally ``kubectl apply -f
tpupodslice.yaml``).

One generic dataclass codec: fields serialize camelCased (k8s convention),
nested dataclasses and lists of dataclasses recurse via type hints, and
deserialization rejects unknown fields (kubebuilder strict-schema
behavior) so a typo'd manifest fails loudly instead of silently dropping
the field.
"""

from __future__ import annotations

import dataclasses
import re
import types as _types
import typing

import yaml

from .types import CustomResource, ValidationError

_KIND_REGISTRY: dict[str, type] = {}


def register_kind(cls: type) -> type:
    _KIND_REGISTRY[cls().kind if dataclasses.is_dataclass(cls) else cls.kind] = cls
    return cls


def known_kinds() -> list[str]:
    _ensure_registry()
    return sorted(_KIND_REGISTRY)


def _ensure_registry() -> None:
    if _KIND_REGISTRY:
        return
    from . import (
        core,
        azurevmpool,
        devenv,
        gitops,
        inferenceservice,
        queue,
        tenancy,
        tpupodslice,
        trainjob,
    )

    for mod in (core, azurevmpool, devenv, gitops, inferenceservice,
                queue, tenancy, tpupodslice, trainjob):
        for name in dir(mod):
            obj = getattr(mod, name)
            if (
                isinstance(obj, type)
                and dataclasses.is_dataclass(obj)
                and issubclass(obj, CustomResource)
                and obj is not CustomResource
            ):
                _KIND_REGISTRY[obj().kind] = obj


def _camel(s: str) -> str:
    head, *rest = s.split("_")
    return head + "".join(p.title() for p in rest)


def _snake(s: str) -> str:
    return re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", s).lower()


def _encode(value):
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        out = {}
        for f in dataclasses.fields(value):
            v = getattr(value, f.name)
            out[_camel(f.name)] = _encode(v)
        return out
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    if isinstance(value, dict):
        return {k: _encode(v) for k, v in value.items()}
    return value


def _decode_into(cls: type, data: dict, path: str):
    hints = typing.get_type_hints(cls)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for key, raw in data.items():
        name = _snake(key)
        if name not in fields:
            raise ValidationError(f"unknown field {path}.{key}")
        kwargs[name] = _decode_value(hints.get(name), raw, f"{path}.{key}")
    return cls(**kwargs)


def _decode_value(hint, raw, path: str):
    origin = typing.get_origin(hint)
    if origin in (typing.Union, _types.UnionType):
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if len(args) == 1:
            return _decode_value(args[0], raw, path)
        # Multi-arm union: try each arm, not just the first — a
        # non-Optional union's later arms must remain reachable.
        last: ValidationError | None = None
        for arm in args:
            try:
                return _decode_value(arm, raw, path)
            except ValidationError as e:
                last = e
        raise last or ValidationError(f"{path}: no union arm matched")
    if hint is not None and dataclasses.is_dataclass(hint):
        if not isinstance(raw, dict):
            raise ValidationError(f"{path} must be a mapping")
        return _decode_into(hint, raw, path)
    if origin in (list, tuple):
        if not isinstance(raw, list):
            raise ValidationError(f"{path} must be a list")
        args = typing.get_args(hint)
        if origin is tuple and len(args) > 1 and args[-1] is not Ellipsis:
            # Heterogeneous tuple[A, B, ...]: per-position element hints.
            if len(raw) != len(args):
                raise ValidationError(
                    f"{path} must have {len(args)} items, got {len(raw)}"
                )
            return tuple(
                _decode_value(a, v, f"{path}[{i}]")
                for i, (a, v) in enumerate(zip(args, raw))
            )
        elem = args[0] if args else None
        vals = [
            _decode_value(elem, v, f"{path}[{i}]") for i, v in enumerate(raw)
        ]
        # Fields typed tuple[...] must round-trip as tuples, not lists.
        return tuple(vals) if origin is tuple else vals
    return raw


# -- public API ------------------------------------------------------------

def to_manifest(obj: CustomResource) -> dict:
    """CR -> kubectl-shaped dict: apiVersion/kind/metadata/spec[/status]."""
    out = {"apiVersion": obj.api_version, "kind": obj.kind}
    meta = {"name": obj.metadata.name, "namespace": obj.metadata.namespace}
    if obj.metadata.labels:
        meta["labels"] = dict(obj.metadata.labels)
    if obj.metadata.annotations:
        meta["annotations"] = dict(obj.metadata.annotations)
    out["metadata"] = meta
    for f in dataclasses.fields(obj):
        if f.name in ("metadata", "api_version", "kind"):
            continue
        out[_camel(f.name)] = _encode(getattr(obj, f.name))
    return out


def to_yaml(obj: CustomResource) -> str:
    return yaml.safe_dump(to_manifest(obj), sort_keys=False)


def from_manifest(doc: dict) -> CustomResource:
    _ensure_registry()
    if not isinstance(doc, dict):
        raise ValidationError("manifest must be a mapping")
    kind = doc.get("kind")
    cls = _KIND_REGISTRY.get(kind)
    if cls is None:
        raise ValidationError(
            f"unknown kind {kind!r}; known: {sorted(_KIND_REGISTRY)}"
        )
    obj = cls()
    meta = doc.get("metadata") or {}
    obj.metadata.name = meta.get("name", "")
    obj.metadata.namespace = meta.get("namespace", "default")
    obj.metadata.labels = dict(meta.get("labels") or {})
    obj.metadata.annotations = dict(meta.get("annotations") or {})
    hints = typing.get_type_hints(cls)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    for key, raw in doc.items():
        if key in ("apiVersion", "kind", "metadata", "status"):
            continue  # status is controller-owned; ignore on apply
        name = _snake(key)
        if name not in fields:
            raise ValidationError(f"unknown field .{key} for kind {kind}")
        setattr(obj, name, _decode_value(hints.get(name), raw, f".{key}"))
    return obj


def load_manifests(text: str) -> list[CustomResource]:
    """Parse a (possibly multi-document) YAML stream of manifests."""
    out = []
    for doc in yaml.safe_load_all(text):
        if doc is None:
            continue
        out.append(from_manifest(doc))
    return out
