"""TrainJob CRD — the training-job unit (the reference's Volcano Job role).

The reference submits training as a Volcano ``Job`` with gang semantics
(``minAvailable``, GPU调度平台搭建.md:638-675) expanded from a user template
(:512-535).  On TPU the gang is the slice (SURVEY §2.7), so a TrainJob
declares the *instance type* (→ accelerator type → worker count) and the
reconciler places one worker per slice host atomically via
scheduling.place_gang.  ``workload`` names a registered in-process JAX
workload (train/registry.py) — the analogue of the reference's
image+command pair, but compiled and run by this framework rather than a
container runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .types import Condition, CustomResource, ValidationError


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclass
class AssetRef:
    """repository/dataset/model references with pinning — C27's
    {space,id,hash/versionId} triples (GPU调度平台搭建.md:521-533)."""

    space: str = ""
    id: str = ""
    version: str = ""  # ""= latest (hash ""==latest semantics, :525)


@dataclass
class TrainJobSpec:
    title: str = ""
    description: str = ""
    image: str = ""
    command: str = ""
    env: list[EnvVar] = field(default_factory=list)
    repository: list[AssetRef] = field(default_factory=list)
    dataset: list[AssetRef] = field(default_factory=list)
    model: list[AssetRef] = field(default_factory=list)
    # Scheduling queue (Volcano `queue:` parity, GPU调度平台搭建.md:650) and
    # priority within it (higher admits first; FIFO among equals).
    queue: str = "default"
    priority: int = 0
    # single (one slice) | multislice (slice_count slices).
    mode: str = "single"
    instance_type: str = "tpu-v5e-8"
    slice_count: int = 1
    # Resolved by template expansion (server-side defaulting).
    accelerator_type: str = ""
    num_workers: int = 0
    # Sub-host job (the reference's 1gpu instance-type semantics,
    # GPU调度平台搭建.md:535): > 0 = run ONE worker on a chip carve-out
    # (scheduling/sharing.py) instead of a whole-slice gang.
    shared_chips: int = 0
    # In-process workload name (train/registry.py); "" = external command.
    workload: str = ""
    workload_args: dict = field(default_factory=dict)
    # Max seconds in Pending-for-capacity before Failed (0 = wait forever).
    queue_timeout_s: float = 0.0
    # Elastic recovery (SURVEY §5.3-5.4; restartPolicy parity with
    # GPU调度平台搭建.md:668): OnFailure re-places the gang and re-runs the
    # workload, which resumes from its latest checkpoint.  Never = one shot.
    restart_policy: str = "Never"
    max_restarts: int = 3
    # Periodic checkpoint cadence for checkpoint-aware workloads (0 = off);
    # dir "" resolves to a stable per-job path so restarts find it.
    checkpoint_interval_steps: int = 0
    checkpoint_dir: str = ""


@dataclass
class TrainJobStatus:
    phase: str = "Pending"  # Pending|Placing|Running|Succeeded|Failed
    message: str = ""
    # pod/worker name → node name (gang placement result).
    placements: dict[str, str] = field(default_factory=dict)
    start_time: float = 0.0
    completion_time: float = 0.0
    # Elastic-recovery bookkeeping: restart count, last step the workload
    # reported, last checkpointed step, and the step resumed from (0 = a
    # fresh start).
    restarts: int = 0
    progress_step: int = 0
    checkpoint_step: int = 0
    resumed_from_step: int = 0
    conditions: list[Condition] = field(default_factory=list)
    logs: list[str] = field(default_factory=list)
    result: dict = field(default_factory=dict)


@dataclass
class TrainJob(CustomResource):
    kind: str = "TrainJob"
    api_version: str = "tpu.k8sgpu.dev/v1alpha1"
    spec: TrainJobSpec = field(default_factory=TrainJobSpec)
    status: TrainJobStatus = field(default_factory=TrainJobStatus)

    def validate(self) -> None:
        super().validate()
        if self.spec.mode not in ("single", "multislice"):
            raise ValidationError(f"mode must be single|multislice, got {self.spec.mode!r}")
        if self.spec.slice_count < 1:
            raise ValidationError("sliceCount must be >= 1")
        if self.spec.mode == "single" and self.spec.slice_count != 1:
            raise ValidationError("mode=single requires sliceCount=1")
        if self.spec.restart_policy not in ("Never", "OnFailure"):
            raise ValidationError(
                f"restartPolicy must be Never|OnFailure, got "
                f"{self.spec.restart_policy!r}"
            )
        if self.spec.max_restarts < 0:
            raise ValidationError("maxRestarts must be >= 0")
        if self.spec.checkpoint_interval_steps < 0:
            raise ValidationError("checkpointIntervalSteps must be >= 0")
