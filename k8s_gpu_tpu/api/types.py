"""Core object-model types for the homegrown controller runtime.

Mirrors the apimachinery surface the reference's Go operator relies on
(ObjectMeta, Conditions, status subresource; reference README.md:83-156) as
plain dataclasses.  Objects are deep-copied at the API-server boundary, so
mutating a fetched object never mutates the stored copy — the same
"serialize through the wire" discipline a real cluster enforces.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Any


class ValidationError(Exception):
    """Rejected by schema validation (kubebuilder-marker parity,
    e.g. ``Minimum=0`` on replicas, reference README.md:94)."""


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    creation_timestamp: float = 0.0
    deletion_timestamp: float | None = None
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)


@dataclass
class Condition:
    """metav1.Condition parity (reference README.md:127, 310: rich Conditions
    such as Provisioning/Ready/Deleting/Failed are a hardening requirement)."""

    type: str
    status: str  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0
    observed_generation: int = 0


def set_condition(
    conditions: list[Condition],
    ctype: str,
    status: str,
    reason: str = "",
    message: str = "",
    now: float | None = None,
    observed_generation: int = 0,
) -> None:
    """Upsert a condition; transition time only changes when status flips."""
    ts = time.time() if now is None else now
    for c in conditions:
        if c.type == ctype:
            if c.status != status:
                c.last_transition_time = ts
            c.status = status
            c.reason = reason
            c.message = message
            c.observed_generation = observed_generation
            return
    conditions.append(
        Condition(ctype, status, reason, message, ts, observed_generation)
    )


def get_condition(conditions: list[Condition], ctype: str) -> Condition | None:
    for c in conditions:
        if c.type == ctype:
            return c
    return None


@dataclass
class CustomResource:
    """Base for all API objects stored in the (fake) API server."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    # Subclasses override.
    api_version: str = "v1"
    kind: str = "CustomResource"

    def validate(self) -> None:
        """Schema validation hook; raise ValidationError to reject a write."""
        if not self.metadata.name:
            raise ValidationError("metadata.name is required")

    def deepcopy(self):
        return copy.deepcopy(self)

    @property
    def key(self) -> tuple[str, str]:
        return (self.metadata.namespace, self.metadata.name)
