"""Tenancy kinds — the multi-tenant model the reference specifies in prose:
"Namespace + RBAC per Space, least-privilege, ResourceQuota/LimitRange with
quota alerting" (GPU调度平台搭建.md:37, 43, 802; SURVEY §2.3 C15).

A *Space* is the user-facing tenancy unit; it materializes as a Namespace
plus RoleBindings plus an optional ResourceQuota — exactly the mapping the
reference describes, with TPU chips (``google.com/tpu``) as the metered
accelerator resource instead of ``nvidia.com/gpu``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .types import Condition, CustomResource, ValidationError


@dataclass
class Namespace(CustomResource):
    """Cluster-scoped; stored under namespace "" by convention."""

    kind: str = "Namespace"
    api_version: str = "v1"
    phase: str = "Active"  # Active | Terminating

    def validate(self) -> None:
        super().validate()
        if self.metadata.namespace != "":
            raise ValidationError("Namespace is cluster-scoped (namespace must be '')")


@dataclass
class ResourceQuotaStatus:
    hard: dict[str, int] = field(default_factory=dict)
    used: dict[str, int] = field(default_factory=dict)
    conditions: list[Condition] = field(default_factory=list)


@dataclass
class ResourceQuotaSpec:
    """``hard`` keys: extended resources (``google.com/tpu``) and object
    counts (``count/pods``, ``count/trainjobs``, ``count/tpupodslices``)."""

    hard: dict[str, int] = field(default_factory=dict)
    # Fraction of any hard limit at which the alert condition fires
    # (the reference's "quota usage alert threshold", GPU调度平台搭建.md:802).
    alert_threshold: float = 0.9


@dataclass
class ResourceQuota(CustomResource):
    kind: str = "ResourceQuota"
    api_version: str = "v1"
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)

    def validate(self) -> None:
        super().validate()
        for k, v in self.spec.hard.items():
            if v < 0:
                raise ValidationError(f"hard[{k}] must be >= 0")
        if not 0 < self.spec.alert_threshold <= 1:
            raise ValidationError("alertThreshold must be in (0, 1]")


@dataclass
class LimitRangeSpec:
    """Per-pod defaulting/ceiling for the TPU chip request."""

    default_tpu: int = 0  # applied when a pod requests no chips
    max_tpu: int = 0  # 0 = unlimited


@dataclass
class LimitRange(CustomResource):
    kind: str = "LimitRange"
    api_version: str = "v1"
    spec: LimitRangeSpec = field(default_factory=LimitRangeSpec)


@dataclass
class RoleBinding(CustomResource):
    """Binds a user or group to a named role within the binding's namespace.
    Roles are the fixed least-privilege set in auth/rbac.py (the reference
    names no custom Role objects, only the pattern; GPU调度平台搭建.md:43)."""

    kind: str = "RoleBinding"
    api_version: str = "rbac.authorization.k8s.io/v1"
    role: str = ""  # space-admin | space-user | space-viewer | cluster-admin
    subject_user: str = ""
    subject_group: str = ""

    def validate(self) -> None:
        super().validate()
        if not self.role:
            raise ValidationError("role is required")
        if bool(self.subject_user) == bool(self.subject_group):
            raise ValidationError(
                "exactly one of subjectUser / subjectGroup is required"
            )
