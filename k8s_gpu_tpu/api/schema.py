"""Per-kind OpenAPI-style schema generation from the dataclass codec — the
``make manifests generate`` analogue (reference README.md:157-160: CRD
manifests are generated from the Go types' kubebuilder markers; here the
dataclasses ARE the markers).

Two consumers:
- ``cli apply --validate`` / ``cli schema``: validate a manifest against
  the schema BEFORE it touches the API server, with schema-derived
  messages (field path + expected type), and export schemas to files.
- ``GET /api/v1/schemas`` on the platform API server.

Schemas are strict (``additionalProperties: false``) — matching the
codec's unknown-field rejection (api/serialize.py)."""

from __future__ import annotations

import dataclasses
import types as _types
import typing

from .serialize import _camel, known_kinds, _KIND_REGISTRY, _ensure_registry

_PRIMITIVES = {
    str: {"type": "string"},
    int: {"type": "integer"},
    float: {"type": "number"},
    bool: {"type": "boolean"},
}


def _hint_schema(hint) -> dict:
    origin = typing.get_origin(hint)
    if origin in (typing.Union, _types.UnionType):
        arms = [a for a in typing.get_args(hint) if a is not type(None)]
        if len(arms) == 1:
            s = _hint_schema(arms[0])
            s["nullable"] = True
            return s
        return {"oneOf": [_hint_schema(a) for a in arms]}
    if hint in _PRIMITIVES:
        return dict(_PRIMITIVES[hint])
    if dataclasses.is_dataclass(hint):
        return _dataclass_schema(hint)
    if origin is dict:
        args = typing.get_args(hint)
        return {
            "type": "object",
            "additionalProperties": _hint_schema(args[1]) if len(args) == 2
            else True,
        }
    if origin in (list, tuple):
        args = typing.get_args(hint)
        elem = args[0] if args else None
        return {
            "type": "array",
            "items": _hint_schema(elem) if elem is not None else {},
        }
    return {}  # Any / unannotated: unconstrained


def _dataclass_schema(cls) -> dict:
    hints = typing.get_type_hints(cls)
    props = {}
    for f in dataclasses.fields(cls):
        s = _hint_schema(hints.get(f.name))
        doc = None
        props[_camel(f.name)] = s if doc is None else {**s, "description": doc}
    return {
        "type": "object",
        "properties": props,
        "additionalProperties": False,
    }


def schema_for_kind(kind: str) -> dict:
    """OpenAPI-style object schema for one registered kind (top-level
    manifest shape: apiVersion/kind/metadata/spec/...)."""
    _ensure_registry()
    cls = _KIND_REGISTRY.get(kind)
    if cls is None:
        raise KeyError(f"unknown kind {kind!r}; known: {known_kinds()}")
    hints = typing.get_type_hints(cls)
    props = {
        "apiVersion": {"type": "string"},
        "kind": {"type": "string", "enum": [kind]},
        "metadata": _hint_schema(hints["metadata"]),
    }
    for f in dataclasses.fields(cls):
        if f.name in ("metadata", "api_version", "kind"):
            continue
        props[_camel(f.name)] = _hint_schema(hints.get(f.name))
    return {
        "type": "object",
        "title": kind,
        "properties": props,
        "required": ["apiVersion", "kind", "metadata"],
        "additionalProperties": False,
    }


def all_schemas() -> dict[str, dict]:
    return {kind: schema_for_kind(kind) for kind in known_kinds()}


# -- validation -------------------------------------------------------------

def _type_ok(value, schema: dict) -> bool:
    t = schema.get("type")
    if t == "string":
        return isinstance(value, str)
    if t == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if t == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if t == "boolean":
        return isinstance(value, bool)
    if t == "object":
        return isinstance(value, dict)
    if t == "array":
        return isinstance(value, list)
    return True


def _validate(value, schema: dict, path: str, errors: list[str]) -> None:
    if value is None:
        if schema.get("nullable"):
            return
        # None for a typed field: report as a type error below.
    if "oneOf" in schema:
        for arm in schema["oneOf"]:
            trial: list[str] = []
            _validate(value, arm, path, trial)
            if not trial:
                return
        errors.append(f"{path}: matches no allowed form")
        return
    if "enum" in schema and value not in schema["enum"]:
        errors.append(f"{path}: must be one of {schema['enum']}, got {value!r}")
        return
    if not _type_ok(value, schema):
        errors.append(
            f"{path}: expected {schema.get('type')}, got "
            f"{type(value).__name__}"
        )
        return
    t = schema.get("type")
    if t == "object" and isinstance(value, dict):
        props = schema.get("properties")
        if props is not None:
            for key, sub in value.items():
                if key in props:
                    _validate(sub, props[key], f"{path}.{key}", errors)
                elif not schema.get("additionalProperties", True):
                    allowed = ", ".join(sorted(props))
                    errors.append(
                        f"{path}.{key}: unknown field (allowed: {allowed})"
                    )
            for req in schema.get("required", []):
                if req not in value:
                    errors.append(f"{path}.{req}: required field missing")
        else:
            ap = schema.get("additionalProperties")
            if isinstance(ap, dict):
                for key, sub in value.items():
                    _validate(sub, ap, f"{path}.{key}", errors)
    elif t == "array" and isinstance(value, list):
        items = schema.get("items") or {}
        for i, sub in enumerate(value):
            _validate(sub, items, f"{path}[{i}]", errors)


def validate_manifest(doc) -> list[str]:
    """Schema-validate one manifest dict.  Returns error strings with
    field paths ('' = valid).  ``status`` is stripped first — it is
    controller-owned and ignored on apply (api/serialize.py)."""
    if not isinstance(doc, dict):
        return ["manifest must be a mapping"]
    kind = doc.get("kind")
    _ensure_registry()
    if not isinstance(kind, str) or kind not in _KIND_REGISTRY:
        return [
            f".kind: unknown kind {kind!r} (known: {known_kinds()})"
        ]
    schema = schema_for_kind(kind)
    doc = {k: v for k, v in doc.items() if k != "status"}
    errors: list[str] = []
    _validate(doc, schema, "", errors)
    return errors
