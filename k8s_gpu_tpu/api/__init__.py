from .types import (
    ObjectMeta,
    Condition,
    CustomResource,
    ValidationError,
    set_condition,
    get_condition,
)
from .azurevmpool import AzureVmPool, AzureVmPoolSpec, AzureVmPoolStatus, ImageReference
from .tpupodslice import TpuPodSlice, TpuPodSliceSpec, TpuPodSliceStatus, SliceStatus
from .core import Secret, Node, Event, Pod, PersistentVolume, PersistentVolumeClaim, Deployment
from .devenv import DevEnv, DevEnvSpec, DevEnvStatus
from .inferenceservice import (
    InferenceService,
    InferenceServiceSpec,
    InferenceServiceStatus,
)
from .trainjob import TrainJob, TrainJobSpec, TrainJobStatus, AssetRef, EnvVar
from .tenancy import LimitRange, Namespace, ResourceQuota, RoleBinding
from .queue import DEFAULT_QUEUE, SchedulingQueue, SchedulingQueueSpec

__all__ = [
    "ObjectMeta",
    "Condition",
    "CustomResource",
    "ValidationError",
    "set_condition",
    "get_condition",
    "AzureVmPool",
    "AzureVmPoolSpec",
    "AzureVmPoolStatus",
    "ImageReference",
    "TpuPodSlice",
    "TpuPodSliceSpec",
    "TpuPodSliceStatus",
    "SliceStatus",
    "Secret",
    "Deployment",
    "Node",
    "Event",
    "Pod",
    "TrainJob",
    "TrainJobSpec",
    "TrainJobStatus",
    "AssetRef",
    "EnvVar",
    "LimitRange",
    "Namespace",
    "ResourceQuota",
    "RoleBinding",
    "DEFAULT_QUEUE",
    "SchedulingQueue",
    "SchedulingQueueSpec",
    "PersistentVolume",
    "PersistentVolumeClaim",
    "DevEnv",
    "DevEnvSpec",
    "DevEnvStatus",
    "InferenceService",
    "InferenceServiceSpec",
    "InferenceServiceStatus",
]
