"""TpuPodSlice CRD — the TPU-native pool resource (BASELINE.json north star).

Where the reference pools individual Azure GPU VMs (AzureVmPool,
reference README.md:83-156), the atomic capacity unit on TPU is a *pod
slice*: an all-or-nothing block of chips wired by ICI.  The CRD therefore
declares slices (acceleratorType + topology + sliceCount for multislice)
rather than VM replicas, and the reconciler drives Cloud TPU queued
resources (CREATING→ACTIVE) rather than VM+NIC+Disk create/delete.

Design notes vs the reference:
- ``spec.slice_count`` > 1 == multislice over DCN (BASELINE config 4);
  gang semantics are inherent (a slice is atomic — SURVEY §2.7), so there is
  no Volcano-style ``minAvailable`` field.
- ``spec.workload_identity`` replaces the Azure Service-Principal secret
  (reference README.md:43-57; BASELINE north star: GCP Workload Identity).
- ``status.ready_replicas`` keeps the reference's printer-column/parity
  semantics (reference README.md:121-133): it counts *ready slices*.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .types import CustomResource, Condition, ValidationError
from ..cloud.topology import parse_accelerator_type


@dataclass
class TpuPodSliceSpec:
    # e.g. "v4-8", "v5p-64", "v5e-256" (BASELINE configs 2-4).
    accelerator_type: str = "v4-8"
    # Optional explicit chip topology ("4x4x4"); derived from accelerator
    # type when empty.  Validated for consistency.
    topology: str = ""
    # Number of identical slices (multislice when > 1).
    slice_count: int = 1
    # TPU software stack on the hosts.
    runtime_version: str = "tpu-ubuntu2204-base"
    # GCP project/zone targeting.
    project: str = ""
    zone: str = ""
    network: str = "default"
    # Kubernetes ServiceAccount annotated for GCP Workload Identity; the
    # client factory exchanges it for cloud credentials (no secret material
    # in-cluster — the hardening step the reference defers, README.md:312).
    workload_identity: str = "tpu-provisioner"
    # Queued-resource niceties.
    reserved: bool = False
    spot: bool = False
    # Best-effort provisioning deadline used for the Ready SLO.
    provisioning_timeout_s: float = 300.0


@dataclass
class SliceStatus:
    name: str = ""
    state: str = ""  # queued-resource state: WAITING|PROVISIONING|ACTIVE|FAILED...
    nodes_total: int = 0
    nodes_ready: int = 0


@dataclass
class TpuPodSliceStatus:
    # Ready *slices* (printer-column parity with the reference's
    # readyReplicas, README.md:121-133).
    ready_replicas: int = 0
    slices: list[SliceStatus] = field(default_factory=list)
    conditions: list[Condition] = field(default_factory=list)
    # Aggregate queued-resource phase for kubectl get output.
    phase: str = "Pending"
    observed_generation: int = 0


@dataclass
class TpuPodSlice(CustomResource):
    kind: str = "TpuPodSlice"
    api_version: str = "tpu.k8sgpu.dev/v1alpha1"
    spec: TpuPodSliceSpec = field(default_factory=TpuPodSliceSpec)
    status: TpuPodSliceStatus = field(default_factory=TpuPodSliceStatus)

    def validate(self) -> None:
        super().validate()
        if self.spec.slice_count < 0:
            raise ValidationError("spec.sliceCount must be >= 0")
        if self.spec.spot and self.spec.reserved:
            # Mirrors the wire contract (cloud/wire.py): the API's tier
            # selector is spot XOR guaranteed — rejecting here keeps the
            # reconciler from ever building an unroutable create.
            raise ValidationError(
                "spec.spot and spec.reserved are mutually exclusive"
            )
        try:
            info = parse_accelerator_type(self.spec.accelerator_type)
        except ValueError as e:
            raise ValidationError(str(e)) from e
        if self.spec.topology:
            try:
                dims = tuple(int(d) for d in self.spec.topology.split("x"))
            except ValueError as e:
                raise ValidationError(
                    f"malformed topology {self.spec.topology!r}; want e.g. '4x4x4'"
                ) from e
            prod = 1
            for d in dims:
                prod *= d
            if prod != info.chips:
                raise ValidationError(
                    f"topology {self.spec.topology} has {prod} chips but "
                    f"{self.spec.accelerator_type} requires {info.chips}"
                )

    @property
    def printer_columns(self) -> dict:
        return {
            "Accelerator": self.spec.accelerator_type,
            "Desired": self.spec.slice_count,
            "Ready": self.status.ready_replicas,
            "Phase": self.status.phase,
        }
