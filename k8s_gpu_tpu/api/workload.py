"""Workload execution contract — the channel between the TrainJob operator
and an in-process workload.

The reference's elastic story is pod-level ``restartPolicy: OnFailure``
plus checkpoint files under ``/output`` (GPU调度平台搭建.md:668, 686-697);
SURVEY §5.3-5.4 demand the end-to-end version: periodic save → preemption
→ re-place → auto-resume from the latest step.  A workload that accepts a
third argument receives a :class:`WorkloadContext`; through it the
workload reports progress/checkpoints into the job status and is told —
via :class:`WorkloadInterrupted` from :meth:`WorkloadContext.heartbeat` —
when the slice under it was preempted, so the operator can re-place the
gang and the workload can resume instead of restarting from step 0.

This module is deliberately JAX-free: the controller imports it without
loading the ML runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable


class WorkloadInterrupted(RuntimeError):
    """The gang's placement vanished mid-run (slice preempted / nodes
    pruned).  The operator treats this as restartable, not fatal."""


@dataclass
class WorkloadContext:
    """Handed to 3-arg workloads: ``fn(spec, placements, ctx)``.

    checkpoint_dir / checkpoint_interval come from the job spec (resolved
    to a stable per-job default by the operator so a restarted job finds
    its own checkpoints).  ``heartbeat(step)`` should be called once per
    training step: it publishes progress to the job status and raises
    WorkloadInterrupted when any placement node is gone.
    """

    checkpoint_dir: str = ""
    checkpoint_interval: int = 0
    placements: dict[str, str] = field(default_factory=dict)
    # Node identity (name → uid) captured at placement time: a preempted
    # slice's nodes may be recreated under the SAME names within
    # milliseconds, so liveness alone can miss the preemption — the uid
    # changing is the reliable "this is not the host you were placed on".
    node_uids: dict[str, str] = field(default_factory=dict)
    # Injected by the operator; kept as callables so this module stays
    # free of controller imports (and trivially fake-able in tests).
    _node_uid: Callable[[str], str | None] | None = None
    _patch_status: Callable[[Callable[[Any], None]], None] | None = None

    def heartbeat(self, step: int) -> None:
        self._set_status("progress_step", step)
        if self._node_uid is None:
            return
        lost = []
        for node in sorted(set(self.placements.values())):
            uid = self._node_uid(node)
            want = self.node_uids.get(node)
            if uid is None:
                lost.append(f"{node} (gone)")
            elif want and uid != want:
                lost.append(f"{node} (replaced)")
        if lost:
            raise WorkloadInterrupted(
                f"placement node(s) lost at step {step}: {', '.join(lost)}"
            )

    def record_checkpoint(self, step: int) -> None:
        self._set_status("checkpoint_step", step)

    def record_resume(self, step: int) -> None:
        self._set_status("resumed_from_step", step)

    def _set_status(self, attr: str, value: int) -> None:
        if self._patch_status is not None:
            self._patch_status(lambda status: setattr(status, attr, value))
