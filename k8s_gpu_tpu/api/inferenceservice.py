"""InferenceService CRD — platform-managed LM serving.

The reference hosts its serving workload as a hand-run Ollama deployment
the Fin-Agent service points at (智能风控解决方案.md:368-419, 440-520:
docker-compose with a fixed `ollama` service) — serving is config, not a
reconciled object.  Here serving joins the workload matrix next to
TrainJob and DevEnv: an InferenceService declares a servable model
bundle from the asset store (serve/bundle.py — the train→export→serve
journey of GPU调度平台搭建.md:686-697) plus replica/engine knobs, and the
reconciler (operators/inferenceservice.py) keeps that many live serving
replicas placed on TPU chip carve-outs, self-healing and optionally
autoscaling on queue depth.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .trainjob import AssetRef
from .types import Condition, CustomResource, ValidationError


@dataclass
class InferenceServiceSpec:
    # Servable model bundle (kind "model" in the AssetStore; must be the
    # serve.bundle format — raw checkpoint exports are rejected at load).
    model: AssetRef = field(default_factory=AssetRef)
    # Optional speculative-decoding draft bundle (serve/speculative.py);
    # empty id = plain decoding.
    draft: AssetRef = field(default_factory=AssetRef)
    # "ngram" = prompt-lookup drafting (proposals from each row's own
    # token history, batcher.ngram_propose) — no draft bundle involved.
    draft_mode: str = ""
    replicas: int = 1
    # Chips carved out of one TPU host per replica (the HAMi-sharing
    # path, scheduling/sharing.py) — serving replicas are single-host;
    # scale throughput by replicas, not slice size.
    chips: int = 1
    # Engine knobs, passed through to serve.LmServer/ContinuousBatcher.
    slots: int = 8
    spec_k: int = 4
    kv_quant: bool = False
    # Paged (block-table) KV pool: > 0 = number of physical blocks of
    # pagedPageSize positions; cache bytes then scale with USED tokens
    # (serve/batcher.py paged mode).  0 = dense slots×max_seq pool.
    paged_blocks: int = 0
    paged_page_size: int = 64
    eos_id: int = -1
    max_new_tokens_cap: int = 256
    # Queue-depth autoscaling: when max_replicas > 0 the reconciler sizes
    # the replica set to clamp(ceil(pending / target_pending_per_replica),
    # min_replicas, max_replicas) from the live batchers' pending-request
    # depth; spec.replicas is then only the initial size.
    min_replicas: int = 0
    max_replicas: int = 0
    target_pending_per_replica: int = 4


@dataclass
class InferenceServiceStatus:
    phase: str = "Pending"  # Pending|Ready|Degraded|Failed
    message: str = ""
    # Desired size after autoscaling (== spec.replicas when off).
    replicas: int = 0
    ready_replicas: int = 0
    # "host:port" per live replica, index-aligned with pods.
    endpoints: list[str] = field(default_factory=list)
    # pod name → node name.
    placements: dict[str, str] = field(default_factory=dict)
    # Last observed total pending-request depth (the autoscale signal).
    pending_requests: int = 0
    conditions: list[Condition] = field(default_factory=list)


@dataclass
class InferenceService(CustomResource):
    kind: str = "InferenceService"
    api_version: str = "tpu.k8sgpu.dev/v1alpha1"
    spec: InferenceServiceSpec = field(default_factory=InferenceServiceSpec)
    status: InferenceServiceStatus = field(
        default_factory=InferenceServiceStatus
    )

    def validate(self) -> None:
        super().validate()
        s = self.spec
        if not s.model.id:
            raise ValidationError("spec.model.id is required")
        if s.replicas < 1:
            raise ValidationError("spec.replicas must be >= 1")
        if s.chips < 1:
            raise ValidationError("spec.chips must be >= 1")
        if s.slots < 1:
            raise ValidationError("spec.slots must be >= 1")
        if s.max_replicas:
            if s.min_replicas < 1:
                raise ValidationError(
                    "autoscaling needs spec.minReplicas >= 1"
                )
            if s.max_replicas < s.min_replicas:
                raise ValidationError(
                    "spec.maxReplicas must be >= spec.minReplicas"
                )
            if s.target_pending_per_replica < 1:
                raise ValidationError(
                    "spec.targetPendingPerReplica must be >= 1"
                )
        if (s.draft.id or s.draft_mode) and s.spec_k < 1:
            raise ValidationError(
                "speculative serving (spec.draft / spec.draftMode) needs "
                "spec.specK >= 1"
            )
        if s.draft_mode not in ("", "ngram"):
            raise ValidationError(
                "spec.draftMode must be '' or 'ngram'"
            )
        if s.draft_mode and s.draft.id:
            raise ValidationError(
                "spec.draftMode and spec.draft are mutually exclusive "
                "(ngram drafting uses no draft bundle)"
            )
        if s.paged_blocks < 0:
            raise ValidationError("spec.pagedBlocks must be >= 0")
        # pagedBlocks + draft/draftMode compose since the paged pool
        # grew block-level prefix sharing: speculative verify extends
        # run directly on the paged pool (serve/batcher.py).
