"""Profiling — the tracing half of SURVEY §5.1 ("JAX profiler traces,
XLA/TensorBoard"), absent from the reference (stdout logs only;
GPU调度平台搭建.md:798-807 monitors utilization, never traces).

Thin, dependency-free wrappers over ``jax.profiler``: a trace context that
captures device/XLA activity into a TensorBoard-readable directory, step
annotations so train steps show as named rows, and a helper that profiles
N steps of a Trainer.  On TPU the trace includes per-op device timing and
HBM usage — the tool for verifying the MXU is actually busy.

This is the DEEP-DIVE path; the always-on counterpart is
``utils/profiler.py`` (continuous phase attribution: ``/debug/profile``,
``obs profile``) — it answers "which phase", this module answers "which
op".  Wall-clock here flows through an injected ``utils.clock.Clock``
(graftcheck det-wallclock compliance: this module is in the determinism
planes), so a ``FakeClock`` caller replays deterministically.
"""

from __future__ import annotations

import contextlib
from pathlib import Path

from .clock import Clock, RealClock


@contextlib.contextmanager
def trace(log_dir: str | Path):
    """Capture a profiler trace into *log_dir* (view with TensorBoard's
    profile plugin, or xprof)."""
    import jax  # lazy: utils is imported by the jax-free control plane

    log_dir = Path(log_dir)
    log_dir.mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(str(log_dir))
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def step_annotation(name: str, step: int):
    """Marks a training step in the trace timeline."""
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def profile_trainer(trainer, data_iter, steps: int,
                    log_dir: str | Path,
                    clock: Clock | None = None) -> dict:
    """Profile *steps* steps (after one un-traced warmup/compile step so the
    trace shows steady-state device time, not compilation).  Returns
    {trace_dir, steps, mean_step_s}.

    ``data_iter`` must yield at least ``steps + 1`` batches (the extra one
    feeds the warmup step); a shorter iterator raises ``ValueError``
    up front instead of leaking a bare ``StopIteration`` mid-trace."""
    clock = clock or RealClock()

    def draw(drawn: int):
        try:
            return next(data_iter)
        except StopIteration:
            raise ValueError(
                f"data_iter exhausted after {drawn} batches: "
                f"profile_trainer(steps={steps}) draws steps + 1 batches "
                "(one un-traced warmup step precedes the trace window) — "
                "pass an iterator yielding at least that many"
            ) from None

    batch = draw(0)
    trainer.step(*batch)  # compile outside the trace
    t0 = clock.now()
    with trace(log_dir) as d:
        for i in range(steps):
            with step_annotation("train", i):
                batch = draw(i + 1)
                trainer.step(*batch)
    wall = clock.now() - t0
    return {
        "trace_dir": str(d),
        "steps": steps,
        "mean_step_s": wall / max(1, steps),
    }


def trace_files(log_dir: str | Path) -> list[Path]:
    """The .xplane.pb artifacts a capture produced (empty = no capture)."""
    return sorted(Path(log_dir).rglob("*.xplane.pb"))
