"""Profiling — the tracing half of SURVEY §5.1 ("JAX profiler traces,
XLA/TensorBoard"), absent from the reference (stdout logs only;
GPU调度平台搭建.md:798-807 monitors utilization, never traces).

Thin, dependency-free wrappers over ``jax.profiler``: a trace context that
captures device/XLA activity into a TensorBoard-readable directory, step
annotations so train steps show as named rows, and a helper that profiles
N steps of a Trainer.  On TPU the trace includes per-op device timing and
HBM usage — the tool for verifying the MXU is actually busy.
"""

from __future__ import annotations

import contextlib
import time
from pathlib import Path


@contextlib.contextmanager
def trace(log_dir: str | Path):
    """Capture a profiler trace into *log_dir* (view with TensorBoard's
    profile plugin, or xprof)."""
    import jax  # lazy: utils is imported by the jax-free control plane

    log_dir = Path(log_dir)
    log_dir.mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(str(log_dir))
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def step_annotation(name: str, step: int):
    """Marks a training step in the trace timeline."""
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def profile_trainer(trainer, data_iter, steps: int,
                    log_dir: str | Path) -> dict:
    """Profile *steps* steps (after one un-traced warmup/compile step so the
    trace shows steady-state device time, not compilation).  Returns
    {trace_dir, steps, mean_step_s}."""
    batch = next(data_iter)
    trainer.step(*batch)  # compile outside the trace
    t0 = time.perf_counter()
    with trace(log_dir) as d:
        for i in range(steps):
            with step_annotation("train", i):
                batch = next(data_iter)
                trainer.step(*batch)
    wall = time.perf_counter() - t0
    return {
        "trace_dir": str(d),
        "steps": steps,
        "mean_step_s": wall / max(1, steps),
    }


def trace_files(log_dir: str | Path) -> list[Path]:
    """The .xplane.pb artifacts a capture produced (empty = no capture)."""
    return sorted(Path(log_dir).rglob("*.xplane.pb"))
