"""Fleet metrics federation: many `/metrics` islands → one registry.

Every replica in the serving fleet (and every control-plane process)
exports its own Prometheus-style exposition, but a per-process endpoint
cannot answer "which replica is hot" or "which tenant is burning its
SLO" — the questions the prefix-aware router and the telemetry-driven
autoscaler (ROADMAP item 1) have to ask every tick.  ``FleetCollector``
is the aggregation substrate:

- **scrape**: each target is a replica name mapped to either a base URL
  (``http://host:port`` — ``/metrics`` is fetched) or a zero-arg
  callable returning exposition text (in-process replicas, fakes in
  tests — fully deterministic under ``FakeClock``).  Targets iterate in
  sorted name order, so two scrape passes over the same inputs produce
  a bit-identical fleet registry.
- **relabel**: every scraped series lands in the fleet registry with a
  ``replica=<name>`` label added, preserving the source labels — the
  per-replica detail plane (``serve_slot_fill_ratio{replica="r1"}``).
- **aggregate**: per-metric policy.  Counters (``_total``/``_count``/
  ``_sum``/``_bucket`` suffixes) are summed *at read time* — the rules
  engine's ``ctx.sum``/``ctx.rate`` already sum across matching
  label-sets, so storing a fleet-sum series under the same name would
  double-count every rate.  Gauges additionally get a STORED aggregate
  series under the same name without the ``replica`` label (``sum``,
  ``max``, ``min`` or ``avg`` per ``GAUGE_AGG``; default ``max`` — the
  hot-spot view), so ``ctx.gauge(name)`` reads the fleet value and
  per-replica label-sets keep their own alert FSMs.  Histogram
  percentiles merge at read time from the summed ``_bucket`` series
  (``FleetCollector.percentile`` — raw reservoirs don't cross the text
  format, so the fleet quantile interpolates inside the merged bucket,
  the standard ``histogram_quantile`` estimate).
- **liveness**: a scrape failure bumps ``fleet_scrape_failures_total``
  and a replica whose scrape fails ``down_after`` CONSECUTIVE passes
  drops ``fleet_replica_up{replica=}`` to 0 and has its per-replica
  series purged (a dead replica's last-seen gauges must not keep
  per-replica alerts firing against nothing — the same vanished-series
  contract the pool gauges follow).  ``FleetReplicaDown`` in the
  default rule pack alerts on exactly this gauge, and recovery flips it
  back to 1 (the alert resolves).

The existing ``RuleEvaluator`` runs over the fleet registry unchanged:
``attach(evaluator)`` registers ``scrape_once`` as an evaluator
collector, so every tick scrapes the fleet BEFORE rules evaluate —
fleet-level burn rates and per-replica saturation alerts fall out of
the default pack with zero new engine code.
"""

from __future__ import annotations

import threading

from .clock import Clock, RealClock
from .metrics import MetricsRegistry, _fmt, parse_exposition

_COUNTERISH = ("_total", "_count", "_sum", "_bucket")

# Stored-aggregate policy for gauge families (the fleet series written
# WITHOUT the replica label).  Everything absent defaults to "max":
# for saturation-shaped gauges the fleet answer is its hottest member.
GAUGE_AGG: dict[str, str] = {
    "serve_slot_fill_ratio": "avg",
    "serve_pending_requests": "sum",
    "serve_slots_active": "sum",
    "serve_decode_tokens_per_second": "sum",
    "serve_kv_blocks_used": "sum",
    "serve_kv_blocks_shared": "sum",
    "serve_kv_blocks_cached": "sum",
    "workqueue_depth": "sum",
    "train_tokens_per_second": "sum",
    "pool_ready_ratio": "min",
    # Attribution plane (ISSUE 9): a phase's fleet share/MFU is the
    # replica mean (summing shares of one wall clock is meaningless);
    # bandwidth keeps the default-max "hottest member" view but is
    # listed here so the policy is explicit, not accidental.
    "serve_phase_share": "avg",
    "train_phase_share": "avg",
    "train_mfu": "avg",
    "collective_bytes_per_second": "max",
    # Goodput plane (ISSUE 13): fleet goodput is the replica mean
    # (each replica partitions its own wall clock); skew keeps the
    # default-max shape explicitly (the fleet's worst straggler is the
    # answer), and the straggler marker / checkpoint size follow it —
    # "which host, how big" are hottest-member questions.
    "train_goodput_ratio": "avg",
    "train_step_skew_ratio": "max",
    "train_straggler_host": "max",
    "train_checkpoint_bytes": "max",
    # Canary/SLO plane (ISSUE 14): fleet health is its SICKEST member
    # (min over the 1.0/0.5/0.0 state gauge — one unhealthy replica
    # makes the fleet row say so), the remaining error budget is the
    # tightest objective's, and burn is hottest-member.
    "probe_replica_healthy": "min",
    "slo_budget_remaining_ratio": "min",
    "slo_burn_rate_fast": "max",
    "slo_burn_rate_slow": "max",
    # Waterfall plane (ISSUE 16): the fleet's clock-skew answer is its
    # worst-aligned process — the one whose attributed segments carry
    # the most alignment error.
    "e2e_clock_skew_seconds": "max",
    # Gateway fleet (ISSUE 18): convergence is its WORST member (one
    # diverged gateway makes the fleet row say 0), and the owner-map
    # hash aggregates min so "all gateways equal" reads as "min equals
    # every member" — any disagreement shows up as the fleet row
    # differing from some replica row.  Tenant share averages across
    # gateways (each admits its own slice of one tenant's traffic);
    # queue depth is total queued work.
    "gateway_converged": "min",
    "gateway_owner_map_hash": "min",
    "admission_tenant_share": "avg",
    "admission_queue_depth": "sum",
}

# Families the collector never writes aggregates for: the fleet
# evaluator OWNS these names in the fleet registry (an aggregate would
# clobber its output); per-replica relabeled copies are still written.
_NO_AGG = frozenset({"alerts_firing"})

# The per-replica gauge set /fleet snapshots and the CLI renderers
# surface (full detail stays queryable from the registry itself).
KEY_GAUGES = (
    "serve_slot_fill_ratio",
    "serve_kv_occupancy_ratio",
    "serve_pending_requests",
    "serve_decode_tokens_per_second",
    "serve_slots_active",
    "workqueue_depth",
)


def _series_key(name: str, labels: dict) -> str:
    return f"{name}{_fmt(tuple(sorted(labels.items())))}"


def bucket_quantile(series: dict, q: float) -> float | None:
    """``histogram_quantile`` over cumulative ``_bucket`` series that
    may span replicas: per-``le`` counts sum (cumulative merges stay
    cumulative), then the quantile interpolates linearly inside the
    first bucket whose merged count covers rank ``q*n``.  None when the
    merged histogram is empty."""
    merged: dict[float, float] = {}
    for lbls, v in series.items():
        le = dict(lbls).get("le")
        if le is None:
            continue
        try:
            b = float(le)
        except ValueError:
            continue
        merged[b] = merged.get(b, 0.0) + v
    if not merged:
        return None
    bounds = sorted(merged)
    total = merged[bounds[-1]]
    if total <= 0.0:
        return None
    rank = max(0.0, min(1.0, q)) * total
    prev_bound, prev_cum = 0.0, 0.0
    for b in bounds:
        cum = merged[b]
        if cum >= rank:
            if b == float("inf"):
                # Observation above the last finite bucket: the best
                # honest answer is that bucket's bound.
                return prev_bound
            span = cum - prev_cum
            frac = (rank - prev_cum) / span if span > 0 else 1.0
            return prev_bound + (b - prev_bound) * frac
        prev_bound, prev_cum = b, cum
    return bounds[-1]


class FleetCollector:
    """Scrapes a named set of exposition targets into one fleet
    ``MetricsRegistry`` (see module docstring for the model)."""

    # Lock contract (graftcheck lockcheck + utils.faults
    # guard_declared): the target map and scrape bookkeeping are shared
    # between the evaluator tick thread, /fleet HTTP handlers, and
    # router refreshes.  ``_scrape_lock`` serializes whole passes and
    # guards nothing by itself — it is ordering, not state.
    _GUARDED_BY = {
        "_lock": (
            "_targets", "_fails", "_last_ok", "_last_fams",
            "_ingested", "_agg_keys", "_scrapes",
        ),
    }

    def __init__(
        self,
        targets: dict | None = None,
        registry: MetricsRegistry | None = None,
        clock: Clock | None = None,
        down_after: int = 3,
        gauge_agg: dict | None = None,
        timeout: float = 5.0,
        max_series_per_name: int = 4096,
    ):
        """``targets``: ``{replica_name: url_or_callable}``.  A fresh
        fleet registry gets a higher cardinality cap than the default —
        every source series fans out per replica, and the guard must
        bound tenants-x-replicas, not clip a healthy fleet."""
        self.registry = registry or MetricsRegistry(
            max_series_per_name=max_series_per_name
        )
        self.clock = clock or RealClock()
        self.down_after = max(1, int(down_after))
        self.timeout = float(timeout)
        self.gauge_agg = {**GAUGE_AGG, **(gauge_agg or {})}
        self._lock = threading.Lock()
        # Serializes whole scrape passes: the evaluator tick thread and
        # a /fleet?refresh=1 HTTP handler can both call scrape_once —
        # interleaved passes would double-step the consecutive-failure
        # counters past the purge threshold and race the stale-series
        # diffs.  Distinct from (and always taken outside) _lock.
        self._scrape_lock = threading.Lock()
        self._targets: dict[str, object] = {}
        self._fails: dict[str, int] = {}
        self._last_ok: dict[str, float] = {}
        self._last_fams: dict[str, dict] = {}
        # Per-replica (name, label_tuple) gauge keys currently written
        # into the fleet registry — the purge/diff bookkeeping.
        self._ingested: dict[str, set] = {}
        self._agg_keys: set = set()
        self._scrapes = 0
        for name, target in (targets or {}).items():
            self.add_target(name, target)

    # -- target management -------------------------------------------------
    def add_target(self, name: str, target) -> None:
        with self._lock:
            self._targets[str(name)] = target
            self._fails.setdefault(str(name), 0)

    def remove_target(self, name: str) -> None:
        with self._lock:
            self._targets.pop(name, None)
            self._fails.pop(name, None)
            self._last_ok.pop(name, None)
            self._last_fams.pop(name, None)
        self._purge(name)
        self.registry.remove_gauge("fleet_replica_up", replica=name)
        self.registry.remove_gauge(
            "fleet_scrape_age_seconds", replica=name
        )

    @property
    def never_scraped(self) -> bool:
        with self._lock:
            return self._scrapes == 0

    def attach(self, evaluator) -> "FleetCollector":
        """Register the scrape as an evaluator collector: every rule
        tick scrapes the fleet first, so rules always see this tick's
        replicas.  The evaluator's clock should be this collector's
        clock (one time domain)."""
        evaluator.collectors.append(self.scrape_once)
        return self

    # -- scraping ----------------------------------------------------------
    def _fetch(self, target) -> str:
        if callable(target):
            return target()
        import urllib.request

        url = str(target).rstrip("/")
        if not url.endswith("/metrics"):
            url += "/metrics"
        with urllib.request.urlopen(url, timeout=self.timeout) as r:
            return r.read().decode()

    def scrape_once(self) -> dict[str, bool]:
        """One federation pass over every target (sorted order —
        deterministic); returns ``{replica: scraped_ok}``.  Concurrent
        calls serialize — the second caller scrapes right after the
        first, never interleaved with it."""
        with self._scrape_lock:
            return self._scrape_once_locked()

    def _scrape_once_locked(self) -> dict[str, bool]:
        now = self.clock.now()
        with self._lock:
            targets = sorted(self._targets.items())
        up: dict[str, bool] = {}
        for name, target in targets:
            try:
                fams = parse_exposition(self._fetch(target))
            except Exception:
                with self._lock:
                    fails = self._fails.get(name, 0) + 1
                    self._fails[name] = fails
                self.registry.inc(
                    "fleet_scrape_failures_total", replica=name
                )
                if fails >= self.down_after:
                    # The M-th consecutive failure: the replica is DOWN.
                    # Purge its per-replica series so stale last-seen
                    # gauges can't keep replica-scoped alerts firing,
                    # but keep (and zero) the up gauge — it IS the
                    # FleetReplicaDown signal.  (>= so a skipped count
                    # can never skip the purge; re-purging is a no-op.)
                    with self._lock:
                        self._last_fams.pop(name, None)
                    self._purge(name)
                    self.registry.set_gauge(
                        "fleet_replica_up", 0.0, replica=name
                    )
                up[name] = False
                continue
            with self._lock:
                self._fails[name] = 0
                self._last_ok[name] = now
                self._last_fams[name] = fams
            self.registry.set_gauge("fleet_replica_up", 1.0, replica=name)
            self._ingest(name, fams)
            up[name] = True
        self._aggregate()
        with self._lock:
            for name, _ in targets:
                last = self._last_ok.get(name)
                if last is not None:
                    self.registry.set_gauge(
                        "fleet_scrape_age_seconds", now - last,
                        replica=name,
                    )
        self.registry.set_gauge("fleet_replicas", float(len(targets)))
        self.registry.set_gauge(
            "fleet_replicas_up", float(sum(1 for v in up.values() if v))
        )
        with self._lock:
            self._scrapes += 1
        return up

    def _ingest(self, replica: str, fams: dict) -> None:
        """Write one replica's parsed families into the fleet registry
        with ``replica=`` added; series that vanished since the last
        scrape of this replica are removed (gauge semantics: a scrape
        REPLACES the replica's contribution, it never accretes)."""
        fresh: set = set()
        for mname, series in fams.items():
            if mname.startswith("fleet_"):
                continue  # never re-federate collector output
            for lbls, v in series.items():
                d = dict(lbls)
                d["replica"] = replica
                self.registry.set_gauge_series(mname, v, d)
                fresh.add((mname, tuple(sorted(d.items()))))
        with self._lock:
            stale = self._ingested.get(replica, set()) - fresh
            self._ingested[replica] = fresh
        for mname, lbls in stale:
            self.registry.remove_gauge(mname, **dict(lbls))

    def _purge(self, replica: str) -> None:
        with self._lock:
            keys = self._ingested.pop(replica, set())
        for mname, lbls in keys:
            self.registry.remove_gauge(mname, **dict(lbls))

    def _aggregate(self) -> None:
        """Stored gauge aggregates across UP replicas: same name, the
        source label-set minus ``replica``.  Counter-suffixed families
        are skipped — their fleet value is the read-time sum the rules
        engine already computes, and a stored sum would double every
        ``ctx.rate``."""
        with self._lock:
            fams_by_rep = sorted(self._last_fams.items())
        groups: dict[tuple, list[float]] = {}
        for _, fams in fams_by_rep:
            for mname, series in fams.items():
                if (
                    mname.endswith(_COUNTERISH)
                    or mname.startswith("fleet_")
                    or mname in _NO_AGG
                ):
                    continue
                for lbls, v in series.items():
                    groups.setdefault((mname, lbls), []).append(v)
        fresh: set = set()
        for (mname, lbls), vals in groups.items():
            how = self.gauge_agg.get(mname, "max")
            if how == "sum":
                v = sum(vals)
            elif how == "min":
                v = min(vals)
            elif how == "avg":
                v = sum(vals) / len(vals)
            else:
                v = max(vals)
            self.registry.set_gauge_series(mname, v, dict(lbls))
            fresh.add((mname, lbls))
        with self._lock:
            stale = self._agg_keys - fresh
            self._agg_keys = fresh
        for mname, lbls in stale:
            self.registry.remove_gauge(mname, **dict(lbls))

    # -- read surface ------------------------------------------------------
    def percentile(self, name: str, q: float, **where) -> float | None:
        """Fleet quantile for histogram family *name*, merged across
        replicas from the federated ``_bucket`` series; ``where``
        filters labels (e.g. ``replica="r1"`` for one replica's view)."""
        series = {
            lbls: v
            for lbls, v in self.registry.series(f"{name}_bucket").items()
            if all(dict(lbls).get(k) == v2 for k, v2 in where.items())
        }
        return bucket_quantile(series, q)

    def replica_names(self) -> list[str]:
        with self._lock:
            return sorted(self._targets)

    def snapshot(self) -> dict:
        """The ``/fleet`` JSON body: per-replica liveness + key gauges,
        fleet aggregates, and per-tenant token/goodput totals summed
        across replicas (the "which tenant is burning" table)."""
        now = self.clock.now()
        with self._lock:
            targets = sorted(self._targets)
            fails = dict(self._fails)
            last_ok = dict(self._last_ok)
            fams = {k: v for k, v in self._last_fams.items()}
            scrapes = self._scrapes
        replicas = []
        for name in targets:
            f = fams.get(name, {})
            gauges = {}
            for g in KEY_GAUGES:
                series = f.get(g)
                if not series:
                    continue
                if len(series) == 1:
                    gauges[g] = next(iter(series.values()))
                else:
                    # Multi-series family on one replica (e.g. a queue
                    # label): keep the labeled breakdown.
                    gauges[g] = {
                        _series_key(g, dict(lbls)): v
                        for lbls, v in sorted(series.items())
                    }
            ttft = self.percentile(
                "serve_ttft_seconds", 0.95, replica=name
            )
            last = last_ok.get(name)
            replicas.append({
                "replica": name,
                "up": fails.get(name, 0) < self.down_after,
                "consecutive_failures": fails.get(name, 0),
                "last_scrape_age_s": (
                    round(now - last, 3) if last is not None else None
                ),
                "gauges": gauges,
                "ttft_p95_s": ttft,
            })
        aggregates = {}
        for g in KEY_GAUGES:
            vals = self.registry.series(g)
            # The stored aggregate is the series WITHOUT a replica label.
            flat = {
                lbls: v for lbls, v in vals.items()
                if "replica" not in dict(lbls)
            }
            if flat:
                aggregates[g] = {
                    "agg": self.gauge_agg.get(g, "max"),
                    "value": (
                        next(iter(flat.values())) if len(flat) == 1
                        else {
                            _series_key(g, dict(lbls)): v
                            for lbls, v in sorted(flat.items())
                        }
                    ),
                }
        tenants: dict[str, dict] = {}
        for metric, key in (
            ("serve_tenant_tokens_total", "tokens"),
            ("serve_tenant_goodput_tokens_total", "goodput_tokens"),
        ):
            for lbls, v in self.registry.series(metric).items():
                t = dict(lbls).get("tenant")
                if t is None:
                    continue
                tenants.setdefault(t, {"tokens": 0.0, "goodput_tokens": 0.0})
                tenants[t][key] += v
        for t, d in tenants.items():
            burn = self.registry.gauge("tenant_slo_burn_rate", tenant=t)
            if burn is not None:
                d["slo_burn_rate"] = burn
        return {
            "now": now,
            "down_after": self.down_after,
            "scrapes": scrapes,
            "replicas": replicas,
            "aggregates": aggregates,
            "tenants": {t: tenants[t] for t in sorted(tenants)},
            "ttft_p95_s": self.percentile("serve_ttft_seconds", 0.95),
        }
