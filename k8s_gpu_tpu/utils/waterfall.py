"""Fleet waterfall: cross-process trace stitching and per-request
critical-path attribution.

PR 15's gateway propagates ``traceparent`` to replicas and both sides
journal the trace id, but the spans land in two separate per-process
rings — nobody can answer "where did THIS request's 900ms go: gateway
routing, network, replica queue, prefill, or decode?".
``FleetTraceAssembler`` is the missing stitcher:

- **scrape**: FleetCollector-style targets — ``{process_name: url}``
  fetches ``/debug/traces?since=<cursor>`` (the tracer's completion
  index, so each pass ships only new traces) plus ``/debug/requests``
  (journal context; optional — a target without a journal just skips
  it), and ``{process_name: callable}`` returns the same JSON shape
  in-process (fully deterministic in tests).  Targets iterate in
  sorted name order; spans dedup by span id, so re-scraping is
  idempotent.
- **stitch**: spans merge by trace id into ONE tree per request.  The
  gateway mints a ``gateway.dispatch`` span per downstream contact and
  propagates that span's PRE-MINTED id as the attempt's
  ``traceparent``, so the replica's server span parents to the attempt
  — a structural cross-process edge that survives both rings being
  scraped independently.
- **clock alignment**: each process runs its own monotonic clock with
  an arbitrary origin.  For every (dispatch span, child server span)
  pair the replica's offset is estimated as the difference of the two
  spans' midpoints, averaged over the trace's pairs — pinning the
  child span centered inside its enclosing dispatch span.  The offset
  is REPORTED (``e2e_clock_skew_seconds{process=}``), never hidden;
  its honesty limit is that the request/response network legs are
  assumed symmetric, so ``network_gap`` splits evenly when they are
  not.  A process with spans but no pair stays unaligned and flags the
  trace.
- **attribution**: a priority interval sweep over the client-observed
  window decomposes E2E (and TTFT, when a prefill span marks the first
  token) into an exhaustive partition — ``kv_handover`` (disagg
  prefill→export→wire→import time, the gateway's ``gateway.handover``
  span; claimed before ``gateway_route`` because the handover happens
  inside the pre-dispatch window), ``gateway_route`` (request
  start → first contact), one ``retry_hop`` per failed rehash attempt
  (a kill-mid-burst request shows the dead replica's partial spans AND
  the survivor's completion in one trace), ``network_gap`` (serving
  dispatch time not covered by the replica's server span, split
  request/response side), ``queue_wait``/``prefill``/``decode`` from
  the serving replica's batcher spans, and an explicit
  ``unattributed`` residual — segments always sum to the
  client-observed elapsed, never to a story.
- **export**: ``e2e_latency_seconds{segment=}`` histograms per stitched
  request, ``e2e_traces_total`` / ``e2e_missing_spans_total`` counters
  (a process that died mid-request leaves a flagged, counted hole),
  ``e2e_scrape_failures_total{process=}`` for scrape liveness, and the
  skew gauges above.  ``/debug/waterfall`` (utils/obs.py) serves the
  snapshot as sort_keys JSON — byte-identical across two FakeClock
  runs over the same captured rings — and ``chrome()`` emits the
  multi-process Perfetto export (utils/profiler.chrome_trace
  ``by_process`` form: one named pid per process, shared timeline).
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict

from .clock import Clock, RealClock
from .metrics import MetricsRegistry, global_metrics

# The exhaustive E2E partition, in claim-priority order: an earlier
# segment wins overlapping time (batcher spans legitimately overlap —
# a fused prefill covers the first decode round), and ``unattributed``
# is the residual that makes the sum exact.
SEGMENTS = (
    "kv_handover",
    "gateway_route",
    "retry_hop",
    "network_gap",
    "queue_wait",
    "prefill",
    "decode",
    "unattributed",
)

_SERVE_SEGMENTS = (
    ("queue_wait", "serve.queue_wait"),
    ("prefill", "serve.prefill"),
    ("decode", "serve.round"),
)


def _claim(covered: list, lo: float, hi: float) -> float:
    """Claim ``[lo, hi)`` minus already-covered time: returns the
    seconds gained and folds the gained pieces into ``covered`` (a
    sorted list of disjoint intervals) — the sweep primitive that makes
    the partition exhaustive and double-count-free."""
    if hi <= lo:
        return 0.0
    pieces = []
    cur = lo
    for c0, c1 in covered:
        if c1 <= cur:
            continue
        if c0 >= hi:
            break
        if c0 > cur:
            pieces.append((cur, min(c0, hi)))
        cur = max(cur, c1)
        if cur >= hi:
            break
    if cur < hi:
        pieces.append((cur, hi))
    if not pieces:
        return 0.0
    covered.extend(pieces)
    covered.sort()
    merged = []
    for c0, c1 in covered:
        if merged and c0 <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], c1))
        else:
            merged.append((c0, c1))
    covered[:] = merged
    return sum(p1 - p0 for p0, p1 in pieces)


def _flatten_tree(trace: dict) -> list[dict]:
    """One assembled trace (the ``/debug/traces`` shape) → flat span
    dicts, children stripped — the stitcher re-parents across
    processes, so source nesting is only a transport detail."""
    out = []
    stack = list(trace.get("tree") or [])
    while stack:
        node = stack.pop()
        out.append({k: v for k, v in node.items() if k != "children"})
        stack.extend(node.get("children") or [])
    return out


def split_by_process(
    traces: list[dict],
    gateway_label: str = "fleet-frontend",
    gateway_name: str = "gateway",
) -> dict[str, list[dict]]:
    """Split assembled traces from ONE shared in-process tracer ring
    into the per-process fragments separate rings would hold — the
    test/demo harness for real stitching without real processes.

    Gateway spans are those labeled ``server=<gateway_label>`` plus
    every ``gateway.dispatch``/``gateway.handover``; a span parented
    to a dispatch span
    belongs to that dispatch's ``replica``; everything else inherits
    its parent's process.  The replica fragment's server span keeps its
    (now unresolved) ``parent_id`` — exactly what a real per-process
    ring ships."""
    by_proc: dict[str, dict[str, list[dict]]] = {}
    for tr in traces:
        tid = str(tr.get("trace_id") or "")
        spans = sorted(
            _flatten_tree(tr), key=lambda s: str(s.get("span_id"))
        )
        byid = {str(s.get("span_id")): s for s in spans}
        proc: dict[str, str] = {}
        for s in spans:
            attrs = s.get("attributes") or {}
            if (
                s.get("name") in ("gateway.dispatch",
                                  "gateway.handover")
                or attrs.get("server") == gateway_label
            ):
                proc[str(s.get("span_id"))] = gateway_name
        changed = True
        while changed:
            changed = False
            for s in spans:
                sid = str(s.get("span_id"))
                if sid in proc:
                    continue
                parent = byid.get(str(s.get("parent_id") or ""))
                if parent is None:
                    continue
                psid = str(parent.get("span_id"))
                if psid not in proc:
                    continue
                if parent.get("name") == "gateway.dispatch":
                    rep = (parent.get("attributes") or {}).get("replica")
                    proc[sid] = str(rep) if rep else proc[psid]
                else:
                    proc[sid] = proc[psid]
                changed = True
        for s in spans:
            p = proc.get(str(s.get("span_id")), gateway_name)
            by_proc.setdefault(p, {}).setdefault(tid, []).append(s)
    out: dict[str, list[dict]] = {}
    for p in sorted(by_proc):
        frags = []
        for tid in sorted(by_proc[p]):
            sps = sorted(
                by_proc[p][tid],
                key=lambda s: (
                    float(s.get("start", 0.0)), str(s.get("span_id"))
                ),
            )
            frags.append({
                "trace_id": tid,
                "span_count": len(sps),
                "tree": [dict(s) for s in sps],
            })
        out[p] = frags
    return out


class FleetTraceAssembler:
    """Scrapes per-process span rings into stitched per-request
    waterfalls (see module docstring for the model)."""

    # Lock contract (graftcheck lockcheck + utils.faults
    # guard_declared): the span store and scrape bookkeeping are shared
    # between a periodic scrape thread and /debug/waterfall HTTP
    # handlers.  ``_scrape_lock`` serializes whole passes (ordering,
    # not state), the same split utils/federation.py uses.
    _GUARDED_BY = {
        "_lock": (
            "_targets", "_cursors", "_spans", "_journal", "_exported",
            "_scrapes", "_last_scrape",
        ),
    }

    def __init__(
        self,
        targets: dict | None = None,
        registry: MetricsRegistry | None = None,
        clock: Clock | None = None,
        timeout: float = 5.0,
        max_traces: int = 256,
        scrape_limit: int = 200,
    ):
        self.registry = registry or global_metrics
        self.clock = clock or RealClock()
        self.timeout = float(timeout)
        self.max_traces = max(1, int(max_traces))
        self.scrape_limit = max(1, int(scrape_limit))
        self._lock = threading.Lock()
        self._scrape_lock = threading.Lock()
        self._targets: dict[str, object] = {}
        self._cursors: dict[str, int] = {}
        # trace_id → span_id → (process, span dict); insertion-ordered
        # for FIFO eviction, exactly like the tracer's own ring.
        self._spans: "OrderedDict[str, dict]" = OrderedDict()
        # trace_id → process → journal record (bounded by _spans: only
        # traces we hold spans for keep journal context).
        self._journal: dict[str, dict] = {}
        self._exported: set[str] = set()
        self._scrapes = 0
        self._last_scrape: float | None = None
        for name, target in (targets or {}).items():
            self.add_target(name, target)

    # -- target management -------------------------------------------------
    def add_target(self, name: str, target) -> None:
        with self._lock:
            self._targets[str(name)] = target
            self._cursors.setdefault(str(name), 0)

    def remove_target(self, name: str) -> None:
        with self._lock:
            self._targets.pop(name, None)
            self._cursors.pop(name, None)

    def process_names(self) -> list[str]:
        with self._lock:
            return sorted(self._targets)

    @property
    def never_scraped(self) -> bool:
        with self._lock:
            return self._scrapes == 0

    # -- scraping ----------------------------------------------------------
    def _fetch(self, target, cursor: int):
        """One target → (traces, new_cursor_or_None, journal_records).
        Callables return the ``/debug/traces`` JSON shape (dict or
        text) and may carry ``requests`` inline; URLs fetch both
        endpoints, journal optional."""
        if callable(target):
            raw = target()
            if isinstance(raw, (str, bytes)):
                raw = json.loads(raw)
            if isinstance(raw, list):
                return raw, None, []
            return (
                raw.get("traces") or [],
                raw.get("cursor"),
                raw.get("requests") or [],
            )
        import urllib.request

        base = str(target).rstrip("/")
        url = (
            f"{base}/debug/traces?since={int(cursor)}"
            f"&limit={self.scrape_limit}"
        )
        with urllib.request.urlopen(url, timeout=self.timeout) as r:
            body = json.loads(r.read().decode())
        records: list = []
        try:
            req_url = f"{base}/debug/requests?limit={self.scrape_limit}"
            with urllib.request.urlopen(
                req_url, timeout=self.timeout
            ) as r:
                records = json.loads(r.read().decode()).get(
                    "requests"
                ) or []
        except Exception:
            records = []  # no journal on this target — context, not data
        return body.get("traces") or [], body.get("cursor"), records

    def scrape_once(self) -> dict[str, bool]:
        """One assembly pass over every target (sorted order —
        deterministic); returns ``{process: scraped_ok}``.  Concurrent
        calls serialize, never interleave."""
        with self._scrape_lock:
            return self._scrape_once_locked()

    def _scrape_once_locked(self) -> dict[str, bool]:
        with self._lock:
            targets = sorted(self._targets.items())
            cursors = dict(self._cursors)
        up: dict[str, bool] = {}
        for name, target in targets:
            try:
                traces, cursor, records = self._fetch(
                    target, cursors.get(name, 0)
                )
            except Exception:
                self.registry.inc(
                    "e2e_scrape_failures_total", process=name
                )
                up[name] = False
                continue
            self._ingest(name, traces, records)
            if cursor is not None:
                with self._lock:
                    self._cursors[name] = int(cursor)
            up[name] = True
        self._export()
        with self._lock:
            self._scrapes += 1
            self._last_scrape = self.clock.now()
        return up

    def _ingest(self, process: str, traces: list, records: list) -> None:
        with self._lock:
            for tr in traces:
                tid = str(tr.get("trace_id") or "")
                if not tid:
                    continue
                bucket = self._spans.get(tid)
                if bucket is None:
                    while len(self._spans) >= self.max_traces:
                        old, _ = self._spans.popitem(last=False)
                        self._journal.pop(old, None)
                        self._exported.discard(old)
                    bucket = {}
                    self._spans[tid] = bucket
                for sp in _flatten_tree(tr):
                    sid = str(sp.get("span_id") or "")
                    if sid:
                        bucket[sid] = (process, sp)
            for rec in records:
                tid = rec.get("trace_id")
                if tid and tid in self._spans:
                    self._journal.setdefault(tid, {}).setdefault(
                        process, dict(rec)
                    )

    def _export(self) -> None:
        """Metrics for traces newly complete (stitched gateway root):
        exactly once per trace, after the whole pass — the gateway and
        its replicas land in the same pass, so a one-pass scrape sees
        the full request."""
        with self._lock:
            pending = [
                (tid, dict(bucket))
                for tid, bucket in self._spans.items()
                if tid not in self._exported
            ]
        offsets: dict[str, float] = {}
        for tid, members in pending:
            wf = self._stitch(tid, members)
            if not wf.get("stitched"):
                continue
            with self._lock:
                self._exported.add(tid)
            for seg in SEGMENTS:
                self.registry.observe(
                    "e2e_latency_seconds",
                    wf["segments"][seg]["seconds"], segment=seg,
                )
            self.registry.inc("e2e_traces_total")
            if wf["missing_spans"]:
                self.registry.inc("e2e_missing_spans_total")
            for proc in sorted(wf["processes"]):
                offsets[proc] = wf["processes"][proc]["offset_s"]
        for proc in sorted(offsets):
            self.registry.set_gauge(
                "e2e_clock_skew_seconds", offsets[proc], process=proc
            )

    # -- stitching ---------------------------------------------------------
    def _stitch(self, trace_id: str, members: dict) -> dict:
        """One trace's scraped spans → the stitched waterfall dict.
        Pure over its inputs: identical members produce byte-identical
        sort_keys JSON — the two-run contract /debug/waterfall pins."""
        spans: dict[str, dict] = {}
        proc_of: dict[str, str] = {}
        for sid, (proc, sp) in members.items():
            spans[sid] = sp
            proc_of[sid] = proc

        def t0(s):
            return float(s.get("start", 0.0))

        def t1(s):
            return t0(s) + float(s.get("duration_ms", 0.0)) / 1000.0

        children: dict[str, list[str]] = {}
        for sid in sorted(spans):
            pid = spans[sid].get("parent_id")
            if pid:
                children.setdefault(str(pid), []).append(sid)

        dispatch = sorted(
            (s for s in spans.values()
             if s.get("name") == "gateway.dispatch"),
            key=lambda s: (
                int((s.get("attributes") or {}).get("attempt", 0) or 0),
                t0(s), str(s.get("span_id")),
            ),
        )
        root = None
        if dispatch:
            root = spans.get(str(dispatch[0].get("parent_id") or ""))
        if root is None:
            cands = sorted(
                (s for s in spans.values()
                 if str(s.get("name", "")).startswith("http ")
                 and str(s.get("parent_id") or "") not in spans),
                key=lambda s: (t0(s), str(s.get("span_id"))),
            )
            root = cands[0] if cands else None
        gw_proc = None
        if dispatch:
            gw_proc = proc_of[str(dispatch[0]["span_id"])]
        elif root is not None:
            gw_proc = proc_of[str(root["span_id"])]

        # -- clock alignment: pin each child server span inside its
        # enclosing dispatch span (midpoint difference, averaged).
        server_of: dict[str, dict] = {}
        pair_deltas: dict[str, list[float]] = {}
        for d in dispatch:
            kids = sorted(
                (spans[k] for k in children.get(str(d["span_id"]), [])
                 if str(spans[k].get("name", "")).startswith("http ")),
                key=lambda s: (t0(s), str(s.get("span_id"))),
            )
            if not kids:
                continue
            s = kids[0]
            server_of[str(d["span_id"])] = s
            d_mid = (t0(d) + t1(d)) / 2.0
            s_mid = (t0(s) + t1(s)) / 2.0
            pair_deltas.setdefault(
                proc_of[str(s["span_id"])], []
            ).append(d_mid - s_mid)

        offsets: dict[str, float] = {}
        processes: dict[str, dict] = {}
        for p in sorted(set(proc_of.values())):
            deltas = pair_deltas.get(p, [])
            if p == gw_proc:
                off, pairs, aligned = 0.0, 0, True
            else:
                off = sum(deltas) / len(deltas) if deltas else 0.0
                pairs, aligned = len(deltas), bool(deltas)
            offsets[p] = off
            processes[p] = {
                "offset_s": round(off, 9),
                "pairs": pairs,
                "aligned": aligned,
            }

        def a0(s):
            return t0(s) + offsets.get(proc_of[str(s["span_id"])], 0.0)

        def a1(s):
            return t1(s) + offsets.get(proc_of[str(s["span_id"])], 0.0)

        stitched = bool(root is not None and dispatch)
        unaligned = any(
            not info["aligned"] for info in processes.values()
        )

        # -- stitched, aligned tree (cross-process parents resolve) ----
        rel = t0(root) if root is not None else min(
            (a0(s) for s in spans.values()), default=0.0
        )
        nodes: dict[str, dict] = {}
        for sid in sorted(spans):
            sp = spans[sid]
            nodes[sid] = {
                "name": sp.get("name"),
                "process": proc_of[sid],
                "span_id": sid,
                "parent_id": sp.get("parent_id"),
                "start_s": round(a0(sp) - rel, 9),
                "duration_ms": round((t1(sp) - t0(sp)) * 1000.0, 6),
                "status": sp.get("status", "ok"),
                "attributes": dict(sp.get("attributes") or {}),
                "children": [],
            }
        roots: list[dict] = []
        for sid in sorted(
            nodes, key=lambda x: (nodes[x]["start_s"], x)
        ):
            n = nodes[sid]
            parent = nodes.get(str(n["parent_id"] or ""))
            (parent["children"] if parent is not None
             else roots).append(n)

        wf: dict = {
            "trace_id": trace_id,
            "stitched": stitched,
            "span_count": len(spans),
            "missing_spans": (not stitched) or unaligned,
            "processes": processes,
            "tree": roots,
        }
        if not stitched:
            return wf

        # -- critical-path partition (priority interval sweep) ---------
        R0, R1 = t0(root), t1(root)
        e2e = max(0.0, R1 - R0)
        serving = None
        for d in reversed(dispatch):
            outcome = (d.get("attributes") or {}).get("outcome")
            if outcome in ("ok", "stream"):
                serving = d
                break
        if serving is None:
            serving = dispatch[-1]

        claims: list[tuple[str, float, float]] = []
        # kv_handover claims FIRST: the disagg handover runs inside
        # the pre-dispatch window whose whole span gateway_route
        # claims next — claim-list order is claim priority, so the
        # handover span must win its interval or it vanishes into
        # gateway_route.
        for h in sorted(
            (s for s in spans.values()
             if s.get("name") == "gateway.handover"),
            key=lambda s: (t0(s), str(s.get("span_id"))),
        ):
            claims.append(("kv_handover", a0(h), a1(h)))
        claims.append(("gateway_route", R0, a0(dispatch[0])))
        for i, d in enumerate(dispatch):
            if d is serving:
                continue
            hop_hi = (
                a0(dispatch[i + 1]) if i + 1 < len(dispatch) else a1(d)
            )
            claims.append(("retry_hop", a0(d), hop_hi))
        net = {"request_s": 0.0, "response_s": 0.0}
        srv_proc = None
        srv = server_of.get(str(serving["span_id"]))
        if srv is not None:
            srv_proc = proc_of[str(srv["span_id"])]
            d0, d1 = a0(serving), a1(serving)
            s0, s1 = a0(srv), a1(srv)
            claims.append(("network_gap", d0, min(s0, d1)))
            claims.append(("network_gap", max(s1, d0), d1))
            net["request_s"] = round(max(0.0, min(s0, d1) - d0), 9)
            net["response_s"] = round(max(0.0, d1 - max(s1, d0)), 9)
        if srv_proc is not None:
            for seg, name in _SERVE_SEGMENTS:
                for sid in sorted(spans):
                    sp = spans[sid]
                    if (
                        sp.get("name") == name
                        and proc_of[sid] == srv_proc
                    ):
                        claims.append((seg, a0(sp), a1(sp)))

        def sweep(hi_bound: float):
            covered: list = []
            segs = {seg: 0.0 for seg in SEGMENTS}
            span_total = max(0.0, hi_bound - R0)
            for seg, lo, hi in claims:
                segs[seg] += _claim(
                    covered, max(lo, R0), min(hi, hi_bound)
                )
            covered_total = sum(c1 - c0 for c0, c1 in covered)
            segs["unattributed"] = max(0.0, span_total - covered_total)
            return segs, span_total

        segs, _ = sweep(R1)
        segments = {
            seg: {
                "seconds": round(segs[seg], 9),
                "share": (
                    round(segs[seg] / e2e, 6) if e2e > 0 else 0.0
                ),
            }
            for seg in SEGMENTS
        }
        critical = max(SEGMENTS, key=lambda s: segs[s])

        ttft = None
        ttft_segments = None
        if srv_proc is not None:
            ends = sorted(
                a1(spans[sid]) for sid in sorted(spans)
                if spans[sid].get("name") == "serve.prefill"
                and proc_of[sid] == srv_proc
            )
            if ends:
                ttft_end = min(max(R0, ends[0]), R1)
                tsegs, tspan = sweep(ttft_end)
                ttft = round(tspan, 9)
                ttft_segments = {
                    seg: round(tsegs[seg], 9) for seg in SEGMENTS
                }

        attempts = []
        for i, d in enumerate(dispatch):
            attrs = d.get("attributes") or {}
            attempts.append({
                "attempt": int(attrs.get("attempt", i + 1) or (i + 1)),
                "replica": str(attrs.get("replica", "?")),
                "outcome": str(attrs.get("outcome", "?")),
                "status": d.get("status", "ok"),
                "start_s": round(a0(d) - R0, 9),
                "end_s": round(a1(d) - R0, 9),
                "server_span": str(d["span_id"]) in server_of,
            })
        served_ok = (
            (serving.get("attributes") or {}).get("outcome")
            in ("ok", "stream")
        )
        wf["missing_spans"] = unaligned or (served_ok and srv is None)
        wf.update({
            "e2e_s": round(e2e, 9),
            "ttft_s": ttft,
            "segments": segments,
            "ttft_segments": ttft_segments,
            "critical": critical,
            "network": net,
            "attempts": attempts,
        })
        return wf

    # -- read surface ------------------------------------------------------
    def waterfall(self, trace_id: str) -> dict | None:
        """The full stitched waterfall for one trace (None if the
        assembler holds no spans for it), journal context attached when
        a scraped ``/debug/requests`` record matched."""
        with self._lock:
            members = dict(self._spans.get(trace_id) or {})
            journal = {
                p: dict(r)
                for p, r in (self._journal.get(trace_id) or {}).items()
            }
        if not members:
            return None
        wf = self._stitch(trace_id, members)
        if journal:
            wf["journal"] = journal
        return wf

    def snapshot(self, limit: int = 50) -> dict:
        """The ``/debug/waterfall`` listing: stitched request traces,
        most recent first — per-trace E2E/TTFT, the starred critical
        segment, attempt count, and the missing-span flag."""
        with self._lock:
            tids = list(self._spans)
            scrapes = self._scrapes
        out = []
        for tid in reversed(tids):
            wf = self.waterfall(tid)
            if wf is None or not wf["stitched"]:
                continue
            out.append({
                "trace_id": tid,
                "e2e_s": wf["e2e_s"],
                "ttft_s": wf["ttft_s"],
                "critical": wf["critical"],
                "attempts": len(wf["attempts"]),
                "missing_spans": wf["missing_spans"],
            })
            if len(out) >= max(1, int(limit)):
                break
        return {"scrapes": scrapes, "traces": out}

    def chrome(self, trace_id: str) -> dict | None:
        """Multi-process Perfetto export of one stitched trace: the
        aligned tree regrouped into per-process fragments, handed to
        ``profiler.chrome_trace(by_process=...)`` — gateway and every
        replica render as named processes on one shared timeline."""
        wf = self.waterfall(trace_id)
        if wf is None:
            return None
        from .profiler import chrome_trace

        frags: dict[str, list[dict]] = {}

        def walk(node: dict, parent_conv, parent_proc) -> None:
            proc = node["process"]
            conv = {
                "name": node["name"],
                "start": node["start_s"],
                "duration_ms": node["duration_ms"],
                "attributes": node["attributes"],
                "status": node["status"],
                "children": [],
            }
            if parent_conv is not None and proc == parent_proc:
                parent_conv["children"].append(conv)
            else:
                frags.setdefault(proc, []).append(conv)
            for child in node["children"]:
                walk(child, conv, proc)

        for r in wf["tree"]:
            walk(r, None, None)
        by_process = {
            p: [{"trace_id": trace_id, "tree": frags[p]}]
            for p in sorted(frags)
        }
        return chrome_trace(by_process=by_process)
