"""Deterministic fault-injection harness — named sites, seeded plans.

The fakes already script per-verb failure counters (ScriptedFaultPlan in
cloud/fake_azure.py, TpuFaultPlan in cloud/fake_cloudtpu.py); that covers
"the Nth create fails" but not the chaos question the ROADMAP north-star
poses: does the whole control plane *converge* when 30% of everything
fails, and does the serving plane degrade instead of hanging?  This module
is the second, orthogonal layer: **injection sites** are named choke
points compiled into production code paths (cloud transport, fake cloud
verbs, workqueue enqueue, reconcile dispatch, serve admission), and a
test/demo *arms* a site with a seeded ``FaultPlan``.  Disarmed sites cost
one dict lookup — the default state everywhere outside a chaos run.

Determinism is the design constraint (the chaos suite must replay
identically under the tier-1 budget): every plan decision comes from a
``random.Random(seed)`` private to the armed site, so a given
(seed, call-sequence) pair always injects the same schedule.  Fault kinds:

- ``error``   — raise the site's error type (CloudError at cloud sites);
- ``timeout`` — raise the same type with a timeout-flavored message (the
  shape a hung-then-expired transport produces);
- ``slow``    — delay: sites with a Clock sleep in *clock* domain, sites
  that schedule (workqueue) fold the returned delay into their deadline;
- flaky-N-then-succeed — ``FaultPlan(flaky=N)``: the first N calls fail,
  then the site heals (the retry-policy acceptance shape).

Every injection counts in ``faults_injected_total{site,kind}`` so a chaos
run can prove faults actually fired (a green run with zero injections is
a broken harness, not a robust system).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass

from .metrics import MetricsRegistry, global_metrics


class InjectedFault(Exception):
    """Default error raised at a site armed with an ``error``/``timeout``
    plan; sites with a domain failure type (CloudError, RuntimeError)
    pass it via ``fire(error_type=...)`` so injected faults travel the
    exact handling path a real one would."""


@dataclass
class FaultPlan:
    """One site's seeded schedule.

    ``rate``/``kinds``/``seed`` drive the PRNG schedule: each call draws
    once; under ``rate`` it injects a kind drawn from ``kinds``.
    ``flaky=N`` overrides the PRNG: the first N calls inject
    ``kinds[0]``, every later call passes (deterministic heal).
    ``limit`` caps total injections regardless of mode; ``slow_s`` is the
    delay a ``slow`` decision carries.
    """

    seed: int = 0
    rate: float = 1.0
    kinds: tuple = ("error",)
    slow_s: float = 0.05
    flaky: int = 0
    limit: int | None = None


class _ArmedSite:
    __slots__ = ("plan", "rng", "calls", "injected")

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.rng = random.Random(plan.seed)
        self.calls = 0
        self.injected = 0

    def decide(self) -> str | None:
        self.calls += 1
        p = self.plan
        if p.limit is not None and self.injected >= p.limit:
            return None
        if p.flaky > 0:
            kind = p.kinds[0] if self.calls <= p.flaky else None
        else:
            # One draw per call whatever the outcome, so the schedule is a
            # pure function of (seed, call index) — a passing call never
            # shifts a later call's decision.
            u = self.rng.random()
            kind = (
                p.kinds[self.rng.randrange(len(p.kinds))]
                if u < p.rate else None
            )
        if kind is not None:
            self.injected += 1
        return kind


class FaultInjector:
    """Named injection sites; ``global_faults`` is the default wired into
    production code, and chaos harnesses may construct private instances
    (the fakes take ``injector=``) for isolation."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or global_metrics
        self._lock = threading.Lock()
        self._sites: dict[str, _ArmedSite] = {}

    # -- arming ------------------------------------------------------------
    def arm(self, site: str, plan: FaultPlan) -> None:
        with self._lock:
            self._sites[site] = _ArmedSite(plan)

    def disarm(self, site: str | None = None) -> None:
        """Disarm one site, or every site when ``site`` is None."""
        with self._lock:
            if site is None:
                self._sites.clear()
            else:
                self._sites.pop(site, None)

    # -- the choke point ---------------------------------------------------
    def fire(
        self,
        site: str,
        error_type: type = InjectedFault,
        clock=None,
        only: tuple | None = None,
    ) -> float:
        """Called by production code at injection site *site*.

        Disarmed → returns 0.0 (the fast path).  An armed decision either
        raises ``error_type`` (kinds ``error``/``timeout``) or handles
        ``slow``: with a ``clock`` the delay is slept here (clock
        domain); without one it is RETURNED for the caller to fold into
        its own scheduling.  ``only`` restricts which kinds this site
        honors — the workqueue site passes ``("slow",)`` because an
        injected error there would *lose an event*, which no real fault
        mode produces.
        """
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                return 0.0
            kind = st.decide()
            if kind is not None and only is not None and kind not in only:
                st.injected -= 1
                kind = None
            if kind is None:
                return 0.0
            slow_s = st.plan.slow_s
            n = st.injected
        self.registry.inc("faults_injected_total", site=site, kind=kind)
        if kind == "slow":
            if clock is not None:
                clock.sleep(slow_s)
                return 0.0
            return slow_s
        flavor = "timeout" if kind == "timeout" else "fault"
        raise error_type(f"injected {flavor} at {site} (#{n})")

    # -- introspection -----------------------------------------------------
    def injected(self, site: str) -> int:
        with self._lock:
            st = self._sites.get(site)
            return st.injected if st else 0

    def calls(self, site: str) -> int:
        with self._lock:
            st = self._sites.get(site)
            return st.calls if st else 0

    def sites(self) -> dict:
        """site → {calls, injected} for every armed site (chaos-demo
        reporting surface)."""
        with self._lock:
            return {
                name: {"calls": st.calls, "injected": st.injected}
                for name, st in self._sites.items()
            }


global_faults = FaultInjector()
