"""Deterministic fault-injection harness — named sites, seeded plans.

The fakes already script per-verb failure counters (ScriptedFaultPlan in
cloud/fake_azure.py, TpuFaultPlan in cloud/fake_cloudtpu.py); that covers
"the Nth create fails" but not the chaos question the ROADMAP north-star
poses: does the whole control plane *converge* when 30% of everything
fails, and does the serving plane degrade instead of hanging?  This module
is the second, orthogonal layer: **injection sites** are named choke
points compiled into production code paths (cloud transport, fake cloud
verbs, workqueue enqueue, reconcile dispatch, serve admission, the
gateway's replica scrapes and peer digest checks — ``gateway.scrape`` /
``gateway.peer`` in serve/frontend.py), and a
test/demo *arms* a site with a seeded ``FaultPlan``.  Disarmed sites cost
one dict lookup — the default state everywhere outside a chaos run.

Determinism is the design constraint (the chaos suite must replay
identically under the tier-1 budget): every plan decision comes from a
``random.Random(seed)`` private to the armed site, so a given
(seed, call-sequence) pair always injects the same schedule.  Fault kinds:

- ``error``   — raise the site's error type (CloudError at cloud sites);
- ``timeout`` — raise the same type with a timeout-flavored message (the
  shape a hung-then-expired transport produces);
- ``slow``    — delay: sites with a Clock sleep in *clock* domain, sites
  that schedule (workqueue) fold the returned delay into their deadline;
- flaky-N-then-succeed — ``FaultPlan(flaky=N)``: the first N calls fail,
  then the site heals (the retry-policy acceptance shape).

Every injection counts in ``faults_injected_total{site,kind}`` so a chaos
run can prove faults actually fired (a green run with zero injections is
a broken harness, not a robust system).

This module also hosts the RUNTIME half of the lock-discipline checker
(the static half is ``k8s_gpu_tpu/analysis`` pass 3): an
``InstrumentedLock`` that records its owner threads, and
``guard_object``/``guard_declared`` which rebind an instance's class so
every access to a *guarded field* asserts the declared lock is held by
the accessing thread.  Violations are RECORDED, not raised — a race
detector that kills the first worker thread it disagrees with would
hide every later violation and wedge the stress harness; the test
asserts the violation list is empty (or, for the seeded-race case,
isn't).  ``_GUARDED_BY`` on the batcher / router / federation /
registry classes is the single source of truth both halves enforce.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass

from .metrics import MetricsRegistry, global_metrics


class InjectedFault(Exception):
    """Default error raised at a site armed with an ``error``/``timeout``
    plan; sites with a domain failure type (CloudError, RuntimeError)
    pass it via ``fire(error_type=...)`` so injected faults travel the
    exact handling path a real one would."""


@dataclass
class FaultPlan:
    """One site's seeded schedule.

    ``rate``/``kinds``/``seed`` drive the PRNG schedule: each call draws
    once; under ``rate`` it injects a kind drawn from ``kinds``.
    ``flaky=N`` overrides the PRNG: the first N calls inject
    ``kinds[0]``, every later call passes (deterministic heal).
    ``limit`` caps total injections regardless of mode; ``slow_s`` is the
    delay a ``slow`` decision carries.
    """

    seed: int = 0
    rate: float = 1.0
    kinds: tuple = ("error",)
    slow_s: float = 0.05
    flaky: int = 0
    limit: int | None = None


class _ArmedSite:
    __slots__ = ("plan", "rng", "calls", "injected")

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.rng = random.Random(plan.seed)
        self.calls = 0
        self.injected = 0

    def decide(self) -> str | None:
        self.calls += 1
        p = self.plan
        if p.limit is not None and self.injected >= p.limit:
            return None
        if p.flaky > 0:
            kind = p.kinds[0] if self.calls <= p.flaky else None
        else:
            # One draw per call whatever the outcome, so the schedule is a
            # pure function of (seed, call index) — a passing call never
            # shifts a later call's decision.
            u = self.rng.random()
            kind = (
                p.kinds[self.rng.randrange(len(p.kinds))]
                if u < p.rate else None
            )
        if kind is not None:
            self.injected += 1
        return kind


class FaultInjector:
    """Named injection sites; ``global_faults`` is the default wired into
    production code, and chaos harnesses may construct private instances
    (the fakes take ``injector=``) for isolation."""

    _GUARDED_BY = {"_lock": ("_sites",)}

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or global_metrics
        self._lock = threading.Lock()
        self._sites: dict[str, _ArmedSite] = {}

    # -- arming ------------------------------------------------------------
    def arm(self, site: str, plan: FaultPlan) -> None:
        with self._lock:
            self._sites[site] = _ArmedSite(plan)

    def disarm(self, site: str | None = None) -> None:
        """Disarm one site, or every site when ``site`` is None."""
        with self._lock:
            if site is None:
                self._sites.clear()
            else:
                self._sites.pop(site, None)

    # -- the choke point ---------------------------------------------------
    def fire(
        self,
        site: str,
        error_type: type = InjectedFault,
        clock=None,
        only: tuple | None = None,
    ) -> float:
        """Called by production code at injection site *site*.

        Disarmed → returns 0.0 (the fast path).  An armed decision either
        raises ``error_type`` (kinds ``error``/``timeout``) or handles
        ``slow``: with a ``clock`` the delay is slept here (clock
        domain); without one it is RETURNED for the caller to fold into
        its own scheduling.  ``only`` restricts which kinds this site
        honors — the workqueue site passes ``("slow",)`` because an
        injected error there would *lose an event*, which no real fault
        mode produces.
        """
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                return 0.0
            kind = st.decide()
            if kind is not None and only is not None and kind not in only:
                st.injected -= 1
                kind = None
            if kind is None:
                return 0.0
            slow_s = st.plan.slow_s
            n = st.injected
        self.registry.inc("faults_injected_total", site=site, kind=kind)
        if kind == "slow":
            if clock is not None:
                clock.sleep(slow_s)
                return 0.0
            return slow_s
        flavor = "timeout" if kind == "timeout" else "fault"
        raise error_type(f"injected {flavor} at {site} (#{n})")

    # -- introspection -----------------------------------------------------
    def injected(self, site: str) -> int:
        with self._lock:
            st = self._sites.get(site)
            return st.injected if st else 0

    def calls(self, site: str) -> int:
        with self._lock:
            st = self._sites.get(site)
            return st.calls if st else 0

    def sites(self) -> dict:
        """site → {calls, injected} for every armed site (chaos-demo
        reporting surface)."""
        with self._lock:
            return {
                name: {"calls": st.calls, "injected": st.injected}
                for name, st in self._sites.items()
            }


global_faults = FaultInjector()


# -- runtime lock-discipline checker ------------------------------------------

@dataclass(frozen=True)
class LockViolation:
    """One guarded-field access that did not hold its lock.

    ``mode`` is "write" for an attribute rebind (``__setattr__``) and
    "access" for everything ``__getattribute__`` sees — which includes
    container mutations (``self._chains[k] = v`` reaches the guard as
    a Load of ``_chains``), so "access" must not be read as
    read-only."""

    cls: str
    field: str
    mode: str      # "access" (read or container mutation) | "write"
    lock: str
    thread: str

    def __str__(self) -> str:
        return (
            f"{self.cls}.{self.field} {self.mode} by thread "
            f"{self.thread!r} without holding {self.lock}"
        )


class InstrumentedLock:
    """Wraps a ``threading.Lock``/``RLock``, tracking per-thread hold
    counts so ``held_by_me`` answers "does MY thread hold this lock" —
    the question the guarded-field check asks.  Re-entrant holds count
    (an RLock-wrapped instance nests correctly); the bookkeeping dict
    is only ever mutated by the thread that just acquired/released, and
    entries are removed at zero so it stays bounded by live holders."""

    def __init__(self, inner=None):
        self._inner = inner if inner is not None else threading.Lock()
        self._holds: dict[int, int] = {}

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            me = threading.get_ident()
            self._holds[me] = self._holds.get(me, 0) + 1
        return ok

    def release(self) -> None:
        me = threading.get_ident()
        n = self._holds.get(me, 0)
        if n > 0:
            if n == 1:
                self._holds.pop(me, None)
            else:
                self._holds[me] = n - 1
            self._inner.release()
            return
        # Cross-thread handoff: a plain Lock may legally be released by
        # a thread that never acquired it — the ACQUIRER's hold ends
        # here, so its entry must not linger (a stale entry would make
        # held_by_me lie True for it forever, silently disabling the
        # detector).  Snapshot before releasing: an RLock's release
        # raises for a non-owner, leaving bookkeeping untouched.
        holders = list(self._holds)
        self._inner.release()
        for h in holders:
            self._holds.pop(h, None)

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    @property
    def held_by_me(self) -> bool:
        return self._holds.get(threading.get_ident(), 0) > 0


def guard_object(obj, guards: dict, violations: list | None = None) -> list:
    """Turn *obj* into its own race detector.

    ``guards`` maps lock attribute -> iterable of guarded field names
    (the ``_GUARDED_BY`` shape).  Each named lock is wrapped in an
    ``InstrumentedLock`` and the instance's class is rebound to a
    subclass whose ``__getattribute__``/``__setattr__`` append a
    ``LockViolation`` whenever a guarded field is touched by a thread
    not holding its lock.  Returns the (shared) violations list.

    Install while the object is quiescent (before the hammering starts):
    the lock attribute swap itself is not atomic with respect to a
    thread already blocked on the old lock object.
    """
    violations = violations if violations is not None else []
    base = type(obj)
    field_lock = {
        f: lock for lock, fields in guards.items() for f in fields
    }
    for lock_attr in guards:
        inner = object.__getattribute__(obj, lock_attr)
        if not isinstance(inner, InstrumentedLock):
            object.__setattr__(obj, lock_attr, InstrumentedLock(inner))

    def _check(self, name: str, mode: str) -> None:
        lock_attr = field_lock.get(name)
        if lock_attr is None:
            return
        lk = object.__getattribute__(self, lock_attr)
        if isinstance(lk, InstrumentedLock) and not lk.held_by_me:
            violations.append(LockViolation(
                cls=base.__name__, field=name, mode=mode,
                lock=lock_attr, thread=threading.current_thread().name,
            ))

    class Guarded(base):
        def __getattribute__(self, name):
            if name in field_lock:
                _check(self, name, "access")
            return super().__getattribute__(name)

        def __setattr__(self, name, value):
            if name in field_lock:
                _check(self, name, "write")
            super().__setattr__(name, value)

    Guarded.__name__ = f"Guarded[{base.__name__}]"
    Guarded.__qualname__ = Guarded.__name__
    obj.__class__ = Guarded
    return violations


def guard_declared(obj, violations: list | None = None) -> list:
    """``guard_object`` driven by the class's own ``_GUARDED_BY``
    declaration — the same contract the static lockcheck pass verifies,
    so the stress test and the linter cannot drift apart.  A class
    without a declaration is a no-op (returns the list unchanged)."""
    guards = getattr(type(obj), "_GUARDED_BY", None) or {}
    if violations is None:
        violations = []
    if guards:
        guard_object(obj, guards, violations)
    return violations
