"""First-party span tracer — the *which request was that* half of C32.

Metrics (utils/metrics.py) answer aggregate questions; they cannot say
which reconcile attempts, cloud-API calls, or batcher rounds ONE slow
request spent its time in.  This module is the missing tracing layer
(SURVEY §5.1), dependency-free by design — the platform's zero-egress
environments cannot ship an OTLP exporter, and the graded baseline metric
(reconcile 0→Ready wall-clock) only needs in-process assembly:

- ``Span``: trace_id/span_id/parent_id + name, monotonic start/end,
  attributes, status.  The clock is an injected ``utils.clock.Clock``
  (default ``RealClock``: ``now()`` = ``time.monotonic()``) — the same
  domain as every other Clock consumer, so control-plane spans whose
  boundaries come from the Clock abstraction line up with HTTP spans,
  and a ``FakeClock`` tracer records fully deterministic durations.
- ``Tracer``: thread-local context stack (``span(...)`` nests
  automatically) plus *explicit* propagation (``use(ctx)`` /
  ``add_span(parent=...)``) for crossing thread boundaries — workqueue
  hand-offs and the serve batcher's scheduler thread.
- Completed spans land in a thread-safe **bounded** ring of traces:
  ``max_traces`` buckets, ``max_spans_per_trace`` spans each; a full
  ring evicts the oldest trace, and a full trace keeps its ORIGIN (the
  first spans — the root request and first reconcile) plus a rolling
  window of the most recent spans, dropping the middle — a lifecycle
  trace that requeues forever still shows how it started and what it
  did last, never only its first seconds.  Every eviction/drop counts
  in ``tracing_dropped_total``; ``tracing_spans_total`` counts every
  recorded span.  Overhead is bounded, never unbounded growth.
- W3C ``traceparent`` (https://www.w3.org/TR/trace-context/) carries
  context over the platform's HTTP surfaces: ``parse_traceparent`` on
  inbound requests (utils/obs.py RequestMetricsMixin), and
  ``format_traceparent``/``cloud.wire.trace_headers`` on outbound calls.

Untraced code paths cost one thread-local read per ``current()`` — the
serve decode hot loop only creates spans at round granularity and only
for requests that carried a context in.
"""

from __future__ import annotations

import threading
import uuid
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from .clock import Clock, RealClock
from .metrics import MetricsRegistry, global_metrics

_TRACEPARENT_VERSION = "00"


@dataclass(frozen=True)
class SpanContext:
    """The propagatable identity of a span: what children parent to and
    what ``traceparent`` carries over the wire."""

    trace_id: str  # 32 lowercase hex chars
    span_id: str   # 16 lowercase hex chars


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def format_traceparent(ctx: SpanContext) -> str:
    """W3C trace-context header value (sampled flag always set — this
    tracer has no sampling; the ring bound is the backpressure)."""
    return f"{_TRACEPARENT_VERSION}-{ctx.trace_id}-{ctx.span_id}-01"


_HEX = set("0123456789abcdefABCDEF")


def _is_hex(s: str) -> bool:
    return bool(s) and all(c in _HEX for c in s)


def parse_traceparent(header: str | None) -> SpanContext | None:
    """``traceparent`` → SpanContext, or None for absent/malformed input
    (a bad header must degrade to "start a new trace", never to a 500)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if version == "ff" or len(version) != 2 or not _is_hex(version):
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) or trace_id == "0" * 32:
        return None
    if len(span_id) != 16 or not _is_hex(span_id) or span_id == "0" * 16:
        return None
    return SpanContext(trace_id.lower(), span_id.lower())


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start: float                       # Clock.now() (monotonic) domain
    end: float = 0.0
    ts: float = 0.0                    # wall clock at start (display only)
    attributes: dict = field(default_factory=dict)
    status: str = "ok"

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def duration_ms(self) -> float:
        return max(0.0, (self.end - self.start) * 1000.0)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "duration_ms": round(self.duration_ms, 3),
            "ts": self.ts,
            "attributes": dict(self.attributes),
            "status": self.status,
        }


class _TraceBucket:
    """One trace's spans under the per-trace cap: ``head`` pins the
    trace's origin (first spans), ``tail`` is a rolling window of the
    most recent — a capped long-lived trace never goes dark, it drops
    its middle.  ``last_seq`` is the tracer-global completion index of
    the newest span recorded here — the ``since=`` cursor's unit."""

    __slots__ = ("head", "tail", "_head_cap", "last_seq")

    def __init__(self, head_cap: int, tail_cap: int):
        self.head: list[Span] = []
        self.tail: "deque[Span]" = deque(maxlen=max(0, tail_cap))
        self._head_cap = head_cap
        self.last_seq = 0

    def add(self, sp: Span) -> bool:
        """Record *sp*; returns True when an older span was dropped."""
        if len(self.head) < self._head_cap:
            self.head.append(sp)
            return False
        dropped = (
            self.tail.maxlen == 0
            or len(self.tail) == self.tail.maxlen
        )
        if self.tail.maxlen:
            self.tail.append(sp)
        return dropped

    def spans(self) -> list[Span]:
        return self.head + list(self.tail)


class Tracer:
    """Thread-safe span recorder with a bounded ring of traces."""

    # Lock contract (verified statically by k8s_gpu_tpu/analysis
    # lockcheck and at runtime by utils.faults.guard_declared): the
    # trace ring and its completion counter are shared between every
    # recording thread and the /debug/traces reader.
    _GUARDED_BY = {"_lock": ("_traces", "_seq")}

    def __init__(
        self,
        max_traces: int = 256,
        max_spans_per_trace: int = 512,
        registry: MetricsRegistry | None = None,
        clock: Clock | None = None,
    ):
        self.max_traces = max(1, int(max_traces))
        self.max_spans_per_trace = max(1, int(max_spans_per_trace))
        # Origin pin: enough for the root request plus its first
        # reconcile pass; everything else rolls.
        self._head_cap = max(1, min(16, self.max_spans_per_trace // 2))
        self.registry = registry or global_metrics
        self.clock = clock or RealClock()
        self._lock = threading.Lock()
        # trace_id → bucket, insertion-ordered for FIFO eviction.
        self._traces: "OrderedDict[str, _TraceBucket]" = OrderedDict()
        # Monotonic completion index: +1 per recorded span, never reset
        # by eviction — the ``/debug/traces?since=`` cursor a periodic
        # scraper (utils/waterfall.py) resumes from, so each pass ships
        # only traces that gained spans since the last one.
        self._seq = 0
        self._tls = threading.local()

    # -- context -----------------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def current(self) -> SpanContext | None:
        """The active context on THIS thread (or None)."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    @contextmanager
    def use(self, ctx: SpanContext | None):
        """Attach an explicitly-propagated context as this thread's
        current one (no span is recorded).  ``use(None)`` is a no-op, so
        call sites don't need to branch."""
        if ctx is None:
            yield
            return
        stack = self._stack()
        stack.append(ctx)
        try:
            yield
        finally:
            stack.pop()

    @contextmanager
    def span(self, name: str, /, parent: SpanContext | None = None,
             **attributes):
        """Open a span: child of ``parent`` (or of the thread's current
        context, or a new trace root), active for the duration of the
        block.  Exceptions mark status=error and re-raise."""
        parent = parent or self.current()
        sp = Span(
            name=name,
            trace_id=parent.trace_id if parent else new_trace_id(),
            span_id=new_span_id(),
            parent_id=parent.span_id if parent else None,
            start=self.clock.now(),
            ts=self.clock.wall(),
            attributes=dict(attributes),
        )
        stack = self._stack()
        stack.append(sp.context)
        try:
            yield sp
        except BaseException as e:
            sp.status = "error"
            sp.attributes.setdefault("error", repr(e))
            raise
        finally:
            stack.pop()
            sp.end = self.clock.now()
            self._record(sp)

    def add_span(
        self,
        name: str,
        /,
        parent: SpanContext | None = None,
        start: float | None = None,
        end: float | None = None,
        status: str = "ok",
        span_id: str | None = None,
        **attributes,
    ) -> SpanContext:
        """Record an already-completed span with explicit boundaries —
        the cross-thread API (queue waits, batcher rounds) where the
        span's lifetime does not match any ``with`` block.  Returns its
        context so further children can chain.  ``span_id`` lets a
        caller pre-mint the identity (``new_span_id()``) and propagate
        it downstream BEFORE the span completes — the gateway's
        per-attempt dispatch span does this so the replica's server
        span parents to the ATTEMPT, not the whole request."""
        now = self.clock.now()
        sp = Span(
            name=name,
            trace_id=parent.trace_id if parent else new_trace_id(),
            span_id=span_id or new_span_id(),
            parent_id=parent.span_id if parent else None,
            start=now if start is None else start,
            ts=self.clock.wall(),
            attributes=dict(attributes),
            status=status,
        )
        sp.end = now if end is None else end
        self._record(sp)
        return sp.context

    # -- storage -----------------------------------------------------------
    def _record(self, sp: Span) -> None:
        with self._lock:
            bucket = self._traces.get(sp.trace_id)
            if bucket is None:
                while len(self._traces) >= self.max_traces:
                    self._traces.popitem(last=False)
                    self.registry.inc("tracing_dropped_total", kind="trace")
                bucket = _TraceBucket(
                    self._head_cap,
                    self.max_spans_per_trace - self._head_cap,
                )
                self._traces[sp.trace_id] = bucket
            if bucket.add(sp):
                self.registry.inc("tracing_dropped_total", kind="span")
            self._seq += 1
            bucket.last_seq = self._seq
            self.registry.inc("tracing_spans_total")

    @property
    def cursor(self) -> int:
        """The current completion index: pass it back as ``since=`` to
        receive only traces that recorded spans after this read."""
        with self._lock:
            return self._seq

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    # -- assembly ----------------------------------------------------------
    @staticmethod
    def _assemble(trace_id: str, spans: list[Span]) -> dict:
        nodes = {s.span_id: {**s.to_dict(), "children": []} for s in spans}
        roots = []
        for s in sorted(spans, key=lambda x: x.start):
            node = nodes[s.span_id]
            parent = nodes.get(s.parent_id) if s.parent_id else None
            (parent["children"] if parent else roots).append(node)
        t0 = min(s.start for s in spans)
        t1 = max(s.end for s in spans)
        return {
            "trace_id": trace_id,
            "span_count": len(spans),
            "duration_ms": round(max(0.0, (t1 - t0) * 1000.0), 3),
            "start": t0,
            "tree": roots,
        }

    def get_trace(self, trace_id: str) -> dict | None:
        with self._lock:
            bucket = self._traces.get(trace_id)
            spans = bucket.spans() if bucket else []
        return self._assemble(trace_id, spans) if spans else None

    def traces(
        self,
        trace_id: str | None = None,
        min_ms: float = 0.0,
        name: str = "",
        limit: int = 50,
        since: int = 0,
    ) -> list[dict]:
        """Assembled traces, most recent first.  ``name`` matches a
        substring of any span name; ``min_ms`` filters on total trace
        duration; ``trace_id`` selects exactly one.  ``since`` is a
        completion-index cursor (``Tracer.cursor``): only traces that
        recorded a span AFTER that read are returned, so a periodic
        scraper ships deltas instead of re-fetching the whole ring."""
        with self._lock:
            snap = [
                (tid, b.spans(), b.last_seq)
                for tid, b in self._traces.items()
            ]
        out = []
        for tid, spans, last_seq in reversed(snap):
            if not spans or (trace_id and tid != trace_id):
                continue
            if since and last_seq <= since:
                continue
            if name and not any(name in s.name for s in spans):
                continue
            t = self._assemble(tid, spans)
            if t["duration_ms"] < min_ms:
                continue
            out.append(t)
            if len(out) >= max(1, int(limit)):
                break
        return out


def render_trace(trace: dict) -> str:
    """Flame-style indented tree of one ASSEMBLED trace (the dict shape
    ``Tracer.traces``/``/debug/traces`` produce) — shared by the ``obs
    traces`` CLI and the trace-demo smoke so both render identically."""
    lines = [
        f"trace {trace['trace_id']}  "
        f"({trace['span_count']} spans, {trace['duration_ms']:.1f} ms)"
    ]

    def walk(node: dict, depth: int) -> None:
        attrs = node.get("attributes") or {}
        extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        flag = "" if node.get("status", "ok") == "ok" else "  [ERROR]"
        lines.append(
            f"{'  ' * depth}• {node['name']:<40s} "
            f"{node['duration_ms']:9.1f} ms{flag}"
            + (f"  {{{extra}}}" if extra else "")
        )
        for child in node.get("children", ()):
            walk(child, depth + 1)

    for root in trace.get("tree", ()):
        walk(root, 1)
    return "\n".join(lines)


global_tracer = Tracer()
