"""Observability HTTP surface — the Prometheus-scrape side of C32.

The reference specifies Prometheus monitoring of GPU utilization, queue
length and storage usage plus quota alerting (GPU调度平台搭建.md:798-807)
but ships no endpoint.  Here the controller manager's metrics registry is
served on a real ``/metrics`` endpoint (text exposition format) with
``/healthz``/``/readyz`` probes — what a Prometheus in the cluster would
scrape off this control plane.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .metrics import MetricsRegistry, global_metrics


class MetricsServer:
    """Serves /metrics, /healthz, /readyz on a daemon thread.

    ``port=0`` binds an ephemeral port (tests); ``.port`` is the bound one.
    ``ready_check`` lets the owner gate readiness (e.g. manager started).
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        ready_check=None,
    ):
        self.registry = registry or global_metrics
        self.started_at = time.time()
        self._ready_check = ready_check
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path == "/metrics":
                    body = outer.registry.render().encode()
                    self._send(200, body, "text/plain; version=0.0.4")
                elif self.path == "/healthz":
                    body = json.dumps(
                        {"ok": True, "uptime_s": time.time() - outer.started_at}
                    ).encode()
                    self._send(200, body, "application/json")
                elif self.path == "/readyz":
                    ready = (
                        outer._ready_check() if outer._ready_check else True
                    )
                    self._send(
                        200 if ready else 503,
                        json.dumps({"ready": bool(ready)}).encode(),
                        "application/json",
                    )
                else:
                    self._send(404, b"not found", "text/plain")

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr spam
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-server", daemon=True
        )

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)


class RequestMetricsMixin:
    """Request instrumentation for stdlib ``BaseHTTPRequestHandler``s
    (C32): counts by route/method/code + latency histograms into the
    shared registry.  Subclasses set ``metrics_server_label`` and
    ``known_routes`` (longest-prefix matched; anything else collapses to
    the fixed label "other" — an attacker scanning paths must not be able
    to mint unbounded metric series in the never-evicting registry), then
    implement ``_get``/``_post`` and set ``self._last_code`` when
    responding.

    Metrics are recorded in a ``finally`` AFTER the response bytes go out
    (the latency must include the write) — scrapers may observe a served
    response a beat before its counter lands."""

    metrics_server_label = "http"
    known_routes: tuple[str, ...] = ()

    def _route(self) -> str:
        path = self.path.split("?")[0]
        for r in self.known_routes:  # declare longest prefixes first
            if path == r:
                return r
            # "/" is exact-only: as a prefix it would swallow every path
            # and defeat the "other" collapse.
            if r != "/" and path.startswith(r.rstrip("/") + "/"):
                return r
        return "other"

    def _timed(self, method: str, impl) -> None:
        self._last_code = 0
        route = self._route()
        t0 = time.time()
        try:
            impl()
        finally:
            global_metrics.inc(
                "http_requests_total", server=self.metrics_server_label,
                method=method, route=route, code=str(self._last_code),
            )
            global_metrics.observe(
                "http_request_seconds", time.time() - t0,
                server=self.metrics_server_label, route=route,
            )

    def do_GET(self):  # noqa: N802 (stdlib API name)
        self._timed("GET", self._get)

    def do_POST(self):  # noqa: N802
        self._timed("POST", self._post)
