"""Observability HTTP surface — the Prometheus-scrape side of C32.

The reference specifies Prometheus monitoring of GPU utilization, queue
length and storage usage plus quota alerting (GPU调度平台搭建.md:798-807)
but ships no endpoint.  Here the controller manager's metrics registry is
served on a real ``/metrics`` endpoint (text exposition format) with
``/healthz``/``/readyz`` probes — what a Prometheus in the cluster would
scrape off this control plane.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .metrics import MetricsRegistry, global_metrics
from .tracing import Tracer, global_tracer, parse_traceparent


class MetricsServer:
    """Serves /metrics, /debug/traces, /healthz, /readyz on a daemon
    thread.

    ``port=0`` binds an ephemeral port (tests); ``.port`` is the bound one.
    ``ready_check`` lets the owner gate readiness (e.g. manager started).
    ``/debug/traces`` exposes the tracer's assembled traces as JSON,
    filterable by ``trace_id=``, ``min_ms=``, ``name=``, ``limit=``.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        ready_check=None,
        tracer: Tracer | None = None,
    ):
        self.registry = registry or global_metrics
        self.tracer = tracer or global_tracer
        self.started_at = time.time()
        self._ready_check = ready_check
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path == "/metrics":
                    body = outer.registry.render().encode()
                    self._send(200, body, "text/plain; version=0.0.4")
                elif self.path.split("?")[0] == "/debug/traces":
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)

                    def one(key, default=""):
                        return (q.get(key) or [default])[0]

                    try:
                        min_ms = float(one("min_ms", "0"))
                        limit = int(one("limit", "50"))
                    except ValueError:
                        return self._send(
                            400,
                            json.dumps({
                                "error": "min_ms/limit must be numeric"
                            }).encode(),
                            "application/json",
                        )
                    traces = outer.tracer.traces(
                        trace_id=one("trace_id") or None,
                        min_ms=min_ms,
                        name=one("name"),
                        limit=limit,
                    )
                    self._send(
                        200,
                        json.dumps({"traces": traces}).encode(),
                        "application/json",
                    )
                elif self.path == "/healthz":
                    body = json.dumps(
                        {"ok": True, "uptime_s": time.time() - outer.started_at}
                    ).encode()
                    self._send(200, body, "application/json")
                elif self.path == "/readyz":
                    ready = (
                        outer._ready_check() if outer._ready_check else True
                    )
                    self._send(
                        200 if ready else 503,
                        json.dumps({"ready": bool(ready)}).encode(),
                        "application/json",
                    )
                else:
                    self._send(404, b"not found", "text/plain")

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr spam
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-server", daemon=True
        )

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)


class RequestMetricsMixin:
    """Request instrumentation for stdlib ``BaseHTTPRequestHandler``s
    (C32): counts by route/method/code + latency histograms into the
    shared registry.  Subclasses set ``metrics_server_label`` and
    ``known_routes`` (longest-prefix matched; anything else collapses to
    the fixed label "other" — an attacker scanning paths must not be able
    to mint unbounded metric series in the never-evicting registry), then
    implement ``_get``/``_post`` and set ``self._last_code`` when
    responding.

    Metrics are recorded in a ``finally`` AFTER the response bytes go out
    (the latency must include the write) — scrapers may observe a served
    response a beat before its counter lands.

    Every request also runs under a tracing span: an inbound W3C
    ``traceparent`` header continues the caller's trace, otherwise the
    request roots a new one.  The span is the thread's current tracing
    context for the handler's duration, so anything the handler touches
    (kube writes → watch enqueues, batcher submits) inherits it;
    ``self.trace_ctx`` exposes it for response stamping."""

    metrics_server_label = "http"
    known_routes: tuple[str, ...] = ()
    trace_ctx = None
    # Probe routes don't open spans: a kubelet hitting /healthz every few
    # seconds would churn real traces out of the bounded ring.
    trace_exempt_routes: tuple[str, ...] = ("/healthz", "/readyz")

    def _route(self) -> str:
        path = self.path.split("?")[0]
        for r in self.known_routes:  # declare longest prefixes first
            if path == r:
                return r
            # "/" is exact-only: as a prefix it would swallow every path
            # and defeat the "other" collapse.
            if r != "/" and path.startswith(r.rstrip("/") + "/"):
                return r
        return "other"

    def _timed(self, method: str, impl) -> None:
        self._last_code = 0
        route = self._route()
        t0 = time.time()
        inbound = parse_traceparent(self.headers.get("traceparent"))
        try:
            if route in self.trace_exempt_routes and inbound is None:
                impl()
            else:
                with global_tracer.span(
                    f"http {method} {route}", parent=inbound,
                    server=self.metrics_server_label,
                ) as sp:
                    self.trace_ctx = sp.context
                    impl()
                    sp.attributes["code"] = self._last_code
        finally:
            global_metrics.inc(
                "http_requests_total", server=self.metrics_server_label,
                method=method, route=route, code=str(self._last_code),
            )
            global_metrics.observe(
                "http_request_seconds", time.time() - t0,
                server=self.metrics_server_label, route=route,
            )

    def do_GET(self):  # noqa: N802 (stdlib API name)
        self._timed("GET", self._get)

    def do_POST(self):  # noqa: N802
        self._timed("POST", self._post)
