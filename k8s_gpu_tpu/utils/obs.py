"""Observability HTTP surface — the Prometheus-scrape side of C32.

The reference specifies Prometheus monitoring of GPU utilization, queue
length and storage usage plus quota alerting (GPU调度平台搭建.md:798-807)
but ships no endpoint.  Here the controller manager's metrics registry is
served on a real ``/metrics`` endpoint (text exposition format) with
``/healthz``/``/readyz`` probes — what a Prometheus in the cluster would
scrape off this control plane — plus ``/alerts``: the in-process rules
engine's firing/pending alerts and transition timeline as JSON
(utils/alerts.py), the quota-alerting half of the same prose spec.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .metrics import MetricsRegistry, global_metrics, parse_exposition
from .tracing import Tracer, global_tracer, parse_traceparent


class MetricsServer:
    """Serves /metrics, /alerts, /debug/traces, /healthz, /readyz on a
    daemon thread.

    ``port=0`` binds an ephemeral port (tests); ``.port`` is the bound one.
    ``ready_check`` lets the owner gate readiness (e.g. manager started).
    ``/debug/traces`` exposes the tracer's assembled traces as JSON,
    filterable by ``trace_id=``, ``min_ms=``, ``name=``, ``limit=``.
    ``alerts`` is a ``utils.alerts.RuleEvaluator``; without one,
    ``/alerts`` answers 404.  ``fleet`` is a
    ``utils.federation.FleetCollector`` — ``/fleet`` serves its
    snapshot (``?refresh=1`` forces a scrape pass; a never-scraped
    collector scrapes once on first read so a bare ``obs fleet`` works
    without an evaluator ticking).  ``journal`` is a
    ``serve.journal.RequestJournal`` — ``/debug/requests`` serves its
    per-request records, filterable by ``tenant=``, ``reason=``,
    ``trace_id=``, ``limit=``.  ``profile`` is a
    ``utils.profiler.PhaseProfiler`` — ``/debug/profile`` serves the
    continuous performance-attribution snapshot (per-phase p50/p95/
    share, XLA compile telemetry, per-axis collective bandwidth —
    ``obs profile`` renders it).  ``goodput`` is a
    ``utils.goodput.GoodputLedger`` — ``/debug/goodput`` serves the
    training wall-clock partition, straggler attribution, checkpoint
    telemetry and incident timeline (``obs goodput`` renders it;
    byte-identical across two scripted FakeClock runs).  ``probes`` is
    a ``serve.canary.CanaryProber`` — ``/debug/probes`` serves its
    per-replica health-FSM snapshot (``obs probes`` renders it; same
    byte-identical contract).  ``/debug/requests`` additionally takes
    ``probes=0`` to drop canary records (``obs requests --no-probes``).
    ``waterfall`` is a ``utils.waterfall.FleetTraceAssembler`` —
    ``/debug/waterfall`` serves the stitched cross-process request
    listing, ``?trace_id=`` the full per-segment waterfall,
    ``&chrome=1`` its multi-process Perfetto export, ``?refresh=1``
    forces a scrape pass (a never-scraped assembler scrapes once on
    first read); ``obs waterfall`` renders it, byte-identical across
    two FakeClock runs over the same captured rings.
    ``/debug/traces`` additionally takes ``since=`` (the tracer's
    completion-index cursor, echoed back as ``cursor`` in every
    response) so a periodic scraper ships only new traces.
    The handler instruments ITSELF through
    ``RequestMetricsMixin`` (server label ``"obs"``), so scrape traffic
    shows up in ``http_requests_total`` like every other HTTP plane.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        ready_check=None,
        tracer: Tracer | None = None,
        alerts=None,
        fleet=None,
        journal=None,
        profile=None,
        goodput=None,
        probes=None,
        waterfall=None,
        replay=None,
    ):
        self.registry = registry or global_metrics
        self.tracer = tracer or global_tracer
        self.alerts = alerts
        self.fleet = fleet
        self.journal = journal
        self.profile = profile
        self.goodput = goodput
        self.probes = probes
        self.waterfall = waterfall
        self.replay = replay
        self.started_at = time.time()
        self._ready_check = ready_check
        outer = self

        class Handler(RequestMetricsMixin, BaseHTTPRequestHandler):
            metrics_server_label = "obs"
            known_routes = (
                "/debug/goodput", "/debug/probes", "/debug/profile",
                "/debug/replay", "/debug/requests", "/debug/traces",
                "/debug/waterfall",
                "/metrics", "/alerts", "/fleet", "/healthz", "/readyz",
            )

            def _get(self):
                path = self.path.split("?")[0]
                if path == "/metrics":
                    body = outer.registry.render().encode()
                    self._send(200, body, "text/plain; version=0.0.4")
                elif path == "/alerts":
                    self._alerts()
                elif path == "/debug/traces":
                    self._traces()
                elif path == "/debug/requests":
                    self._requests()
                elif path == "/debug/profile":
                    self._profile()
                elif path == "/debug/goodput":
                    self._goodput()
                elif path == "/debug/probes":
                    self._probes()
                elif path == "/debug/waterfall":
                    self._waterfall()
                elif path == "/debug/replay":
                    self._replay()
                elif path == "/fleet":
                    self._fleet()
                elif path == "/healthz":
                    body = json.dumps(
                        {"ok": True, "uptime_s": time.time() - outer.started_at}
                    ).encode()
                    self._send(200, body, "application/json")
                elif path == "/readyz":
                    ready = (
                        outer._ready_check() if outer._ready_check else True
                    )
                    self._send(
                        200 if ready else 503,
                        json.dumps({"ready": bool(ready)}).encode(),
                        "application/json",
                    )
                else:
                    self._send(404, b"not found", "text/plain")

            def _post(self):
                self._send(404, b"not found", "text/plain")

            def _query(self):
                from urllib.parse import parse_qs, urlparse

                q = parse_qs(urlparse(self.path).query)

                def one(key, default=""):
                    return (q.get(key) or [default])[0]

                return one

            def _alerts(self):
                if outer.alerts is None:
                    return self._send(
                        404,
                        json.dumps(
                            {"error": "no rules engine attached"}
                        ).encode(),
                        "application/json",
                    )
                one = self._query()
                try:
                    limit = int(one("limit", "100"))
                except ValueError:
                    return self._send(
                        400,
                        json.dumps({"error": "limit must be an int"}).encode(),
                        "application/json",
                    )
                snap = outer.alerts.snapshot(limit=limit)
                state = one("state")
                if state:
                    snap["alerts"] = [
                        a for a in snap["alerts"] if a["state"] == state
                    ]
                self._send(
                    200, json.dumps(snap).encode(), "application/json"
                )

            def _fleet(self):
                if outer.fleet is None:
                    return self._send(
                        404,
                        json.dumps(
                            {"error": "no fleet collector attached"}
                        ).encode(),
                        "application/json",
                    )
                one = self._query()
                if one("refresh") == "1" or outer.fleet.never_scraped:
                    outer.fleet.scrape_once()
                self._send(
                    200,
                    json.dumps(outer.fleet.snapshot()).encode(),
                    "application/json",
                )

            def _profile(self):
                if outer.profile is None:
                    return self._send(
                        404,
                        json.dumps(
                            {"error": "no phase profiler attached"}
                        ).encode(),
                        "application/json",
                    )
                from .profiler import profile_snapshot

                body = json.dumps(
                    profile_snapshot(outer.profile, outer.registry),
                    sort_keys=True,
                ).encode()
                self._send(200, body, "application/json")

            def _goodput(self):
                if outer.goodput is None:
                    return self._send(
                        404,
                        json.dumps(
                            {"error": "no goodput ledger attached"}
                        ).encode(),
                        "application/json",
                    )
                from .goodput import goodput_snapshot

                # sort_keys: the two-run byte-identical contract.
                body = json.dumps(
                    goodput_snapshot(outer.goodput, outer.registry),
                    sort_keys=True,
                ).encode()
                self._send(200, body, "application/json")

            def _probes(self):
                if outer.probes is None:
                    return self._send(
                        404,
                        json.dumps(
                            {"error": "no canary prober attached"}
                        ).encode(),
                        "application/json",
                    )
                # sort_keys: the two-run byte-identical contract.
                body = json.dumps(
                    outer.probes.snapshot(), sort_keys=True
                ).encode()
                self._send(200, body, "application/json")

            def _waterfall(self):
                if outer.waterfall is None:
                    return self._send(
                        404,
                        json.dumps(
                            {"error": "no trace assembler attached"}
                        ).encode(),
                        "application/json",
                    )
                one = self._query()
                try:
                    limit = int(one("limit", "50"))
                except ValueError:
                    return self._send(
                        400,
                        json.dumps({"error": "limit must be an int"}).encode(),
                        "application/json",
                    )
                if one("refresh") == "1" or outer.waterfall.never_scraped:
                    outer.waterfall.scrape_once()
                tid = one("trace_id")
                if tid:
                    if one("chrome") == "1":
                        snap = outer.waterfall.chrome(tid)
                    else:
                        snap = outer.waterfall.waterfall(tid)
                    if snap is None:
                        return self._send(
                            404,
                            json.dumps(
                                {"error": f"no spans for trace {tid!r}"}
                            ).encode(),
                            "application/json",
                        )
                else:
                    snap = outer.waterfall.snapshot(limit=limit)
                # sort_keys: the two-run byte-identical contract.
                body = json.dumps(snap, sort_keys=True).encode()
                self._send(200, body, "application/json")

            def _replay(self):
                if outer.replay is None:
                    return self._send(
                        404,
                        json.dumps(
                            {"error": "no replay state attached"}
                        ).encode(),
                        "application/json",
                    )
                # sort_keys: the two-run byte-identical contract.
                body = json.dumps(
                    outer.replay.snapshot(), sort_keys=True
                ).encode()
                self._send(200, body, "application/json")

            def _requests(self):
                if outer.journal is None:
                    return self._send(
                        404,
                        json.dumps(
                            {"error": "no request journal attached"}
                        ).encode(),
                        "application/json",
                    )
                one = self._query()
                try:
                    limit = int(one("limit", "100"))
                    since = int(one("since", "0"))
                except ValueError:
                    return self._send(
                        400,
                        json.dumps(
                            {"error": "limit/since must be ints"}
                        ).encode(),
                        "application/json",
                    )
                # cursor first (the /debug/traces discipline): a record
                # appended between snapshot() and the cursor read would
                # otherwise be skipped by the NEXT since= pass;
                # double-shipping dedups, gaps don't.
                cursor = outer.journal.cursor
                origin = outer.journal.origin
                recs = outer.journal.snapshot(
                    limit=limit,
                    tenant=one("tenant"),
                    reason=one("reason"),
                    trace_id=one("trace_id"),
                    probes=one("probes", "1") != "0",
                    since=since,
                )
                self._send(
                    200,
                    json.dumps({
                        "requests": recs,
                        "cursor": cursor,
                        "origin": origin,
                    }).encode(),
                    "application/json",
                )

            def _traces(self):
                one = self._query()
                try:
                    min_ms = float(one("min_ms", "0"))
                    limit = int(one("limit", "50"))
                    since = int(one("since", "0"))
                except ValueError:
                    return self._send(
                        400,
                        json.dumps({
                            "error": "min_ms/limit/since must be numeric"
                        }).encode(),
                        "application/json",
                    )
                # cursor first: a span recorded between traces() and the
                # cursor read would otherwise be skipped by the NEXT
                # since= pass; double-shipping dedups, gaps don't.
                cursor = outer.tracer.cursor
                traces = outer.tracer.traces(
                    trace_id=one("trace_id") or None,
                    min_ms=min_ms,
                    name=one("name"),
                    limit=limit,
                    since=since,
                )
                self._send(
                    200,
                    json.dumps(
                        {"traces": traces, "cursor": cursor}
                    ).encode(),
                    "application/json",
                )

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self._last_code = code
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr spam
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-server", daemon=True
        )

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)


class RequestMetricsMixin:
    """Request instrumentation for stdlib ``BaseHTTPRequestHandler``s
    (C32): counts by route/method/code + latency histograms into the
    shared registry.  Subclasses set ``metrics_server_label`` and
    ``known_routes`` (longest-prefix matched; anything else collapses to
    the fixed label "other" — an attacker scanning paths must not be able
    to mint unbounded metric series in the never-evicting registry), then
    implement ``_get``/``_post`` and set ``self._last_code`` when
    responding.

    Metrics are recorded in a ``finally`` AFTER the response bytes go out
    (the latency must include the write) — scrapers may observe a served
    response a beat before its counter lands.

    Every request also runs under a tracing span: an inbound W3C
    ``traceparent`` header continues the caller's trace, otherwise the
    request roots a new one.  The span is the thread's current tracing
    context for the handler's duration, so anything the handler touches
    (kube writes → watch enqueues, batcher submits) inherits it;
    ``self.trace_ctx`` exposes it for response stamping."""

    metrics_server_label = "http"
    known_routes: tuple[str, ...] = ()
    trace_ctx = None
    # Probe routes don't open spans: a kubelet hitting /healthz every few
    # seconds would churn real traces out of the bounded ring.  /metrics
    # scrapes are probe-cadence traffic too.
    trace_exempt_routes: tuple[str, ...] = ("/healthz", "/readyz", "/metrics")

    def _route(self) -> str:
        path = self.path.split("?")[0]
        for r in self.known_routes:  # declare longest prefixes first
            if path == r:
                return r
            # "/" is exact-only: as a prefix it would swallow every path
            # and defeat the "other" collapse.
            if r != "/" and path.startswith(r.rstrip("/") + "/"):
                return r
        return "other"

    def _timed(self, method: str, impl) -> None:
        self._last_code = 0
        # Reset per request: on a keep-alive connection an exempt route
        # must not inherit (and stamp x-trace-id with) the PREVIOUS
        # request's context.
        self.trace_ctx = None
        route = self._route()
        t0 = time.time()
        inbound = parse_traceparent(self.headers.get("traceparent"))
        try:
            if route in self.trace_exempt_routes and inbound is None:
                impl()
            else:
                with global_tracer.span(
                    f"http {method} {route}", parent=inbound,
                    server=self.metrics_server_label,
                ) as sp:
                    self.trace_ctx = sp.context
                    impl()
                    sp.attributes["code"] = self._last_code
        finally:
            global_metrics.inc(
                "http_requests_total", server=self.metrics_server_label,
                method=method, route=route, code=str(self._last_code),
            )
            global_metrics.observe(
                "http_request_seconds", time.time() - t0,
                server=self.metrics_server_label, route=route,
            )

    def do_GET(self):  # noqa: N802 (stdlib API name)
        self._timed("GET", self._get)

    def do_POST(self):  # noqa: N802
        self._timed("POST", self._post)


def render_top(text: str) -> str:
    """The ``obs top`` view: a fleet-utilization snapshot rendered from
    ONE ``/metrics`` exposition (a live scrape or the persisted
    ``metrics.prom``) — KV/batch occupancy on the serve plane, per-queue
    depth/age on the control plane, ready ratios per pool, and the train
    plane's step cadence.  Families absent from the scrape render as
    "-" rather than erroring: a control-plane-only snapshot is normal."""
    fam = parse_exposition(text)

    def one(name, default=None):
        series = fam.get(name)
        if not series:
            return default
        return next(iter(series.values()))

    def pct(v):
        return f"{v:6.1%}" if v is not None else "     -"

    def num(v, fmt="{:,.1f}"):
        return fmt.format(v) if v is not None else "-"

    lines = ["FLEET UTILIZATION", ""]
    lines.append("serve plane")
    lines.append(
        f"  kv occupancy {pct(one('serve_kv_occupancy_ratio'))}"
        f"   batch fill {pct(one('serve_slot_fill_ratio'))}"
        f"   slots active {num(one('serve_slots_active'), '{:,.0f}')}"
    )
    lines.append(
        f"  pending reqs {num(one('serve_pending_requests'), '{:,.0f}'):>7}"
        f"   decode tok/s {num(one('serve_decode_tokens_per_second'))}"
        f"   kv blocks used {num(one('serve_kv_blocks_used'), '{:,.0f}')}"
    )
    lines.append("")
    lines.append("controller queues")
    depths = fam.get("workqueue_depth", {})
    ages = fam.get("workqueue_oldest_age_seconds", {})
    if depths:
        lines.append(f"  {'QUEUE':<24} {'DEPTH':>6} {'OLDEST(S)':>10}")
        for lbls, depth in sorted(depths.items()):
            name = dict(lbls).get("queue", "?")
            age = ages.get(lbls)
            lines.append(
                f"  {name:<24} {depth:>6.0f} "
                f"{age if age is not None else float('nan'):>10.1f}"
            )
    else:
        lines.append("  (no workqueue gauges in this snapshot)")
    lines.append("")
    lines.append("accelerator pools")
    ready = fam.get("pool_ready_replicas", {})
    desired = fam.get("pool_desired_replicas", {})
    ratios = fam.get("pool_ready_ratio", {})
    if ratios or ready:
        lines.append(
            f"  {'KIND':<14} {'POOL':<20} {'READY':>5} {'DESIRED':>7} "
            f"{'RATIO':>7}"
        )
        for lbls in sorted(set(ready) | set(ratios)):
            d = dict(lbls)
            r = ratios.get(lbls)
            pool = d.get("pool", "?")
            if d.get("namespace"):
                pool = f"{d['namespace']}/{pool}"
            lines.append(
                f"  {d.get('kind', '?'):<14} {pool:<20} "
                f"{num(ready.get(lbls), '{:,.0f}'):>5} "
                f"{num(desired.get(lbls), '{:,.0f}'):>7} "
                f"{pct(r):>7}"
            )
    else:
        lines.append("  (no pool gauges in this snapshot)")
    lines.append("")
    lines.append("train plane")
    lines.append(
        f"  last step {num(one('train_last_step_seconds'), '{:.3f}')} s"
        f"   tokens/s {num(one('train_tokens_per_second'))}"
    )
    firing = fam.get("alerts_firing", {})
    hot = {dict(l).get("alertname", "?"): v for l, v in firing.items() if v}
    lines.append("")
    lines.append(
        "alerts firing: "
        + (", ".join(sorted(hot)) if hot else "none")
    )
    return "\n".join(lines)


def _flatval(v, fmt="{:,.2f}") -> str:
    """One cell of a fleet table: a scalar formats; a labeled breakdown
    (multi-series family) collapses to its sum for the columnar view."""
    if v is None:
        return "-"
    if isinstance(v, dict):
        v = sum(v.values())
    return fmt.format(v)


def render_top_columns(snap: dict) -> str:
    """The multi-replica ``obs top``: one column per replica plus the
    FLEET aggregate column, rendered from a ``FleetCollector.snapshot``
    (relabel/aggregate already applied — the CLI never re-implements
    the policy).  Rows are the key serve/controller gauges; a down
    replica renders "down" instead of stale numbers."""
    reps = snap.get("replicas", [])
    names = [r["replica"] for r in reps]
    width = max([10] + [len(n) + 2 for n in names])
    rows = [
        ("slot fill", "serve_slot_fill_ratio", "{:.1%}"),
        ("kv occupancy", "serve_kv_occupancy_ratio", "{:.1%}"),
        ("pending", "serve_pending_requests", "{:,.0f}"),
        ("decode tok/s", "serve_decode_tokens_per_second", "{:,.1f}"),
        ("slots active", "serve_slots_active", "{:,.0f}"),
        ("queue depth", "workqueue_depth", "{:,.0f}"),
    ]
    agg = snap.get("aggregates", {})
    lines = [
        "FLEET UTILIZATION  "
        f"({len(reps)} replicas, "
        f"{sum(1 for r in reps if r['up'])} up)",
        "",
        "  " + f"{'':<14}" + "".join(f"{n:>{width}}" for n in names)
        + f"{'FLEET':>{width}}",
    ]
    for label, gauge, fmt in rows:
        cells = []
        for r in reps:
            if not r["up"]:
                cells.append(f"{'down':>{width}}")
            else:
                cells.append(
                    f"{_flatval(r['gauges'].get(gauge), fmt):>{width}}"
                )
        a = agg.get(gauge)
        fleet_cell = _flatval(a["value"], fmt) if a else "-"
        how = f" ({a['agg']})" if a else ""
        lines.append(
            f"  {label:<14}" + "".join(cells)
            + f"{fleet_cell:>{width}}" + how
        )
    p95 = [
        f"{(r['ttft_p95_s'] * 1000):.0f}ms"
        if r["up"] and r.get("ttft_p95_s") is not None else "-"
        for r in reps
    ]
    fp = snap.get("ttft_p95_s")
    lines.append(
        f"  {'ttft p95':<14}" + "".join(f"{c:>{width}}" for c in p95)
        + f"{(f'{fp * 1000:.0f}ms' if fp is not None else '-'):>{width}}"
        + " (merged)"
    )
    return "\n".join(lines)


def render_fleet(snap: dict) -> str:
    """The ``obs fleet`` view of one ``/fleet`` snapshot: replica
    liveness + key gauges per row, then the per-tenant SLO table."""
    reps = snap.get("replicas", [])
    lines = [
        f"FLEET  ({len(reps)} replicas, "
        f"{sum(1 for r in reps if r['up'])} up; "
        f"down after {snap.get('down_after', '?')} failed scrapes)",
        "",
        f"  {'REPLICA':<18} {'UP':<4} {'FILL':>7} {'KV OCC':>7} "
        f"{'PENDING':>8} {'TOK/S':>8} {'TTFT P95':>9} {'AGE(S)':>7}",
    ]
    for r in reps:
        g = r.get("gauges", {})
        p95 = r.get("ttft_p95_s")
        age = r.get("last_scrape_age_s")
        lines.append(
            f"  {r['replica']:<18} "
            f"{'up' if r['up'] else 'DOWN':<4} "
            f"{_flatval(g.get('serve_slot_fill_ratio'), '{:.1%}'):>7} "
            f"{_flatval(g.get('serve_kv_occupancy_ratio'), '{:.1%}'):>7} "
            f"{_flatval(g.get('serve_pending_requests'), '{:,.0f}'):>8} "
            f"{_flatval(g.get('serve_decode_tokens_per_second'), '{:,.1f}'):>8} "
            f"{(f'{p95 * 1000:.0f}ms' if p95 is not None else '-'):>9} "
            f"{(f'{age:.1f}' if age is not None else '-'):>7}"
        )
    tenants = snap.get("tenants", {})
    if tenants:
        lines.append("")
        lines.append(
            f"  {'TENANT':<18} {'TOKENS':>10} {'GOODPUT':>10} "
            f"{'GOODPUT%':>9} {'BURN':>7}"
        )
        for t, d in tenants.items():
            tot = d.get("tokens", 0.0)
            good = d.get("goodput_tokens", 0.0)
            burn = d.get("slo_burn_rate")
            lines.append(
                f"  {t:<18} {tot:>10,.0f} {good:>10,.0f} "
                f"{(good / tot if tot else 1.0):>9.1%} "
                f"{(f'{burn:.1f}x' if burn is not None else '-'):>7}"
            )
    return "\n".join(lines)


def render_gateways(snaps: list[dict]) -> str:
    """The ``obs gateways`` view: one row per gateway (owner-map digest,
    generation, tracked chains, peer-agreement verdict), then the
    admission plane's per-tenant quota/WFQ table from the first gateway
    that serves one.  ``snaps`` rows are ``{"name", "ownermap", per
    gateway /admin/ownermap body or None, "admission": /admin/admission
    body or None}``."""
    if not snaps:
        return "no gateways to render"
    digests = [
        (s.get("ownermap") or {}).get("digest") or "" for s in snaps
    ]
    have = [d for d in digests if d]
    converged = bool(have) and len(set(have)) == 1
    lines = [
        f"GATEWAYS  ({len(snaps)} gateways, "
        + ("owner maps CONVERGED" if converged
           else "owner maps DIVERGED" if have else "no owner maps yet")
        + ")",
        "",
        f"  {'GATEWAY':<16} {'DIGEST':<18} {'SEQ':>5} {'CHAINS':>7} "
        f"{'REPLICAS':>9} {'PEERS':>6} {'AGREE':>6}",
    ]
    for s, d in zip(snaps, digests):
        om = s.get("ownermap")
        if om is None:
            lines.append(
                f"  {s.get('name', '?'):<16} {'unreachable':<18}"
            )
            continue
        agree = (
            "-" if len(have) < 2
            else "yes" if d and all(d == x for x in have)
            else "NO"
        )
        lines.append(
            f"  {s.get('name', '?'):<16} {d or '-':<18} "
            f"{om.get('seq', 0):>5} {om.get('tracked', 0):>7} "
            f"{len(om.get('replicas', [])):>9} "
            f"{len(om.get('peers', [])):>6} {agree:>6}"
        )
    adm = next(
        (
            (s.get("name", "?"), s["admission"]) for s in snaps
            if (s.get("admission") or {}).get("enabled")
        ),
        None,
    )
    if adm is not None:
        name, a = adm
        lines.append("")
        lines.append(
            f"  ADMISSION @ {name}  "
            f"(slots {a.get('held', 0)}/{a.get('slots', 0)} held, "
            f"quantum {a.get('quantum', 0):.0f} tokens)"
        )
        tenants = a.get("tenants", [])
        if tenants:
            lines.append(
                f"  {'TENANT':<14} {'CLASS':<12} {'WEIGHT':>7} "
                f"{'SHARE':>8} {'DEFICIT':>8} {'QUEUED':>7} "
                f"{'QUOTA/S':>8} {'LEVEL':>8}"
            )
            for d in tenants:
                q = d.get("quota_tokens_per_s")
                lv = d.get("quota_level")
                lines.append(
                    f"  {d.get('tenant', '?'):<14} "
                    f"{d.get('priority', '?'):<12} "
                    f"{d.get('weight', 1.0):>7.1f} "
                    f"{d.get('share', 0.0):>8.1%} "
                    f"{d.get('deficit', 0.0):>8,.0f} "
                    f"{d.get('queued', 0):>7} "
                    f"{(f'{q:,.0f}' if q is not None else '-'):>8} "
                    f"{(f'{lv:,.0f}' if lv is not None else '-'):>8}"
                )
    return "\n".join(lines)


def render_requests(records: list[dict]) -> str:
    """The ``obs requests`` view of ``/debug/requests`` records —
    newest first, one line per retired request, trace id last so the
    eye can carry it into ``obs traces --trace <id>``."""
    if not records:
        return "no journal records (no requests retired yet)"
    routed = any(r.get("replica") for r in records)
    # Disagg handover columns appear only when some request actually
    # handed over — the same conditional-column discipline as ROUTE.
    disagg = any(r.get("prefill_replica") for r in records)
    head = f"  {'TENANT':<12} {'REASON':<11} {'PATH':<13} "
    if routed:
        head += f"{'REPLICA':<12} {'ROUTE':<9} "
    if disagg:
        head += f"{'PREFILL':<12} {'HAND(MS)':>9} "
    head += (
        f"{'TOK':>5} {'WAIT(MS)':>9} {'TTFT(MS)':>9} {'TPOT(MS)':>9} "
        f"{'PFX':>4} {'ACC%':>5}  TRACE"
    )
    lines = [head]
    for r in records:
        acc = (
            f"{r['spec_accepted'] / r['spec_drafted']:.0%}"
            if r.get("spec_drafted") else "-"
        )
        line = (
            f"  {r['tenant']:<12} {r['reason']:<11} "
            f"{(r.get('path') or '-'):<13} "
        )
        if routed:
            line += (
                f"{(r.get('replica') or '-'):<12} "
                f"{(r.get('route_reason') or '-'):<9} "
            )
        if disagg:
            h = r.get("handover", 0.0) or 0.0
            line += (
                f"{(r.get('prefill_replica') or '-'):<12} "
                f"{(f'{h * 1000:.1f}' if h else '-'):>9} "
            )
        line += (
            f"{r['tokens']:>5} "
            f"{r['queue_wait_s'] * 1000:>9.1f} "
            f"{r['ttft_s'] * 1000:>9.1f} "
            f"{r['tpot_s'] * 1000:>9.1f} "
            f"{r.get('prefix_blocks', 0):>4} {acc:>5}  "
            f"{r.get('trace_id') or '-'}"
        )
        lines.append(line)
    return "\n".join(lines)


def render_replay(diff: dict) -> str:
    """The ``obs replay diff`` view of one replay diff report: the
    per-segment baseline/candidate attribution with regressed segments
    starred, then the headline ratios and the gate verdict."""
    lines = [
        f"REPLAY DIFF  (matched {diff.get('matched', 0)}, "
        f"baseline-only {diff.get('only_baseline', 0)}, "
        f"candidate-only {diff.get('only_candidate', 0)}, "
        f"mismatches {diff.get('mismatches', 0)})",
        "",
        f"  {'SEGMENT':<14} {'BASE(MS)':>10} {'CAND(MS)':>10} "
        f"{'DELTA(MS)':>10} {'RATIO':>7}",
    ]
    segs = diff.get("segments", {})
    if not segs:
        lines.append("  (no matched requests to attribute)")
    for name in sorted(segs):
        s = segs[name]
        star = " *" if s.get("regressed") else ""
        lines.append(
            f"  {name:<14} {s['baseline_s'] * 1000:>10.2f} "
            f"{s['candidate_s'] * 1000:>10.2f} "
            f"{s['delta_s'] * 1000:>10.2f} "
            f"{s['ratio']:>7.2f}{star}"
        )
    lines.append("")
    for metric in ("ttft", "tpot", "e2e"):
        m = diff.get(metric, {})
        if m:
            lines.append(
                f"  {metric.upper():<6} "
                f"{m.get('baseline_s', 0) * 1000:.2f}ms -> "
                f"{m.get('candidate_s', 0) * 1000:.2f}ms "
                f"({m.get('ratio', 1.0):.2f}x)"
            )
    regressed = diff.get("regressed_segments", [])
    lines.append("")
    if diff.get("mismatches"):
        lines.append(
            f"  VERDICT: FAIL — {diff['mismatches']} golden mismatches "
            "(wrong bytes always gate)"
        )
    elif regressed:
        lines.append(
            "  VERDICT: REGRESSION in " + ", ".join(regressed)
            + "  (* = regressed segment)"
        )
    else:
        lines.append("  VERDICT: OK — no segment regressed")
    return "\n".join(lines)


def render_profile(snap: dict) -> str:
    """The ``obs profile`` view of one ``/debug/profile`` snapshot (or
    its ``snapshot_from_exposition`` offline reconstruction): the
    per-phase attribution table, the residual, compile telemetry, and
    the per-axis collective bandwidth — with the jax.profiler deep-dive
    path cross-linked at the bottom."""
    phases = snap.get("phases", {})
    plane = snap.get("plane") or "?"
    lines = [
        f"PHASE ATTRIBUTION  (plane={plane}, "
        f"window {snap.get('window_s', 0):g}s, "
        f"span {snap.get('span_s', 0):.1f}s)",
        "",
        f"  {'PHASE':<22} {'COUNT':>7} {'P50(MS)':>9} {'P95(MS)':>9} "
        f"{'EWMA(MS)':>9} {'SHARE':>7}",
    ]
    if not phases:
        lines.append("  (no phase samples recorded yet)")
    for ph in sorted(
        phases, key=lambda p: -phases[p].get("share", 0.0)
    ):
        st = phases[ph]
        ewma = st.get("ewma_s")
        lines.append(
            f"  {ph:<22} {st.get('count', 0):>7} "
            f"{st.get('p50_s', 0.0) * 1000:>9.2f} "
            f"{st.get('p95_s', 0.0) * 1000:>9.2f} "
            f"{(f'{ewma * 1000:.2f}' if ewma is not None else '-'):>9} "
            f"{st.get('share', 0.0):>7.1%}"
        )
    res = snap.get("residual_share")
    if res is not None:
        lines.append(f"  {'(residual)':<22} {'':>7} {'':>9} {'':>9} {'':>9} "
                     f"{res:>7.1%}")
    comp = snap.get("compile") or {}
    lines.append("")
    lines.append(
        f"xla compiles: {comp.get('compiles_total', 0):.0f} total, "
        f"{comp.get('compile_seconds_sum', 0.0):.2f}s spent, "
        f"p95 {comp.get('compile_p95_s', 0.0) * 1000:.0f}ms "
        "(steady state should add zero — CompileStorm pages on the rate)"
    )
    coll = snap.get("collectives") or {}
    if coll:
        lines.append("")
        lines.append(f"  {'AXIS':<8} {'BANDWIDTH':>12}")
        for axis in sorted(coll):
            bw = coll[axis].get("bytes_per_second", 0.0)
            lines.append(f"  {axis:<8} {bw / 1e9:>10.3f} GB/s")
    lines.append("")
    lines.append(
        "deep dive (per-op device timing, HBM): utils.profiling.trace / "
        "profile_trainer -> jax.profiler xplane (TensorBoard/xprof)"
    )
    return "\n".join(lines)


def render_goodput(snap: dict) -> str:
    """The ``obs goodput`` view of one ``/debug/goodput`` snapshot (or
    its ``goodput_snapshot_from_exposition`` offline reconstruction):
    the wall-clock segment partition with the residual, the windowed
    goodput ratio, checkpoint telemetry, straggler attribution, and
    the incident flight-recorder timeline."""
    segments = snap.get("segments", {})
    elapsed = snap.get("elapsed_s", 0.0)
    ratio = snap.get("goodput_ratio")
    lines = [
        f"TRAINING GOODPUT  (elapsed {elapsed:.1f}s, productive "
        f"{snap.get('productive_s', 0.0):.1f}s = "
        f"{snap.get('goodput_ratio_total', 0.0):.1%} lifetime"
        + (f", windowed {ratio:.1%}" if ratio is not None else "")
        + ")",
        "",
        f"  {'SEGMENT':<20} {'SECONDS':>10} {'SHARE':>7} {'COUNT':>7}",
    ]
    if not segments:
        lines.append("  (no segments recorded yet)")
    for seg in sorted(
        segments, key=lambda s: -segments[s].get("seconds", 0.0)
    ):
        st = segments[seg]
        mark = " *" if snap.get("open") == seg else ""
        lines.append(
            f"  {seg + mark:<20} {st.get('seconds', 0.0):>10.3f} "
            f"{st.get('share', 0.0):>7.1%} {st.get('count', 0):>7}"
        )
    res = snap.get("residual_s")
    if res is not None and segments:
        lines.append(
            f"  {'(residual)':<20} {res:>10.3f} "
            f"{snap.get('residual_share', 0.0):>7.1%} {'':>7}"
        )
    ck = snap.get("checkpoint") or {}
    ops = ck.get("ops") or {}
    if ops or ck.get("last_bytes") is not None:
        parts = []
        for op in sorted(ops):
            d = ops[op]
            cell = f"{op} p95 {d.get('p95_s', 0.0):.2f}s"
            if d.get("failures"):
                cell += f" ({d['failures']:.0f} failed)"
            parts.append(cell)
        if ck.get("last_bytes") is not None:
            parts.append(f"last {ck['last_bytes'] / 1e6:.2f} MB")
        lines.append("")
        lines.append("checkpoints: " + ", ".join(parts))
    strag = snap.get("straggler")
    hosts = snap.get("hosts", {})
    if strag is not None:
        lines.append(
            f"straggler: {strag['host']} at "
            f"{strag.get('skew_ratio', 0.0):.2f}x the median step "
            f"({len(hosts)} hosts reporting)"
        )
    elif hosts:
        lines.append(f"straggler: none ({len(hosts)} host(s) reporting)")
    incidents = snap.get("incidents", [])
    counts = snap.get("incident_counts", {})
    if incidents:
        lines.append("")
        lines.append("INCIDENTS  (oldest first)")
        lines.append(
            f"  {'T(S)':>9} {'KIND':<11} {'TRACE':<17} EVENT / DETAIL"
        )
        for inc in incidents:
            what = " — ".join(
                x for x in (inc.get("event"), inc.get("detail")) if x
            )
            lines.append(
                f"  {inc.get('t', 0.0):>9.1f} {inc.get('kind', '?'):<11} "
                f"{(inc.get('trace_id') or '-')[:16]:<17} {what}"
            )
    elif counts:
        lines.append("")
        lines.append(
            "incidents (counters only — the timeline lives on "
            "/debug/goodput): "
            + ", ".join(f"{k}={v:.0f}" for k, v in sorted(counts.items()))
        )
    return "\n".join(lines)


def render_probes(snap: dict) -> str:
    """The ``obs probes`` view of one ``/debug/probes`` snapshot: the
    fleet-wide probe config line, one row per replica (FSM state, the
    K-of-N window drawn as ``.``/``x``, failure tally by reason, last
    outside-in latencies), then recent FSM transitions."""
    fsm = snap.get("fsm", {})
    golden = snap.get("golden") or "(unset)"
    lines = [
        f"CANARY PROBES  (round {snap.get('rounds', 0)}, every "
        f"{snap.get('interval_s', 0):g}s, deadline "
        f"{snap.get('deadline_s', 0):g}s, golden {golden}, fsm "
        f"{fsm.get('fail_k', '?')}-of-{fsm.get('window_n', '?')} fail / "
        f"{fsm.get('recover_k', '?')} recover)",
        "",
        f"  {'REPLICA':<18} {'STATE':<10} {'WINDOW':<8} {'PROBES':>7} "
        f"{'FAILURES':<22} {'TTFT(MS)':>9} {'TPOT(MS)':>9}  LAST",
    ]
    replicas = snap.get("replicas", {})
    if not replicas:
        lines.append("  (no probe targets registered)")
    for name, rep in replicas.items():
        window = "".join(
            "." if o else "x" for o in rep.get("window", [])
        ) or "-"
        fails = rep.get("failures", {})
        failcell = (
            ",".join(f"{k}={v}" for k, v in fails.items()) if fails
            else "-"
        )
        last = rep.get("last", {})
        lastcell = (
            ("ok" if last.get("ok") else last.get("reason") or "?")
            if last else "-"
        )
        lines.append(
            f"  {name:<18} {rep.get('state', '?'):<10} {window:<8} "
            f"{rep.get('probes', 0):>7} {failcell:<22} "
            f"{last.get('ttft_s', 0.0) * 1000:>9.1f} "
            f"{last.get('tpot_s', 0.0) * 1000:>9.1f}  {lastcell}"
        )
    transitions = [
        {**t, "replica": name}
        for name, rep in replicas.items()
        for t in rep.get("transitions", [])
    ]
    if transitions:
        transitions.sort(key=lambda t: (t.get("t", 0.0), t["replica"]))
        lines.append("")
        lines.append("TRANSITIONS  (oldest first)")
        for t in transitions:
            lines.append(
                f"  {t.get('t', 0.0):>9.1f} {t['replica']:<18} "
                f"{t.get('from', '?')} -> {t.get('to', '?')}"
            )
    return "\n".join(lines)


def render_waterfall(snap: dict) -> str:
    """The ``obs waterfall`` view.  A listing snapshot (``/debug/
    waterfall``) renders one line per stitched request; a single-trace
    snapshot (``?trace_id=``) renders the per-segment table with the
    critical-path segment starred, the attempt timeline (a rehash shows
    the dead replica's attempt AND the survivor's), and the per-process
    clock-skew line — the honesty report, never hidden."""
    if "traces" in snap:
        traces = snap.get("traces", [])
        lines = [
            f"FLEET WATERFALL  ({len(traces)} stitched requests, "
            f"{snap.get('scrapes', 0)} scrapes)",
            "",
            f"  {'TRACE':<34} {'E2E(MS)':>9} {'TTFT(MS)':>9} "
            f"{'HOPS':>5} {'CRITICAL':<14} FLAGS",
        ]
        if not traces:
            lines.append("  (no stitched request traces yet)")
        for t in traces:
            ttft = t.get("ttft_s")
            lines.append(
                f"  {t['trace_id']:<34} {t['e2e_s'] * 1000:>9.2f} "
                f"{(f'{ttft * 1000:.2f}' if ttft is not None else '-'):>9} "
                f"{t.get('attempts', 0):>5} {t.get('critical', '?'):<14} "
                f"{'missing-spans' if t.get('missing_spans') else '-'}"
            )
        return "\n".join(lines)
    e2e = snap.get("e2e_s", 0.0)
    ttft = snap.get("ttft_s")
    lines = [
        f"WATERFALL  trace {snap.get('trace_id', '?')}  "
        f"(e2e {e2e * 1000:.2f} ms"
        + (f", ttft {ttft * 1000:.2f} ms" if ttft is not None else "")
        + (", MISSING SPANS" if snap.get("missing_spans") else "")
        + ")",
        "",
        f"  {'SEGMENT':<16} {'SECONDS':>12} {'SHARE':>7} {'TTFT(MS)':>9}",
    ]
    segments = snap.get("segments", {})
    tseg = snap.get("ttft_segments") or {}
    for seg in sorted(
        segments, key=lambda s: -segments[s].get("seconds", 0.0)
    ):
        st = segments[seg]
        mark = " *" if snap.get("critical") == seg else ""
        tv = tseg.get(seg)
        lines.append(
            f"  {seg + mark:<16} {st.get('seconds', 0.0):>12.6f} "
            f"{st.get('share', 0.0):>7.1%} "
            f"{(f'{tv * 1000:.2f}' if tv is not None else '-'):>9}"
        )
    attempts = snap.get("attempts", [])
    if attempts:
        lines.append("")
        lines.append(
            f"  {'#':>3} {'REPLICA':<18} {'OUTCOME':<9} {'START(MS)':>10} "
            f"{'END(MS)':>10}  SERVER SPAN"
        )
        for a in attempts:
            lines.append(
                f"  {a.get('attempt', 0):>3} {a.get('replica', '?'):<18} "
                f"{a.get('outcome', '?'):<9} "
                f"{a.get('start_s', 0.0) * 1000:>10.2f} "
                f"{a.get('end_s', 0.0) * 1000:>10.2f}  "
                f"{'yes' if a.get('server_span') else 'MISSING'}"
            )
    procs = snap.get("processes", {})
    if procs:
        lines.append("")
        cells = []
        for p in sorted(procs):
            info = procs[p]
            off = info.get("offset_s", 0.0)
            # Monotonic origins differ by arbitrary amounts (process
            # uptimes) — sub-second offsets are the readable-in-ms case.
            cell = f"{p} " + (
                f"{off * 1000:+.3f}ms" if abs(off) < 1.0
                else f"{off:+.3f}s"
            )
            cell += (
                f" ({info.get('pairs', 0)} pairs)"
                if info.get("aligned") else " (UNALIGNED)"
            )
            cells.append(cell)
        lines.append("clock skew vs gateway: " + ", ".join(cells))
    net = snap.get("network")
    if net:
        lines.append(
            f"network gap: request {net.get('request_s', 0.0) * 1000:.3f}ms"
            f" / response {net.get('response_s', 0.0) * 1000:.3f}ms "
            "(symmetric-legs assumption — see docs)"
        )
    return "\n".join(lines)


def render_slo(families: dict) -> str:
    """The ``obs slo`` view over parsed ``/metrics`` families
    (``parse_exposition`` shape: ``{name: {label_tuple: value}}``):
    per-objective budget remaining + fast/slow burn, and the
    per-replica probe-health gauge underneath — the error-budget
    plane at a glance."""
    remaining = families.get("slo_budget_remaining_ratio", {})
    fast = families.get("slo_burn_rate_fast", {})
    slow = families.get("slo_burn_rate_slow", {})
    lines = ["SLO ERROR BUDGETS", ""]
    if not remaining:
        lines.append(
            "  (no slo_budget_remaining_ratio series — is the rules "
            "engine ticking with the slo pack?)"
        )
    else:
        lines.append(
            f"  {'SLO':<22} {'BUDGET LEFT':>12} {'FAST BURN':>10} "
            f"{'SLOW BURN':>10}"
        )
        for lbls in sorted(remaining):
            slo = dict(lbls).get("slo", "?")
            f_burn = fast.get(lbls)
            s_burn = slow.get(lbls)
            lines.append(
                f"  {slo:<22} {remaining[lbls]:>12.2%} "
                f"{(f'{f_burn:.2f}x' if f_burn is not None else '-'):>10} "
                f"{(f'{s_burn:.2f}x' if s_burn is not None else '-'):>10}"
            )
    health = families.get("probe_replica_healthy", {})
    if health:
        lines.append("")
        lines.append(f"  {'REPLICA':<22} {'PROBE HEALTH':>12}")
        state = {1.0: "healthy", 0.5: "degraded", 0.0: "UNHEALTHY"}
        for lbls in sorted(health):
            v = health[lbls]
            lines.append(
                f"  {dict(lbls).get('replica', '?'):<22} "
                f"{state.get(v, f'{v:g}'):>12}"
            )
    return "\n".join(lines)


def render_lint(report: dict) -> str:
    """The ``obs lint`` table view of a graftcheck report
    (``k8s_gpu_tpu.analysis.run_report`` shape): per-rule counts, then
    each new finding and stale baseline entry.  Deterministic — the
    report carries no timestamps and findings arrive pre-sorted."""
    new = report["new"]
    lines = [
        "GRAFTCHECK  "
        f"({len(new)} new, {report['suppressed']} baselined, "
        f"{len(report['stale'])} stale baseline)",
    ]
    by_rule: dict[str, int] = {}
    for f in new:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    if by_rule:
        lines.append("")
        lines.append(f"  {'RULE':<22} {'COUNT':>5}")
        for rule in sorted(by_rule):
            lines.append(f"  {rule:<22} {by_rule[rule]:>5}")
        lines.append("")
        for f in new:
            lines.append(f"  {f.path}:{f.line}")
            lines.append(f"    [{f.rule}] {f.message}")
    for path, rule, detail in report["stale"]:
        lines.append(
            f"  STALE baseline entry: {path} [{rule}] {detail} — "
            "remove it from config/analysis_baseline.json"
        )
    lines.append("")
    lines.append(
        "clean — every contract holds" if report["ok"]
        else "FAIL — fix the findings or (for pre-existing debt only) "
             "pin them: python -m k8s_gpu_tpu.analysis --write-baseline"
    )
    return "\n".join(lines)


def render_route(decision, snap: dict) -> str:
    """The ``obs route`` explain view: one routing decision (a
    ``serve.router.RouteDecision``) plus the router snapshot's
    per-replica table — why THIS replica, and what the alternatives
    scored."""
    lines = [
        f"ROUTE  -> {decision.replica}  ({decision.reason}; chain depth "
        f"{decision.chain_depth}, warm depth {decision.warm_depth})",
        "",
        f"  {'REPLICA':<18} {'SCORE':>8} {'CHAINS':>7} {'LOAD':>7} "
        f"{'FLAGS':<18}",
    ]
    by_name = {r["replica"]: r for r in snap.get("replicas", [])}
    for name in sorted(set(decision.scores) | set(by_name)):
        r = by_name.get(name, {})
        flags = [f for f in ("hot", "draining", "down") if r.get(f)]
        score = decision.scores.get(name)
        lines.append(
            f"  {name + (' *' if name == decision.replica else ''):<18} "
            f"{(f'{score:+.3f}' if score is not None else '-'):>8} "
            f"{r.get('chains', 0):>7} "
            f"{_flatval(r.get('load'), '{:.1%}'):>7} "
            f"{','.join(flags) or '-':<18}"
        )
    return "\n".join(lines)
