"""Rendezvous env contract — the pure (jax-free) half of multi-host
orchestration, importable by the control plane.

The trainjob controller injects these variables into worker pods (the
Kubeflow-operator PET_* role, reference GPU调度平台搭建.md:606-630); the
workload side (`parallel/multihost.py`) consumes them with
``jax.distributed.initialize``.  Split out so reconcilers never import
the JAX runtime just to render pod env.
"""

from __future__ import annotations

from dataclasses import dataclass

ENV_COORDINATOR = "TPU_COORDINATOR_ADDRESS"
ENV_PROCESS_ID = "TPU_PROCESS_ID"
ENV_PROCESS_COUNT = "TPU_PROCESS_COUNT"


@dataclass(frozen=True)
class HostEnv:
    """The per-host rendezvous env the trainjob controller injects."""

    coordinator_address: str
    process_id: int
    process_count: int

    def as_env(self) -> dict[str, str]:
        return {
            ENV_COORDINATOR: self.coordinator_address,
            ENV_PROCESS_ID: str(self.process_id),
            ENV_PROCESS_COUNT: str(self.process_count),
        }


def rendezvous_env(
    hosts: int, coordinator_host: str = "localhost", port: int = 8476
) -> list[HostEnv]:
    """Env for each of *hosts* workers; worker 0's host is the coordinator
    (the torchrun master_addr convention)."""
    addr = f"{coordinator_host}:{port}"
    return [HostEnv(addr, i, hosts) for i in range(hosts)]
