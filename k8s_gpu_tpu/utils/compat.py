"""Workarounds for upstream bugs in pinned dependencies.

The reference has no analogue (it pins no accelerator runtime at all);
this module exists because the framework drives JAX from background
threads (serve/batcher.py's scheduler loop) and the environment's jaxlib
has a thread-safety bug in its CPU compiler.
"""

from __future__ import annotations

import contextlib
import threading

_compile_lock = threading.Lock()
_install_lock = threading.Lock()
_installed = False


@contextlib.contextmanager
def large_thread_stack(nbytes: int = 64 << 20):
    """Start threads under an enlarged fixed stack.

    ``threading.stack_size`` is consumed at OS-thread creation inside
    ``Thread.start()`` — NOT at ``Thread()`` construction — so this must
    wrap the ``.start()`` call.  XLA's CPU codegen recurses deeply
    enough to blow a worker thread's default stack (segfault inside
    ``backend_compile_and_load`` with no concurrent compile); the
    growable main-thread stack never hits this, so only spawned
    compile-capable threads need it."""
    try:
        prev = threading.stack_size(nbytes)
    except (ValueError, RuntimeError):
        prev = None
    try:
        yield
    finally:
        if prev is not None:
            threading.stack_size(prev)


def serialize_xla_compiles() -> None:
    """Serialize all XLA backend compiles behind one process-wide lock.

    This jaxlib's CPU compiler segfaults when two threads compile
    concurrently — observed repeatedly in full-suite runs as a hard
    ``Fatal Python error: Segmentation fault`` inside
    ``jax._src.compiler.backend_compile_and_load`` with a second thread
    (the continuous batcher's scheduler loop) also inside a compile.
    Compilation is a tiny fraction of steady-state serving time, so the
    lock costs nothing once programs are warm.

    Idempotent; call early (before the racing threads start).  Wraps a
    private jax API on purpose: the environment pins jax/jaxlib, and the
    patch degrades to a no-op wrapper on any version that has fixed the
    underlying race."""
    global _installed
    with _install_lock:  # two threads racing here must not double-wrap
        if _installed:
            return
        from jax._src import compiler as _compiler

        orig = getattr(_compiler, "backend_compile_and_load", None)
        if orig is None:
            # A jax that renamed the private symbol presumably also
            # fixed the race — degrade to a no-op as documented.
            _installed = True
            return

        def locked(*args, **kwargs):
            with _compile_lock:
                return orig(*args, **kwargs)

        _compiler.backend_compile_and_load = locked
        _installed = True
