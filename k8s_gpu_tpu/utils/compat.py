"""Workarounds for upstream bugs in pinned dependencies.

The reference has no analogue (it pins no accelerator runtime at all);
this module exists because the framework drives JAX from background
threads (serve/batcher.py's scheduler loop) and the environment's jaxlib
has a thread-safety bug in its CPU compiler.
"""

from __future__ import annotations

import contextlib
import threading

_compile_lock = threading.Lock()
_install_lock = threading.Lock()
_installed = False
_telemetry_installed = False
_telemetry_registry = None


@contextlib.contextmanager
def large_thread_stack(nbytes: int = 64 << 20):
    """Start threads under an enlarged fixed stack.

    ``threading.stack_size`` is consumed at OS-thread creation inside
    ``Thread.start()`` — NOT at ``Thread()`` construction — so this must
    wrap the ``.start()`` call.  XLA's CPU codegen recurses deeply
    enough to blow a worker thread's default stack (segfault inside
    ``backend_compile_and_load`` with no concurrent compile); the
    growable main-thread stack never hits this, so only spawned
    compile-capable threads need it."""
    try:
        prev = threading.stack_size(nbytes)
    except (ValueError, RuntimeError):
        prev = None
    try:
        yield
    finally:
        if prev is not None:
            threading.stack_size(prev)


def serialize_xla_compiles() -> None:
    """Serialize all XLA backend compiles behind one process-wide lock.

    This jaxlib's CPU compiler segfaults when two threads compile
    concurrently — observed repeatedly in full-suite runs as a hard
    ``Fatal Python error: Segmentation fault`` inside
    ``jax._src.compiler.backend_compile_and_load`` with a second thread
    (the continuous batcher's scheduler loop) also inside a compile.
    Compilation is a tiny fraction of steady-state serving time, so the
    lock costs nothing once programs are warm.

    Idempotent; call early (before the racing threads start).  Wraps a
    private jax API on purpose: the environment pins jax/jaxlib, and the
    patch degrades to a no-op wrapper on any version that has fixed the
    underlying race."""
    global _installed
    with _install_lock:  # two threads racing here must not double-wrap
        if _installed:
            return
        from jax._src import compiler as _compiler

        orig = getattr(_compiler, "backend_compile_and_load", None)
        if orig is None:
            # A jax that renamed the private symbol presumably also
            # fixed the race — degrade to a no-op as documented.
            _installed = True
            return

        def locked(*args, **kwargs):
            with _compile_lock:
                return orig(*args, **kwargs)

        _compiler.backend_compile_and_load = locked
        _installed = True


def install_compile_telemetry(registry=None) -> None:
    """Promote XLA compile counting from a test-only conftest fixture
    into runtime telemetry: every real backend compile (the
    ``/jax/core/compile/backend_compile_duration`` jax.monitoring event
    — executable-cache hits fire nothing) bumps ``xla_compiles_total``
    and lands its duration in ``xla_compile_seconds``.

    Steady-state continuous batching compiles ZERO new executables after
    warmup (the recompile guard ``tests/conftest.py`` pins in CI); a
    nonzero steady-state rate is the silent killer — every stray compile
    is seconds of dead air per occurrence on a tunneled TPU — and the
    ``CompileStorm`` rule in ``utils.alerts.default_rule_pack`` alerts
    on exactly this counter's rate.

    Idempotent and process-global (jax.monitoring has no per-listener
    unregister): the first caller's *registry* wins — pass one only in
    single-registry processes; the default is the process-global
    registry, which is correct for multi-replica processes too (compiles
    are a per-process resource, not a per-replica one)."""
    global _telemetry_installed, _telemetry_registry
    with _install_lock:
        if _telemetry_installed:
            return
        from .metrics import global_metrics

        _telemetry_registry = registry if registry is not None else global_metrics
        import jax

        def _on_event(event, duration, **kw):
            if event == "/jax/core/compile/backend_compile_duration":
                _telemetry_registry.inc("xla_compiles_total")
                _telemetry_registry.observe(
                    "xla_compile_seconds", float(duration)
                )

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _telemetry_installed = True


def xla_compile_count() -> int:
    """Process-wide backend-compile count from the installed telemetry
    (0 until ``install_compile_telemetry`` has run) — the recompile
    guard's read surface: ``snap = xla_compile_count(); ...;
    assert xla_compile_count() == snap``."""
    if _telemetry_registry is None:
        return 0
    return int(_telemetry_registry.counter("xla_compiles_total"))
