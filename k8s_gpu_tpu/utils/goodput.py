"""Training goodput ledger & incident flight recorder — where a run's
wall-clock went, and what interrupted it.

The training plane so far exports instantaneous gauges
(``train_last_step_seconds``, ``train_mfu``) — rates, not an account.
ROADMAP item 4's claim ("training resumes within one step of a
preemption") is unprovable without one: you need the run's *elapsed*
time partitioned into productive steps vs everything else, and a
timeline of the preemptions/evictions/restarts that carved it up.
VirtualFlow frames elasticity as delivered-vs-ideal throughput across
resource changes; this module is that measurement substrate:

- **GoodputLedger** — a Clock-driven, exhaustive, NON-overlapping
  partition of the run's wall-clock into named segments (``SEGMENTS``
  below).  Exactly one segment is open at a time (``begin`` closes the
  previous one at the same instant); time between an ``end`` and the
  next ``begin`` is the *residual* — unattributed but never lost:
  ``sum(segments) + residual == elapsed`` exactly, the same honest
  remainder the phase profiler reports.  Productive time is the
  ``step`` segment; ``train_goodput_ratio`` is productive share over a
  rolling window (so the gauge recovers after an outage leaves the
  window), and every non-productive segment close feeds
  ``train_nonproductive_seconds_total{segment}``.
- **incident timeline** — a bounded ring of
  preemption/eviction/restart/resize events, each stamped with the
  active trace id and the operator Event that caused it
  (``record_incident`` is the operators' cross-stamp hook: the
  TrainJob restart seam and the TpuPodSlice broken-queued-resource
  seam call it next to their Warning Events).
- **straggler attribution** — per-host step heartbeats; the slowest
  host's EWMA over the median is ``train_step_skew_ratio`` and the
  host itself is named by ``train_straggler_host{host}``.

All time flows through an injected ``utils.clock.Clock`` (default
``RealClock``); under ``FakeClock``/``TickingFakeClock`` two scripted
runs serialize byte-identical ``/debug/goodput`` bodies — this module
is in graftcheck's determinism planes, the same contract the profiler,
alert FSM and federation collector keep.  The chaos path is
``utils/faults.py``: ``Trainer.fit`` fires the ``train.preempt`` site
each iteration, so a seeded plan preempts mid-fit deterministically
and the ledger records the ``preempted`` segment + incident.

Metric families (documented in ``docs/platform/observability.md``;
graftcheck keeps doc and code in sync): ``train_goodput_ratio``,
``train_nonproductive_seconds_total{segment}``,
``train_incidents_total{kind}``, ``train_step_skew_ratio``,
``train_straggler_host{host}``.  The checkpoint families
(``train_checkpoint_seconds{op}``, ``train_checkpoint_bytes``,
``train_checkpoint_failures_total{op}``) are minted by
``train/checkpoint.py`` and assembled into the ``/debug/goodput`` body
here.
"""

from __future__ import annotations

import threading
from collections import deque
from contextlib import contextmanager

from .clock import Clock, RealClock
from .metrics import MetricsRegistry, global_metrics, parse_exposition

# The exhaustive segment taxonomy.  ``step`` is the only productive
# segment — goodput is optimizer progress, and everything else (even
# compile, even checkpoints) is overhead the ratio must charge for.
SEGMENTS = (
    "init", "compile", "data_wait", "step", "checkpoint_save",
    "checkpoint_restore", "preempted", "reshard", "idle",
)
PRODUCTIVE = ("step",)

# Incident kinds the flight recorder accepts — anything else raises, so
# a typo'd kind can't silently mint a new counter series.
INCIDENT_KINDS = (
    "preemption", "eviction", "restart", "resize", "resume",
)


class _SegStat:
    """Cumulative per-segment accounting (guarded by the ledger lock)."""

    __slots__ = ("count", "total_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0


class GoodputLedger:
    """Clock-driven wall-clock partition + incident ring for one run.

    ``window_s`` is the rolling window the ``train_goodput_ratio``
    gauge is computed over — cumulative ratio never recovers from a
    long outage, windowed ratio does once productive steps refill the
    window.  ``max_incidents``/``max_samples`` bound the incident ring
    and the windowed sample ring.

    Threading: recording (``begin``/``end``/``heartbeat``/``incident``)
    and reading (``snapshot`` on an HTTP thread) share the lock;
    metric writes happen outside it (the registry has its own).
    """

    _GUARDED_BY = {
        "_lock": ("_totals", "_open", "_window", "_win_prod",
                  "_incidents", "_hosts", "_straggler"),
    }

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        clock: Clock | None = None,
        window_s: float = 300.0,
        max_incidents: int = 256,
        max_samples: int = 2048,
        ewma_alpha: float = 0.3,
    ):
        self.registry = registry if registry is not None else global_metrics
        self.clock = clock or RealClock()
        self.window_s = max(1e-6, float(window_s))
        self.alpha = min(1.0, max(1e-6, float(ewma_alpha)))
        self._lock = threading.Lock()
        self._t0 = self.clock.now()
        self._totals: dict[str, _SegStat] = {}
        self._open: tuple[str, float] | None = None  # (segment, start)
        # Rolling (t_end, segment, dt) closed samples + incremental
        # productive-seconds sum — the windowed-ratio math, profiler
        # idiom (manual bound so every eviction subtracts its append).
        self._max_samples = max(64, int(max_samples))
        self._window: "deque[tuple]" = deque()
        self._win_prod = 0.0
        self._incidents: "deque[dict]" = deque(maxlen=max(8, max_incidents))
        # host -> {"step", "t", "last_s", "ewma_s"}
        self._hosts: dict[str, dict] = {}
        self._straggler: str | None = None

    # -- the segment partition ---------------------------------------------
    def begin(self, segment: str) -> None:
        """Open *segment*, closing the currently-open one (if any) at
        the same instant — the partition never overlaps and never gaps
        across a begin→begin chain."""
        if segment not in SEGMENTS:
            raise ValueError(
                f"unknown goodput segment {segment!r}; one of {SEGMENTS}"
            )
        now = self.clock.now()
        with self._lock:
            closed = self._close_locked(now)
            self._open = (segment, now)
        self._export_closed(closed, now)

    def end(self) -> None:
        """Close the open segment.  Time until the next ``begin`` is
        residual — reported, never silently attributed.  No-op when
        nothing is open."""
        now = self.clock.now()
        with self._lock:
            closed = self._close_locked(now)
            self._open = None
        self._export_closed(closed, now)

    @contextmanager
    def segment(self, name: str):
        """``with ledger.segment("data_wait"): ...`` — the exception-
        safe form.  Segments are FLAT, not nested: entering one while
        another is open closes the outer one (the partition stays
        non-overlapping by construction)."""
        self.begin(name)
        try:
            yield
        finally:
            self.end()

    def _close_locked(self, now: float):
        """Fold the open segment into totals + window.  Lock held.
        Returns ``(segment, dt)`` or None for the metric export the
        caller performs outside the lock."""
        if self._open is None:
            return None
        seg, start = self._open
        dt = max(0.0, now - start)
        st = self._totals.get(seg)
        if st is None:
            st = self._totals[seg] = _SegStat()
        st.count += 1
        st.total_s += dt
        self._evict_locked(now - self.window_s)
        while len(self._window) >= self._max_samples:
            _, old_seg, old_dt = self._window.popleft()
            if old_seg in PRODUCTIVE:
                self._win_prod -= old_dt
        self._window.append((now, seg, dt))
        if seg in PRODUCTIVE:
            self._win_prod += dt
        return (seg, dt)

    def _evict_locked(self, cut: float) -> None:
        while self._window and self._window[0][0] < cut:
            _, seg, dt = self._window.popleft()
            if seg in PRODUCTIVE:
                self._win_prod -= dt

    def _export_closed(self, closed, now: float) -> None:
        """Registry writes for one closed segment — outside the lock."""
        if closed is None:
            return
        seg, dt = closed
        if seg not in PRODUCTIVE and dt > 0.0:
            self.registry.inc(
                "train_nonproductive_seconds_total", dt, segment=seg
            )
        self.registry.set_gauge(
            "train_goodput_ratio", self._windowed_ratio(now)
        )

    # -- goodput -----------------------------------------------------------
    def _windowed_ratio(self, now: float) -> float:
        """Productive share of the trailing window.  The open segment's
        elapsed-so-far counts toward its kind, so a long outage drags
        the ratio down WHILE it is happening, not only at close."""
        with self._lock:
            self._evict_locked(now - self.window_s)
            prod = max(0.0, self._win_prod)
            if self._open is not None and self._open[0] in PRODUCTIVE:
                prod += max(0.0, now - self._open[1])
        span = min(self.window_s, max(1e-9, now - self._t0))
        return min(1.0, prod / span)

    def goodput_ratio(self) -> float:
        """The windowed ratio, read fresh (the gauge's value source)."""
        return self._windowed_ratio(self.clock.now())

    def export_gauges(self) -> None:
        """Refresh ``train_goodput_ratio`` from the current instant —
        register this as a ``RuleEvaluator`` collector so the gauge
        decays DURING an outage (no segment closes while preempted,
        so close-driven refresh alone would leave it stale)."""
        self.registry.set_gauge(
            "train_goodput_ratio", self._windowed_ratio(self.clock.now())
        )

    # -- incidents ---------------------------------------------------------
    def incident(
        self,
        kind: str,
        detail: str = "",
        trace_id: str = "",
        event: str = "",
    ) -> None:
        """Append one flight-recorder entry.  ``trace_id`` defaults to
        the calling thread's active tracing span (the operator Event
        handlers and the chaos seam run under one); ``event`` names the
        operator Event that caused it (``"Warning/Restarting ns/job"``)."""
        if kind not in INCIDENT_KINDS:
            raise ValueError(
                f"unknown incident kind {kind!r}; one of {INCIDENT_KINDS}"
            )
        if not trace_id:
            from .tracing import global_tracer

            ctx = global_tracer.current()
            trace_id = ctx.trace_id if ctx is not None else ""
        now = self.clock.now()
        rec = {
            "t": round(now, 9),
            "kind": kind,
            "detail": detail,
            "trace_id": trace_id,
            "event": event,
        }
        with self._lock:
            self._incidents.append(rec)
        self.registry.inc("train_incidents_total", kind=kind)

    # -- straggler attribution ---------------------------------------------
    def heartbeat(self, host: str, step: int, step_seconds: float) -> None:
        """One host's per-step heartbeat.  With >= 2 reporting hosts the
        slowest EWMA over the median EWMA is the skew ratio, and the
        slowest host is published as ``train_straggler_host{host}``
        (value: its EWMA step seconds).  In a gang-scheduled step every
        host waits for the slowest — the skew ratio IS the wasted
        fraction."""
        now = self.clock.now()
        dt = max(0.0, float(step_seconds))
        with self._lock:
            h = self._hosts.get(host)
            if h is None:
                h = self._hosts[host] = {
                    "step": 0, "t": now, "last_s": 0.0, "ewma_s": 0.0,
                }
                h["ewma_s"] = dt
            else:
                h["ewma_s"] = self.alpha * dt + (1.0 - self.alpha) * h["ewma_s"]
            h["step"] = int(step)
            h["t"] = now
            h["last_s"] = dt
            skew, slowest, prev = self._skew_locked()
            self._straggler = slowest
        self.registry.set_gauge("train_step_skew_ratio", skew)
        if prev is not None and prev != slowest:
            self.registry.remove_gauge("train_straggler_host", host=prev)
        if slowest is not None:
            with self._lock:
                val = self._hosts[slowest]["ewma_s"]
            self.registry.set_gauge(
                "train_straggler_host", val, host=slowest
            )
        self.registry.set_gauge(
            "train_goodput_ratio", self._windowed_ratio(now)
        )

    def _skew_locked(self):
        """``(skew_ratio, straggler_host | None, previous_straggler)``.
        Lock held.  One host reports skew 1.0 and no straggler —
        attribution needs a comparison set."""
        prev = self._straggler
        if len(self._hosts) < 2:
            return 1.0, None, prev
        ewmas = sorted(
            (h["ewma_s"], name) for name, h in sorted(self._hosts.items())
        )
        slowest_s, slowest = ewmas[-1]
        mid = ewmas[len(ewmas) // 2][0] if len(ewmas) % 2 else (
            (ewmas[len(ewmas) // 2 - 1][0] + ewmas[len(ewmas) // 2][0]) / 2.0
        )
        skew = slowest_s / max(1e-9, mid)
        return skew, slowest, prev

    # -- read surface ------------------------------------------------------
    def snapshot(self) -> dict:
        """The ledger's half of the ``/debug/goodput`` body.  The open
        segment's elapsed-so-far is folded into its segment entry, so
        ``sum(seconds) + residual_s == elapsed_s`` EXACTLY — the
        exhaustive-partition invariant tests pin bit-for-bit under
        FakeClock.  All floats are ``round(x, 9)`` and every dict
        iterates sorted, so two identically-scripted runs serialize
        byte-identically (``json.dumps(..., sort_keys=True)``)."""
        now = self.clock.now()
        elapsed = max(0.0, now - self._t0)
        with self._lock:
            totals = {
                seg: (st.count, st.total_s)
                for seg, st in self._totals.items()
            }
            open_seg = self._open
            incidents = list(self._incidents)
            hosts = {
                name: dict(h) for name, h in self._hosts.items()
            }
            skew, slowest, _ = self._skew_locked()
        if open_seg is not None:
            seg, start = open_seg
            count, total = totals.get(seg, (0, 0.0))
            totals[seg] = (count + 1, total + max(0.0, now - start))
        attributed = sum(t for _, t in totals.values())
        residual = max(0.0, elapsed - attributed)
        productive = sum(
            totals.get(seg, (0, 0.0))[1] for seg in PRODUCTIVE
        )
        segments = {}
        for seg in sorted(totals):
            count, total = totals[seg]
            segments[seg] = {
                "count": count,
                "seconds": round(total, 9),
                "share": round(total / elapsed, 9) if elapsed > 0 else 0.0,
            }
        return {
            "now": round(now, 9),
            "started": round(self._t0, 9),
            "elapsed_s": round(elapsed, 9),
            "window_s": self.window_s,
            "segments": segments,
            "open": open_seg[0] if open_seg is not None else None,
            "residual_s": round(residual, 9),
            "residual_share": (
                round(residual / elapsed, 9) if elapsed > 0 else 0.0
            ),
            "productive_s": round(productive, 9),
            "goodput_ratio": round(self._windowed_ratio(now), 9),
            "goodput_ratio_total": (
                round(productive / elapsed, 9) if elapsed > 0 else 0.0
            ),
            "hosts": {
                name: {
                    "step": h["step"],
                    "last_s": round(h["last_s"], 9),
                    "ewma_s": round(h["ewma_s"], 9),
                    "age_s": round(max(0.0, now - h["t"]), 9),
                }
                for name, h in sorted(hosts.items())
            },
            "straggler": (
                {"host": slowest, "skew_ratio": round(skew, 9)}
                if slowest is not None else None
            ),
            "incidents": incidents,
        }


# -- operator cross-stamp hook ------------------------------------------------
#
# Operators (trainjob/tpupodslice reconcilers) run in the control plane
# and must not grow a constructor dependency on the training plane's
# ledger; instead the run that owns a ledger attaches it here and the
# operators' incident seams call the module function.  No ledger
# attached -> a no-op (the default outside training runs).

_ATTACH_LOCK = threading.Lock()
_LEDGERS: list[GoodputLedger] = []


def attach_ledger(ledger: GoodputLedger) -> None:
    with _ATTACH_LOCK:
        if ledger not in _LEDGERS:
            _LEDGERS.append(ledger)


def detach_ledger(ledger: GoodputLedger | None = None) -> None:
    """Detach one ledger, or every ledger when None (test teardown)."""
    with _ATTACH_LOCK:
        if ledger is None:
            _LEDGERS.clear()
        elif ledger in _LEDGERS:
            _LEDGERS.remove(ledger)


def record_incident(
    kind: str, detail: str = "", trace_id: str = "", event: str = ""
) -> None:
    """Cross-stamp an operator-observed incident into every attached
    ledger — called at the seams that also emit the Warning Event (the
    TrainJob ``Restarting`` block, the TpuPodSlice broken-queued-
    resource deletion), so the flight recorder and the Event stream
    tell one story."""
    with _ATTACH_LOCK:
        sinks = list(_LEDGERS)
    for ledger in sinks:
        ledger.incident(kind, detail=detail, trace_id=trace_id, event=event)


# -- the /debug/goodput body --------------------------------------------------

def goodput_snapshot(
    ledger: GoodputLedger | None = None,
    registry: MetricsRegistry | None = None,
) -> dict:
    """The full ``/debug/goodput`` JSON body: the ledger's partition +
    incident timeline plus the registry-resident checkpoint telemetry
    (``train/checkpoint.py`` mints it).  Either half may be absent —
    the shape stays stable."""
    reg = registry if registry is not None else (
        ledger.registry if ledger is not None else global_metrics
    )
    snap = (
        ledger.snapshot() if ledger is not None
        else {
            "now": 0.0, "started": 0.0, "elapsed_s": 0.0, "window_s": 0.0,
            "segments": {}, "open": None, "residual_s": 0.0,
            "residual_share": 0.0, "productive_s": 0.0,
            "goodput_ratio": None, "goodput_ratio_total": 0.0,
            "hosts": {}, "straggler": None, "incidents": [],
        }
    )
    ckpt: dict[str, dict] = {}
    for lbls, q in sorted(
        reg.hist_percentiles("train_checkpoint_seconds", 0.95).items()
    ):
        op = dict(lbls).get("op")
        if op:
            ckpt[op] = {"p95_s": round(q, 9)}
    for lbls, v in sorted(
        reg.series("train_checkpoint_failures_total").items()
    ):
        op = dict(lbls).get("op")
        if op:
            ckpt.setdefault(op, {})["failures"] = v
    snap["checkpoint"] = {
        "ops": ckpt,
        "last_bytes": reg.gauge("train_checkpoint_bytes"),
    }
    return snap


def goodput_snapshot_from_exposition(text: str) -> dict:
    """Reconstruct a ``/debug/goodput``-shaped snapshot from one
    Prometheus text exposition (live scrape or the persisted
    ``metrics.prom``) — the ``obs goodput`` offline path.  Productive
    seconds come from the ``train_step_seconds`` histogram sum,
    non-productive from the per-segment counter, checkpoint
    percentiles from the cumulative buckets
    (``utils.federation.bucket_quantile``).  The incident RING does
    not ride the exposition — only the per-kind counters do — so
    ``incidents`` is empty and ``incident_counts`` carries what the
    scrape knows."""
    from .federation import bucket_quantile

    fams = parse_exposition(text)
    productive = sum(fams.get("train_step_seconds_sum", {}).values())
    step_count = int(sum(fams.get("train_step_seconds_count", {}).values()))
    totals: dict[str, float] = {}
    for lbls, v in sorted(
        fams.get("train_nonproductive_seconds_total", {}).items()
    ):
        seg = dict(lbls).get("segment")
        if seg:
            totals[seg] = totals.get(seg, 0.0) + v
    if productive > 0.0:
        totals["step"] = productive
    elapsed = sum(totals.values())
    segments = {
        seg: {
            "count": step_count if seg == "step" else 0,
            "seconds": round(t, 9),
            "share": round(t / elapsed, 9) if elapsed > 0 else 0.0,
        }
        for seg, t in sorted(totals.items())
    }
    ratio = None
    series = fams.get("train_goodput_ratio", {})
    if series:
        ratio = next(iter(series.values()))
    skew_series = fams.get("train_step_skew_ratio", {})
    skew = next(iter(skew_series.values())) if skew_series else None
    straggler = None
    for lbls, v in sorted(fams.get("train_straggler_host", {}).items()):
        host = dict(lbls).get("host")
        if host:
            straggler = {
                "host": host,
                "skew_ratio": skew if skew is not None else 0.0,
            }
    ckpt: dict[str, dict] = {}
    for op in ("restore", "save"):
        sub = {
            l: v
            for l, v in fams.get("train_checkpoint_seconds_bucket", {}).items()
            if dict(l).get("op") == op
        }
        if sub:
            ckpt[op] = {"p95_s": bucket_quantile(sub, 0.95) or 0.0}
    for lbls, v in sorted(
        fams.get("train_checkpoint_failures_total", {}).items()
    ):
        op = dict(lbls).get("op")
        if op:
            ckpt.setdefault(op, {})["failures"] = v
    bytes_series = fams.get("train_checkpoint_bytes", {})
    incident_counts = {
        dict(lbls).get("kind", "?"): v
        for lbls, v in sorted(fams.get("train_incidents_total", {}).items())
    }
    return {
        "now": 0.0,
        "started": 0.0,
        "elapsed_s": round(elapsed, 9),
        "window_s": 0.0,
        "segments": segments,
        "open": None,
        "residual_s": 0.0,
        "residual_share": 0.0,
        "productive_s": round(productive, 9),
        "goodput_ratio": ratio,
        "goodput_ratio_total": (
            round(productive / elapsed, 9) if elapsed > 0 else 0.0
        ),
        "hosts": {},
        "straggler": straggler,
        "incidents": [],
        "incident_counts": incident_counts,
        "checkpoint": {
            "ops": ckpt,
            "last_bytes": (
                next(iter(bytes_series.values())) if bytes_series else None
            ),
        },
    }
