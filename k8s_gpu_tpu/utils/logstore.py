"""Log aggregation — the stdout → Fluent Bit → Loki pipeline of the
reference (GPU调度平台搭建.md:798-800: container stdout shipped to
Loki/Elasticsearch, queried per job/pod from Grafana), in-process.

``LogStore`` holds bounded label-indexed streams with Loki-style selector
queries; ``LogStoreHandler`` is the Fluent Bit role — a ``logging.Handler``
that ships every controller log record into the store, labeled by logger
and level, so platform logs are queryable the way the reference's ops
manual describes.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass


@dataclass(frozen=True)
class LogEntry:
    ts: float
    line: str
    labels: tuple  # sorted (key, value) pairs


class LogStore:
    """Bounded, label-indexed log streams.

    A *stream* is a unique label set (Loki semantics).  Each stream keeps
    the newest ``max_lines_per_stream`` entries; queries select streams by
    exact label match and optionally filter by substring and time range.
    """

    def __init__(self, max_lines_per_stream: int = 10_000,
                 max_streams: int = 1_000):
        self._lock = threading.Lock()
        self._streams: dict[tuple, deque[LogEntry]] = {}
        self.max_lines_per_stream = max_lines_per_stream
        self.max_streams = max_streams
        self.dropped_streams = 0

    @staticmethod
    def _key(labels: dict[str, str]) -> tuple:
        return tuple(sorted(labels.items()))

    def push(self, labels: dict[str, str], line: str,
             ts: float | None = None) -> None:
        key = self._key(labels)
        with self._lock:
            stream = self._streams.get(key)
            if stream is None:
                if len(self._streams) >= self.max_streams:
                    # Evict the stream with the oldest newest-entry (the
                    # quietest one) instead of refusing new streams.
                    victim = min(
                        self._streams,
                        key=lambda k: self._streams[k][-1].ts
                        if self._streams[k] else 0,
                    )
                    del self._streams[victim]
                    self.dropped_streams += 1
                stream = self._streams[key] = deque(
                    maxlen=self.max_lines_per_stream
                )
            stream.append(LogEntry(ts if ts is not None else time.time(),
                                   line, key))

    def query(
        self,
        selector: dict[str, str] | None = None,
        contains: str = "",
        since: float = 0.0,
        limit: int = 1_000,
    ) -> list[LogEntry]:
        """Streams whose labels are a superset of *selector*, newest last."""
        sel = (selector or {}).items()
        out: list[LogEntry] = []
        with self._lock:
            for key, stream in self._streams.items():
                labels = dict(key)
                if not all(labels.get(k) == v for k, v in sel):
                    continue
                for e in stream:
                    if e.ts < since:
                        continue
                    if contains and contains not in e.line:
                        continue
                    out.append(e)
        out.sort(key=lambda e: e.ts)
        return out[-limit:]

    def streams(self) -> list[dict[str, str]]:
        with self._lock:
            return [dict(k) for k in self._streams]


class LogStoreHandler(logging.Handler):
    """The Fluent Bit role: ships log records into a LogStore, labeled by
    logger name and level (+ any static labels, e.g. component/namespace)."""

    def __init__(self, store: LogStore,
                 static_labels: dict[str, str] | None = None):
        super().__init__()
        self.store = store
        self.static_labels = dict(static_labels or {})

    def emit(self, record: logging.LogRecord) -> None:
        try:
            labels = {
                "logger": record.name,
                "level": record.levelname.lower(),
                **self.static_labels,
            }
            self.store.push(labels, self.format(record), ts=record.created)
        except Exception:  # a logging path must never raise into callers
            self.handleError(record)


global_logstore = LogStore()
