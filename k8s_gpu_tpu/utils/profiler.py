"""Continuous phase-level performance attribution — where the time goes.

ROADMAP items 2 (prefill/decode worker-ratio shifting), 3 (raw speed) and
5 (bandwidth-aware scheduling) all steer on the SAME missing signal: the
running system's per-phase time split.  Until now that split existed only
as a one-shot offline study (`docs/perf/mfu_breakdown.md` via
`tools/profile_step.py`) — a kernel win or regression was invisible until
the next manual bench.  This module is the always-on half:

- **PhaseProfiler** — named-phase instrumentation for a hot loop.  A
  per-thread phase *stack* attributes SELF time (entering a nested phase
  pauses the enclosing one), so wrapping coarse regions around fine ones
  keeps every phase disjoint and the shares a true partition.  Per phase:
  a bounded reservoir (exact p50/p95 over recent samples), an EWMA, and
  share-of-window accounting over a rolling wall window with the
  *residual* (unattributed time) reported — shares sum to <= 1.0 by
  construction.  All time flows through an injected ``utils.clock.Clock``
  (default ``RealClock``), so a ``FakeClock`` run is two-run
  bit-identical — the same determinism contract the alert FSM and the
  federation collector already keep (graftcheck enforces it: this module
  is in the determinism planes).
- **profile_snapshot** — the ``/debug/profile`` JSON body: per-phase
  p50/p95/ewma/share + residual, XLA compile telemetry
  (``xla_compiles_total`` / ``xla_compile_seconds``, installed by
  ``utils.compat.install_compile_telemetry``), and the per-axis
  collective bandwidth gauges (``parallel/collectives.py``).
- **chrome_trace** — Chrome/Perfetto trace-event export of the span ring
  (``/debug/traces`` shape) plus the profiler's rolling phase samples;
  ``obs profile --chrome-trace out.json`` writes it, and the file loads
  directly in ui.perfetto.dev.

Metric families (one label set each; ``docs/platform/observability.md``
documents them and graftcheck keeps the two in sync):
``serve_phase_seconds{phase}`` / ``serve_phase_share{phase}`` for the
serve plane (the continuous batcher's seams), ``train_phase_seconds`` /
``train_phase_share`` for the training runner, which also exports the
rolling ``train_mfu`` gauge.  For the TPU-native deep dive (per-op device
timing, HBM), the ``jax.profiler`` wrappers in ``utils/profiling.py``
remain the tool — this module answers "which phase", that one answers
"which op".
"""

from __future__ import annotations

import threading
from collections import deque
from contextlib import contextmanager

from .clock import Clock, RealClock
from .metrics import MetricsRegistry, global_metrics, parse_exposition


class _PhaseStat:
    """Cumulative per-phase accounting (guarded by the profiler lock)."""

    __slots__ = ("count", "total_s", "ewma_s", "reservoir")

    def __init__(self, reservoir: int):
        self.count = 0
        self.total_s = 0.0
        self.ewma_s = 0.0
        # Per-INSTANCE reservoir, deliberately separate from the
        # registry histogram the same sample lands in: the registry may
        # be shared (global_metrics across several batchers/trainers in
        # one process — the bench does exactly this), so its reservoir
        # mixes instances and outlives restarts; snapshot()'s p50/p95
        # must describe THIS profiler's window only.
        self.reservoir: "deque[float]" = deque(maxlen=reservoir)


class _Seg:
    """One open frame of the per-thread phase stack."""

    __slots__ = ("name", "acc", "last")

    def __init__(self, name: str, now: float):
        self.name = name
        self.acc = 0.0   # self-time accumulated before the current run
        self.last = now  # start of the current run


class PhaseProfiler:
    """Bounded, Clock-driven phase accounting for one plane.

    ``plane`` selects the metric family the samples land in:
    ``"serve"`` → ``serve_phase_seconds{phase}`` histograms +
    ``serve_phase_share{phase}`` gauges, ``"train"`` → the ``train_``
    pair.  ``window_s`` is the share-accounting window;
    ``reservoir`` bounds the per-phase percentile reservoir and
    ``max_samples`` the rolling (t_end, phase, dt) sample ring the
    share math and the Chrome-trace export read.

    Threading: ``phase``/``push``/``pop`` keep a *per-thread* stack
    (nested phases record self-time, never double-count); the shared
    stats/window are lock-guarded — scrape/snapshot readers on HTTP
    threads race the recording thread safely.
    """

    _GUARDED_BY = {"_lock": ("_stats", "_window", "_win_sums")}

    def __init__(
        self,
        plane: str = "serve",
        registry: MetricsRegistry | None = None,
        clock: Clock | None = None,
        window_s: float = 60.0,
        reservoir: int = 512,
        ewma_alpha: float = 0.2,
        max_samples: int = 2048,
    ):
        if plane not in ("serve", "train"):
            raise ValueError(
                f"unknown profiler plane {plane!r}: 'serve' or 'train'"
            )
        self.plane = plane
        self.registry = registry if registry is not None else global_metrics
        self.clock = clock or RealClock()
        self.window_s = max(1e-6, float(window_s))
        self.reservoir = max(8, int(reservoir))
        self.alpha = min(1.0, max(1e-6, float(ewma_alpha)))
        self._lock = threading.Lock()
        self._stats: dict[str, _PhaseStat] = {}
        # Rolling (t_end, phase, self_seconds) samples — the share window
        # AND the Chrome-trace phase track.  Bounded manually (not via
        # deque maxlen) so the incremental per-phase window sums below
        # stay exact: every eviction subtracts what the append added.
        self._max_samples = max(64, int(max_samples))
        self._window: "deque[tuple]" = deque()
        # phase -> seconds currently inside the window.  Incremental so
        # export_shares is O(evicted + phases), not O(window) — it runs
        # on the batcher's gauge-refresh cadence (every drain).
        self._win_sums: dict[str, float] = {}
        self._t0 = self.clock.now()
        self._tls = threading.local()

    # -- recording ---------------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def push(self, name: str) -> None:
        """Enter *name* on this thread's phase stack.  The enclosing
        phase (if any) stops accumulating — nested phases record SELF
        time, so shares stay a partition of wall time."""
        now = self.clock.now()
        stack = self._stack()
        if stack:
            top = stack[-1]
            top.acc += now - top.last
        stack.append(_Seg(name, now))

    def pop(self) -> float:
        """Exit the current phase, record its self-time sample, resume
        the enclosing phase.  Returns the recorded seconds."""
        now = self.clock.now()
        stack = self._stack()
        seg = stack.pop()
        if stack:
            stack[-1].last = now
        dt = seg.acc + (now - seg.last)
        self.record(seg.name, dt, end=now)
        return dt

    @contextmanager
    def phase(self, name: str):
        """``with profiler.phase("decode_dispatch"): ...`` — the stack
        form of ``record`` (exception-safe; nested phases subtract)."""
        self.push(name)
        try:
            yield
        finally:
            self.pop()

    def record(self, name: str, seconds: float, end: float | None = None) -> None:
        """Record one completed phase sample of *seconds* ending at
        *end* (default: now).  The direct form for callers that already
        hold both timestamps."""
        dt = max(0.0, float(seconds))
        now = self.clock.now() if end is None else end
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _PhaseStat(self.reservoir)
            st.count += 1
            st.total_s += dt
            st.reservoir.append(dt)
            st.ewma_s = (
                dt if st.count == 1
                else self.alpha * dt + (1.0 - self.alpha) * st.ewma_s
            )
            self._evict_locked(now - self.window_s)
            while len(self._window) >= self._max_samples:
                _, old_name, old_dt = self._window.popleft()
                self._win_sums[old_name] -= old_dt
            self._window.append((now, name, dt))
            self._win_sums[name] = self._win_sums.get(name, 0.0) + dt
        # Outside the profiler lock: the registry has its own.
        if self.plane == "train":
            self.registry.observe("train_phase_seconds", dt, phase=name)
        else:
            self.registry.observe("serve_phase_seconds", dt, phase=name)

    def _evict_locked(self, cut: float) -> None:
        """Drop window samples older than *cut*, keeping the per-phase
        sums exact.  Lock held by caller."""
        while self._window and self._window[0][0] < cut:
            _, name, dt = self._window.popleft()
            self._win_sums[name] -= dt

    # -- shares ------------------------------------------------------------
    def shares(self, now: float | None = None) -> tuple[dict, float, float]:
        """``(per_phase_share, residual, span_s)`` over the trailing
        window.  A sample straddling the window edge attributes fully,
        so the raw sums can slightly exceed the span — shares are then
        normalized so they stay a partition (sum <= 1.0) and the
        residual is the honest unattributed remainder."""
        now = self.clock.now() if now is None else now
        with self._lock:
            self._evict_locked(now - self.window_s)
            # Clamp at 0: subtract-on-evict float drift must never leak
            # a tiny negative share.
            per = {
                name: max(0.0, v) for name, v in self._win_sums.items()
            }
            phases = sorted(self._stats)
        span = min(self.window_s, max(1e-9, now - self._t0))
        # Edge samples attribute fully, so the measured total can poke
        # past the span — dividing by max(span, total) keeps the shares
        # a partition (sum <= 1.0) without distorting the common case.
        denom = max(span, sum(per.values()))
        out = {ph: per.get(ph, 0.0) / denom for ph in phases}
        residual = max(0.0, 1.0 - sum(out.values()))
        return out, residual, span

    def export_shares(self) -> None:
        """Write the current shares as ``{plane}_phase_share{phase}``
        gauges (plus ``phase="residual"``) into the registry — called
        from the instrumented loop at its own cadence (the batcher's
        gauge refresh, the trainer's step tail)."""
        per, residual, _ = self.shares()
        if self.plane == "train":
            for ph, v in per.items():
                self.registry.set_gauge("train_phase_share", v, phase=ph)
            self.registry.set_gauge(
                "train_phase_share", residual, phase="residual"
            )
        else:
            for ph, v in per.items():
                self.registry.set_gauge("serve_phase_share", v, phase=ph)
            self.registry.set_gauge(
                "serve_phase_share", residual, phase="residual"
            )

    # -- read surface ------------------------------------------------------
    @staticmethod
    def _quantile(sorted_vals: list, q: float) -> float:
        if not sorted_vals:
            return 0.0
        k = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
        return sorted_vals[k]

    def snapshot(self) -> dict:
        """The profiler's half of the ``/debug/profile`` body: per-phase
        count/total/ewma/p50/p95/share, the residual, and the rolling
        sample ring (the Chrome-trace phase track).  Deterministic under
        ``FakeClock``: every number derives from recorded samples and
        clock reads — two identically-scripted runs serialize
        byte-identically."""
        now = self.clock.now()
        per, residual, span = self.shares(now)
        with self._lock:
            stats = {
                ph: (st.count, st.total_s, st.ewma_s, sorted(st.reservoir))
                for ph, st in self._stats.items()
            }
            samples = [[t, ph, dt] for t, ph, dt in self._window]
        phases = {}
        for ph in sorted(stats):
            count, total_s, ewma_s, res = stats[ph]
            phases[ph] = {
                "count": count,
                "total_s": round(total_s, 9),
                "ewma_s": round(ewma_s, 9),
                "p50_s": round(self._quantile(res, 0.5), 9),
                "p95_s": round(self._quantile(res, 0.95), 9),
                "share": round(per.get(ph, 0.0), 9),
            }
        return {
            "plane": self.plane,
            "now": now,
            "window_s": self.window_s,
            "span_s": round(span, 9),
            "phases": phases,
            "residual_share": round(residual, 9),
            "samples": samples,
        }


def profile_snapshot(
    profiler: PhaseProfiler | None = None,
    registry: MetricsRegistry | None = None,
) -> dict:
    """The full ``/debug/profile`` JSON body: the profiler's phase view
    plus the registry-resident attribution families — XLA compile
    telemetry and the per-axis collective bandwidth gauges.  Either half
    may be absent (a control-plane-only registry has no phases; a fresh
    profiler has no compiles) — the shape stays stable."""
    reg = registry if registry is not None else (
        profiler.registry if profiler is not None else global_metrics
    )
    snap = (
        profiler.snapshot() if profiler is not None
        else {
            "plane": None, "now": 0.0, "window_s": 0.0, "span_s": 0.0,
            "phases": {}, "residual_share": None, "samples": [],
        }
    )
    hist = reg.histogram("xla_compile_seconds")
    snap["compile"] = {
        "compiles_total": reg.counter("xla_compiles_total"),
        "compile_seconds_sum": round(hist.total, 9) if hist else 0.0,
        "compile_p95_s": round(
            reg.percentile("xla_compile_seconds", 0.95), 9
        ),
    }
    coll: dict[str, dict] = {}
    for lbls, v in sorted(reg.series("collective_bytes_per_second").items()):
        axis = dict(lbls).get("axis")
        if axis:
            coll[axis] = {"bytes_per_second": v}
    for lbls, q in sorted(
        reg.hist_percentiles("collective_seconds", 0.5).items()
    ):
        d = dict(lbls)
        axis, op = d.get("axis"), d.get("op", "?")
        if axis:
            coll.setdefault(axis, {}).setdefault("p50_s", {})[op] = round(q, 9)
    snap["collectives"] = coll
    snap["deep_dive"] = (
        "TPU-native per-op timing: utils.profiling.trace / "
        "profile_trainer (jax.profiler xplane -> TensorBoard/xprof)"
    )
    return snap


def snapshot_from_exposition(text: str) -> dict:
    """Reconstruct a ``/debug/profile``-shaped snapshot from one
    Prometheus text exposition (a live ``/metrics`` scrape or the
    persisted ``metrics.prom``) — the ``obs profile`` offline path.
    Percentiles come from the cumulative ``_bucket`` series (the
    ``histogram_quantile`` estimate, ``utils.federation.bucket_quantile``);
    shares/residual from the exported share gauges.  Train-plane phases
    ride the same table prefixed ``train:``."""
    from .federation import bucket_quantile

    fams = parse_exposition(text)
    phases: dict[str, dict] = {}
    residual = None
    for plane, share_fam, sec_fam in (
        ("serve", "serve_phase_share", "serve_phase_seconds"),
        ("train", "train_phase_share", "train_phase_seconds"),
    ):
        shares = fams.get(share_fam, {})
        buckets = fams.get(f"{sec_fam}_bucket", {})
        counts = fams.get(f"{sec_fam}_count", {})
        names = set()
        for lbls in list(shares) + list(counts):
            ph = dict(lbls).get("phase")
            if ph and ph != "residual":
                names.add(ph)
        for ph in sorted(names):
            key = ph if plane == "serve" else f"train:{ph}"
            sub = {
                l: v for l, v in buckets.items()
                if dict(l).get("phase") == ph
            }
            phases[key] = {
                "count": int(counts.get((("phase", ph),), 0.0)),
                "p50_s": bucket_quantile(sub, 0.5) or 0.0,
                "p95_s": bucket_quantile(sub, 0.95) or 0.0,
                "share": shares.get((("phase", ph),), 0.0),
            }
        r = shares.get((("phase", "residual"),))
        if r is not None and plane == "serve":
            residual = r
    compiles = sum(fams.get("xla_compiles_total", {}).values())
    csum = sum(fams.get("xla_compile_seconds_sum", {}).values())
    coll = {}
    for lbls, v in sorted(
        fams.get("collective_bytes_per_second", {}).items()
    ):
        axis = dict(lbls).get("axis")
        if axis:
            coll[axis] = {"bytes_per_second": v}
    return {
        "plane": "snapshot",
        "now": 0.0,
        "window_s": 0.0,
        "span_s": 0.0,
        "phases": phases,
        "residual_share": residual,
        "samples": [],
        "compile": {
            "compiles_total": compiles,
            "compile_seconds_sum": csum,
            "compile_p95_s": bucket_quantile(
                fams.get("xla_compile_seconds_bucket", {}), 0.95
            ) or 0.0,
        },
        "collectives": coll,
        "deep_dive": (
            "TPU-native per-op timing: utils.profiling.trace / "
            "profile_trainer (jax.profiler xplane -> TensorBoard/xprof)"
        ),
    }


# -- Chrome/Perfetto trace export --------------------------------------------

def _walk_tree(node: dict, pid: int, tid: int, events: list) -> None:
    start = float(node.get("start", 0.0))
    dur_ms = float(node.get("duration_ms", 0.0))
    args = dict(node.get("attributes") or {})
    if node.get("status", "ok") != "ok":
        args["status"] = node.get("status")
    events.append({
        "name": str(node.get("name", "?")),
        "ph": "X",
        "ts": start * 1e6,
        "dur": max(0.0, dur_ms * 1e3),
        "pid": pid,
        "tid": tid,
        "args": {k: str(v) for k, v in sorted(args.items())},
    })
    for child in node.get("children", ()):
        _walk_tree(child, pid, tid, events)


def chrome_trace(traces: list | None = None,
                 profile: dict | None = None,
                 by_process: dict | None = None) -> dict:
    """Chrome trace-event JSON (the Perfetto-loadable format) from the
    assembled span ring (the ``/debug/traces`` shape) and a profile
    snapshot's rolling phase samples.  Spans render under pid 1 (one
    Perfetto track per trace), phase samples under pid 2 (one track per
    phase).  Events are sorted by timestamp — monotonic ``ts`` is part
    of the format contract the export test pins.

    ``by_process`` is the multi-process form the fleet waterfall
    (utils/waterfall.py) exports: ``{process_name: [assembled traces]}``
    with span times already aligned onto one clock.  Process names map
    to pids 1..N in sorted order (deterministic across runs) with
    ``process_name`` metadata, so Perfetto shows gateway and replicas
    as separate named processes on a shared timeline; the profile track
    then lands on pid N+1.  Mutually exclusive with ``traces``."""
    events: list[dict] = []
    meta: list[dict] = []
    if by_process is not None:
        procs = sorted(by_process)
        for pid, proc in enumerate(procs, start=1):
            meta.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": str(proc)},
            })
            for i, trace in enumerate(by_process[proc]):
                tid = i + 1
                meta.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid,
                    "args": {
                        "name":
                        f"trace {str(trace.get('trace_id', '?'))[:8]}"
                    },
                })
                for root in trace.get("tree", ()):
                    _walk_tree(root, pid, tid, events)
        profile_pid = len(procs) + 1
    else:
        meta.append({
            "name": "process_name", "ph": "M", "pid": 1, "tid": 0,
            "args": {"name": "spans"},
        })
        for i, trace in enumerate(traces or []):
            tid = i + 1
            meta.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {
                    "name": f"trace {str(trace.get('trace_id', '?'))[:8]}"
                },
            })
            for root in trace.get("tree", ()):
                _walk_tree(root, 1, tid, events)
        profile_pid = 2
    if by_process is None or profile:
        meta.append({
            "name": "process_name", "ph": "M", "pid": profile_pid,
            "tid": 0, "args": {"name": "phases"},
        })
    if profile:
        names = sorted({ph for _, ph, _ in profile.get("samples", [])})
        tids = {ph: i + 1 for i, ph in enumerate(names)}
        for ph, tid in tids.items():
            meta.append({
                "name": "thread_name", "ph": "M", "pid": profile_pid,
                "tid": tid, "args": {"name": ph},
            })
        for t_end, ph, dt in profile.get("samples", []):
            events.append({
                "name": str(ph),
                "ph": "X",
                "ts": (float(t_end) - float(dt)) * 1e6,
                "dur": float(dt) * 1e6,
                "pid": profile_pid,
                "tid": tids[ph],
                "args": {},
            })
    events.sort(key=lambda e: (e["ts"], e["pid"], e["tid"]))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
