"""Clock abstraction so reconcile/requeue timing is testable without real sleeps.

The reference operator's retry ladder (20/30/40 s error requeues, 60 s resync;
reference README.md:184,192,207,219,233-234) would stall a CPU-only test suite
for minutes if the work queue used wall-clock sleeps.  ``FakeClock``
auto-advances to the next scheduled deadline when every worker is blocked,
so the envtest-style harness replays hours of reconcile cadence in
milliseconds while preserving ordering semantics.
"""

from __future__ import annotations

import threading
import time as _time


class Clock:
    """Monotonic time source + interruptible wait.

    ``now()`` is the MONOTONIC domain (durations, deadlines, FSM holds);
    ``wall()`` is the EPOCH domain (display timestamps, token/code
    expiry claims, asset ``created_at``).  Splitting them lets modules
    that need human-meaningful timestamps stay FakeClock-testable — the
    graftcheck determinism pass (k8s_gpu_tpu/analysis) forbids ambient
    ``time.time()``/``time.monotonic()`` in the deterministic planes,
    and these two methods are the sanctioned replacements.
    """

    def now(self) -> float:
        raise NotImplementedError

    def wall(self) -> float:
        """Epoch seconds for display/expiry timestamps.  FakeClock
        keeps one time line (wall == now), so a test that advances fake
        time advances token expiry with it."""
        return self.now()

    def wait(self, cond: threading.Condition, timeout: float | None) -> None:
        """Wait on *cond* (already held) up to *timeout* clock-seconds."""
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        """Block for *seconds* CLOCK-seconds — the retry-backoff primitive
        (cloud/resilience.py).  Under RealClock this is a plain sleep;
        under FakeClock the caller parks on the cheap poll until a test
        advances fake time past the deadline, so chaos suites replay
        whole backoff ladders instantly."""
        deadline = self.now() + max(0.0, seconds)
        cond = threading.Condition()
        with cond:
            while True:
                remaining = deadline - self.now()
                if remaining <= 0:
                    return
                self.wait(cond, remaining)


class RealClock(Clock):
    def now(self) -> float:
        return _time.monotonic()

    def wall(self) -> float:
        return _time.time()

    def wait(self, cond: threading.Condition, timeout: float | None) -> None:
        cond.wait(timeout)


class FakeClock(Clock):
    """Manually-advanced clock: time moves ONLY via ``advance``/``set_time``.

    Workers blocked on a deadline poll cheaply in real time but never move
    fake time themselves, so a test can (a) reach a stable quiescence point
    (nothing due "now"), then (b) ``advance(30)`` to fire exactly the retry
    ladder step under test.  This keeps requeue ordering deterministic —
    SURVEY §7 hard part 2 is precisely this correctness.
    """

    def __init__(self, start: float = 0.0):
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def advance(self, dt: float) -> None:
        with self._lock:
            self._now += dt

    def set_time(self, t: float) -> None:
        with self._lock:
            self._now = t

    def wait(self, cond: threading.Condition, timeout: float | None) -> None:
        # Short real-time poll; notify_all() wakes us earlier.  Fake time is
        # never advanced here.
        cond.wait(0.0005 if timeout is not None else 0.002)


class TickingFakeClock(FakeClock):
    """FakeClock whose ``now()`` auto-advances by a fixed dyadic tick.

    A plain FakeClock reads the same instant until a test advances it,
    which makes every instrumented duration zero — useless for code
    whose OUTPUT is a duration partition (the goodput ledger).  This
    variant moves time forward one ``tick`` per ``now()`` read, so a
    scripted run accrues durations proportional to its clock-read
    sequence while staying fully deterministic: two identical runs make
    identical read sequences and therefore identical timelines.
    ``advance``/``set_time`` still work for the big jumps (an outage, a
    rule-evaluator hold window).

    The default tick is 2**-9 s: dyadic, so every sum of ticks and
    advances (use dyadic advances: 0.5, 10.0, ...) is float-exact AND
    survives the snapshot layer's ``round(x, 9)`` unchanged — the
    exhaustive-partition invariant (segments + residual == elapsed,
    exactly) holds bit-for-bit.
    """

    def __init__(self, start: float = 0.0, tick: float = 0.001953125):
        super().__init__(start)
        self._tick = tick

    def now(self) -> float:
        with self._lock:
            self._now += self._tick
            return self._now
