"""In-process Prometheus-style rules engine: recording + alerting rules.

The reference asks for Prometheus monitoring of GPU utilization, queue
length and storage plus quota alerting (GPU调度平台搭建.md:798-807), but the
stack so far stops at raw signal collection — counters and gauges nobody
evaluates.  This module is the evaluation half, dependency-free (no
Prometheus server in zero-egress environments):

- **RecordingRule** — a named derived series (error ratio, p95, SLO burn
  rate) computed from ``MetricsRegistry`` counters/histograms each tick
  and written back as a gauge, so ``/metrics`` exposes it and later rules
  can reference it.  Rules evaluate in pack order: a recording rule's
  output is visible to every rule after it in the same tick.
- **AlertingRule** — threshold (``above``/``below``) plus a ``for_s``
  hold duration, per label-set:

      inactive → pending (condition holds, held < for_s)
               → firing  (held ≥ for_s)
               → resolved (condition clears after firing; one transition,
                           then the series is inactive again)

  Every transition bumps ``alert_transitions_total{alertname,to}`` and
  lands in a bounded timeline; ``alerts_firing{alertname}`` gauges the
  number of firing label-sets.  A ``notify`` hook fires on
  firing/resolved — the controller plane wires it to Warning Events on
  the affected objects (controller/alerting.py).
- **RuleEvaluator** — owns the rules, a Clock, and counter-rate history.
  ``evaluate_once()`` is pure function of (registry state, clock time):
  two runs over the same scripted mutations produce identical transition
  timelines under ``FakeClock`` — the determinism the chaos/alerts demos
  assert.  ``start()`` runs the tick loop on a daemon thread (the
  controller manager owns one in production).

Rate/burn-rate math: the evaluator snapshots each *watched* counter
family per tick (watching is self-registering — the first ``ctx.rate``
call on a name starts its history), and ``rate(name, window)`` is the
per-second increase of the summed matching series between the oldest and
newest samples inside the window.  ``burn_rate`` divides the bad/total
ratio by the SLO's error budget — the standard SRE burn-rate signal.
"""

from __future__ import annotations

import collections
import logging
import threading
from dataclasses import dataclass, field

from .clock import Clock, RealClock
from .metrics import MetricsRegistry, global_metrics

log = logging.getLogger("k8s_gpu_tpu.alerts")

# A label-set is the registry's canonical tuple(sorted((k, v), ...)).
LabelSet = tuple


def _match(lbls: LabelSet, where: dict) -> bool:
    """Label filter: values are exact strings or predicates on the value."""
    d = dict(lbls)
    for k, want in where.items():
        have = d.get(k)
        if callable(want):
            if have is None or not want(have):
                return False
        elif have != want:
            return False
    return True


def _normalize(result) -> dict[LabelSet, float]:
    """Rule expressions may return a scalar (one unlabeled series) or a
    ``{label_tuple: value}`` dict (one FSM per label-set)."""
    if result is None:
        return {}
    if isinstance(result, dict):
        return {k: float(v) for k, v in result.items()}
    return {(): float(result)}


class Ctx:
    """What a rule expression sees for one evaluation tick: registry
    reads, windowed counter rates, and the tick's clock time."""

    def __init__(self, evaluator: "RuleEvaluator", now: float):
        self._ev = evaluator
        self.registry = evaluator.registry
        self.now = now

    def gauge(self, name: str, default: float = 0.0, **labels) -> float:
        v = self.registry.gauge(name, **labels)
        return default if v is None else v

    def series(self, name: str, **where) -> dict[LabelSet, float]:
        return {
            lbls: v
            for lbls, v in self.registry.series(name).items()
            if _match(lbls, where)
        }

    def sum(self, name: str, **where) -> float:
        return float(sum(self.series(name, **where).values()))

    def rate(self, name: str, window: float, **where) -> float:
        """Per-second increase of the summed matching counter series over
        the trailing *window* clock-seconds; 0.0 until two samples exist."""
        return self._ev._rate(name, window, where, self.now)

    def percentile(self, name: str, q: float, **labels) -> float:
        return self.registry.percentile(name, q, **labels)

    def percentiles(self, name: str, q: float) -> dict[LabelSet, float]:
        return self.registry.hist_percentiles(name, q)

    @staticmethod
    def ratio(num: float, den: float) -> float:
        return num / den if den else 0.0

    def burn_rate(self, name: str, window: float, slo: float,
                  bad: dict, total: dict | None = None) -> float:
        """SLO burn rate: (bad-rate / total-rate) / (1 - slo).  1.0 means
        the error budget burns exactly at the sustainable pace; N means N
        times too fast."""
        t = self.rate(name, window, **(total or {}))
        if t <= 0.0:
            return 0.0
        b = self.rate(name, window, **bad)
        return (b / t) / max(1e-9, 1.0 - slo)


@dataclass
class RecordingRule:
    """Evaluate ``expr(ctx)`` and write the result back as gauge
    ``record`` (per label-set when the expr returns a dict)."""

    record: str
    expr: object
    labels: dict = field(default_factory=dict)


@dataclass
class AlertingRule:
    """Threshold alert with a hold duration, one FSM per label-set."""

    name: str
    expr: object
    above: float | None = None
    below: float | None = None
    for_s: float = 0.0
    severity: str = "warning"
    annotation: str = ""

    def breached(self, v: float) -> bool:
        if self.above is not None and v > self.above:
            return True
        if self.below is not None and v < self.below:
            return True
        return False

    def annotate(self, lbls: LabelSet, v: float) -> str:
        if not self.annotation:
            return ""
        try:
            return self.annotation.format(value=v, **dict(lbls))
        except (KeyError, IndexError, ValueError):
            return self.annotation


class RuleEvaluator:
    """Evaluates a rule pack against one registry on a Clock cadence.

    ``collectors`` run before every tick — hooks for gauges that need
    polling rather than event-driven updates (workqueue oldest-item age;
    the manager registers one).  ``notify(rule, labels, transition,
    value)`` fires on transitions to ``firing``/``resolved``."""

    _GUARDED_BY = {
        "_lock": ("_watched", "_state", "_last_eval", "timeline"),
    }

    def __init__(
        self,
        rules,
        clock: Clock | None = None,
        registry: MetricsRegistry | None = None,
        interval: float = 10.0,
        notify=None,
        max_timeline: int = 512,
        history_samples: int = 240,
    ):
        self.rules = list(rules)
        self.clock = clock or RealClock()
        self.registry = registry or global_metrics
        self.interval = float(interval)
        self.notify = notify
        self.collectors: list = []
        self.timeline: collections.deque = collections.deque(
            maxlen=max_timeline
        )
        self._history_samples = history_samples
        self._lock = threading.Lock()
        # Lock contract (graftcheck lockcheck + utils.faults
        # guard_declared): tick thread vs the /alerts HTTP readers.
        self._watched: dict[str, collections.deque] = {}
        # alertname -> label-set -> {"state", "since", "value"}
        self._state: dict[str, dict[LabelSet, dict]] = {}
        self._last_eval = float("-inf")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        for r in self.rules:
            if isinstance(r, AlertingRule):
                # Visible from tick 0 so dashboards can tell "no rule" from
                # "rule evaluated, nothing firing".
                self.registry.set_gauge(
                    "alerts_firing", 0.0, alertname=r.name
                )

    # -- counter-rate history ---------------------------------------------
    def _rate(self, name: str, window: float, where: dict,
              now: float) -> float:
        """Windowed counter rate from the per-tick snapshots.  Lock
        held by caller (rule expressions run inside the evaluation
        tick's lock)."""
        hist = self._watched.get(name)
        if hist is None:
            # Self-registering watch: seed the history with this tick's
            # snapshot; a rate needs two samples, so this tick reads 0.0.
            hist = collections.deque(maxlen=self._history_samples)
            hist.append((now, self.registry.series(name)))
            self._watched[name] = hist
            return 0.0
        inside = [(t, snap) for t, snap in hist if t >= now - window]
        if len(inside) < 2:
            return 0.0
        t0, s0 = inside[0]
        t1, s1 = inside[-1]
        if t1 <= t0:
            return 0.0

        def total(snap):
            return sum(v for lbls, v in snap.items() if _match(lbls, where))

        return max(0.0, (total(s1) - total(s0)) / (t1 - t0))

    # -- evaluation --------------------------------------------------------
    def evaluate_once(self) -> None:
        now = self.clock.now()
        for c in list(self.collectors):
            try:
                c()
            except Exception:
                log.exception("alert collector failed")
        with self._lock:
            self._last_eval = now
            # One snapshot per watched family per tick (skip duplicate
            # timestamps: FakeClock loops may re-enter at the same now).
            for name, hist in self._watched.items():
                if not hist or hist[-1][0] < now:
                    hist.append((now, self.registry.series(name)))
            ctx = Ctx(self, now)
            for rule in self.rules:
                try:
                    if isinstance(rule, RecordingRule):
                        self._record(rule, ctx)
                    else:
                        self._alert(rule, ctx, now)
                except Exception:
                    log.exception("rule %s failed", getattr(
                        rule, "name", getattr(rule, "record", rule)))

    def _record(self, rule: RecordingRule, ctx: Ctx) -> None:
        """Evaluate one recording rule.  Lock held by caller
        (``evaluate_once``)."""
        for lbls, v in _normalize(rule.expr(ctx)).items():
            # Dict variant: source label keys are data and may collide
            # with the kwargs form's reserved parameter names.
            self.registry.set_gauge_series(
                rule.record, v, {**dict(lbls), **rule.labels}
            )

    def _alert(self, rule: AlertingRule, ctx: Ctx, now: float) -> None:
        """Walk one alerting rule's per-label-set FSM.  Lock held by
        caller (``evaluate_once``)."""
        values = _normalize(rule.expr(ctx))
        st = self._state.setdefault(rule.name, {})
        for lbls, v in values.items():
            cur = st.get(lbls)
            breached = rule.breached(v)
            if cur is None:
                if breached:
                    cur = {"state": "inactive", "since": now, "value": v}
                    st[lbls] = cur
                else:
                    continue
            cur["value"] = v
            if cur["state"] == "inactive":
                if breached:
                    self._transition(rule, lbls, cur, "pending", v, now)
            elif cur["state"] == "pending" and not breached:
                self._transition(rule, lbls, cur, "inactive", v, now)
            # pending→firing in the SAME tick the hold elapses (for_s=0
            # traverses inactive→pending→firing in one tick — the full
            # FSM path is always walked, never skipped).
            if cur["state"] == "pending" and breached and (
                now - cur["since"] >= rule.for_s
            ):
                self._transition(rule, lbls, cur, "firing", v, now)
            elif cur["state"] == "firing" and not breached:
                self._transition(rule, lbls, cur, "resolved", v, now)
        # Series that vanished from the registry resolve/deactivate too.
        for lbls in [k for k in st if k not in values]:
            cur = st[lbls]
            if cur["state"] == "firing":
                self._transition(rule, lbls, cur, "resolved",
                                 cur["value"], now)
            elif cur["state"] == "pending":
                self._transition(rule, lbls, cur, "inactive",
                                 cur["value"], now)
            else:
                del st[lbls]
        self._export_firing(rule, st)

    def _transition(self, rule: AlertingRule, lbls: LabelSet, cur: dict,
                    to: str, v: float, now: float) -> None:
        """Record one FSM transition.  Lock held by caller."""
        frm = cur["state"]
        # "resolved" is a recorded transition, not a resting state.
        cur["state"] = "inactive" if to == "resolved" else to
        cur["since"] = now
        self.timeline.append({
            "t": now, "alert": rule.name, "labels": dict(lbls),
            "from": frm, "to": to, "value": v,
        })
        self.registry.inc(
            "alert_transitions_total", alertname=rule.name, to=to
        )
        if to in ("firing", "resolved") and self.notify is not None:
            try:
                self.notify(rule, dict(lbls), to, v)
            except Exception:
                log.exception("alert notifier failed for %s", rule.name)

    def _export_firing(self, rule: AlertingRule, st: dict) -> None:
        """Refresh the alerts_firing gauge.  Lock held by caller."""
        firing = sum(1 for c in st.values() if c["state"] == "firing")
        self.registry.set_gauge(
            "alerts_firing", float(firing), alertname=rule.name
        )

    # -- introspection (the /alerts surface) -------------------------------
    def active_alerts(self) -> list[dict]:
        """Pending + firing alert instances, firing first."""
        now = self.clock.now()
        out = []
        with self._lock:
            for rule in self.rules:
                if not isinstance(rule, AlertingRule):
                    continue
                for lbls, cur in self._state.get(rule.name, {}).items():
                    if cur["state"] not in ("pending", "firing"):
                        continue
                    out.append({
                        "alertname": rule.name,
                        "labels": dict(lbls),
                        "state": cur["state"],
                        "since": cur["since"],
                        "active_s": max(0.0, now - cur["since"]),
                        "value": cur["value"],
                        "severity": rule.severity,
                        "annotation": rule.annotate(lbls, cur["value"]),
                    })
        out.sort(key=lambda a: (a["state"] != "firing", a["alertname"]))
        return out

    def snapshot(self, limit: int = 100) -> dict:
        """The ``/alerts`` JSON body: active alerts + recent transitions.
        The timeline copy happens under the evaluator lock — an HTTP
        thread iterating the deque while a tick appends would otherwise
        hit the same mutated-during-iteration race the registry's
        percentile fix closes."""
        alerts = self.active_alerts()
        with self._lock:
            # limit<=0 means none: a bare [-0:] slice would return ALL.
            transitions = (
                list(self.timeline)[-int(limit):] if limit > 0 else []
            )
        return {
            "now": self.clock.now(),
            "alerts": alerts,
            "transitions": transitions,
        }

    # -- the tick loop -----------------------------------------------------
    def start(self) -> "RuleEvaluator":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="rule-evaluator", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _loop(self) -> None:
        cond = threading.Condition()
        while not self._stop.is_set():
            # _last_eval is tick-thread-written under the lock and read
            # here; take the lock for the read too (the lock contract
            # the analysis pass enforces — and the honest ordering).
            with self._lock:
                due = self.clock.now() - self._last_eval >= self.interval
            if due:
                try:
                    self.evaluate_once()
                except Exception:
                    log.exception("rule evaluation tick failed")
            with cond:
                # Short waits so stop() is responsive under RealClock and
                # FakeClock's cheap poll keeps ticks aligned to fake time.
                self.clock.wait(cond, 0.25)


def _is_5xx(code: str) -> bool:
    return str(code).startswith("5")


# -- the SLO objective layer (ISSUE 14) ---------------------------------------

@dataclass
class SloObjective:
    """A declared service-level objective over two counter families:
    ``bad`` events out of ``total`` events, against a ``target``
    success ratio.  The budget math is the standard SRE shape —
    error budget = 1 - target; burn rate = (bad_rate / total_rate) /
    (1 - target); 1.0 burns the budget exactly at the sustainable
    pace.

    ``total_where``/``bad_where`` are label filters (exact strings or
    predicates) applied when summing the families — so one counter
    family can back several objectives (probe_failures_total splits
    into availability's hard failures and the latency objective's
    ``reason="slow"`` events)."""

    name: str                    # the {slo=} label value
    target: float                # e.g. 0.999 — the success-ratio goal
    total: str                   # counter family counting all events
    bad: str                     # counter family counting bad events
    total_where: dict = field(default_factory=dict)
    bad_where: dict = field(default_factory=dict)


def default_slo_objectives() -> list[SloObjective]:
    """The platform's declared objectives, both fed by the canary
    prober (serve/canary.py): availability 99.9% (a probe answered in
    deadline with the golden content — ``slow`` is not an availability
    failure) and probe-TTFT 99% under the prober's ``ttft_slo_s``
    bound (the prober classifies the breach per probe, so the budget
    math here is pure counter arithmetic)."""
    return [
        SloObjective(
            "probe-availability", 0.999,
            total="probe_requests_total", bad="probe_failures_total",
            bad_where={"reason": lambda r: r != "slow"},
        ),
        SloObjective(
            "probe-ttft", 0.99,
            total="probe_requests_total", bad="probe_failures_total",
            bad_where={"reason": "slow"},
        ),
    ]


def slo_rule_pack(
    objectives: list[SloObjective] | None = None,
    *,
    fast_window: float = 300.0,
    slow_window: float = 3600.0,
    burn_threshold: float = 14.4,
    for_s: float = 60.0,
) -> list:
    """Recording + alerting rules for a set of declared objectives.

    Per objective (one ``{slo=}`` label-set each):

    - ``slo_budget_remaining_ratio`` — the cumulative error budget
      left, from the raw counters: ``1 - (bad/total)/(1-target)``,
      clamped to [0, 1].  1.0 = untouched budget, 0.0 = fully spent.
      Cumulative by design: the chaos drill's spent budget stays
      visible after recovery (a windowed remaining-ratio would forgive
      the incident as it scrolls out).
    - ``slo_burn_rate_fast`` / ``slo_burn_rate_slow`` — windowed burn
      over ``fast_window``/``slow_window``.
    - ``SloBudgetBurn`` — the multi-window page: fires only when BOTH
      windows burn above ``burn_threshold`` (the expression is
      ``min(fast, slow)``), so a short blip (fast spikes, slow calm)
      and a long-forgiven incident (slow raised, fast recovered) both
      stay quiet — the standard multi-window multi-burn policy.

    Objectives whose families are absent read total 0 → burn 0.0 and a
    full budget; the pack is safe on any registry."""
    objectives = (
        list(objectives) if objectives is not None
        else default_slo_objectives()
    )

    def _remaining(ctx: Ctx) -> dict:
        out: dict[LabelSet, float] = {}
        for o in objectives:
            total = ctx.sum(o.total, **o.total_where)
            bad = ctx.sum(o.bad, **o.bad_where)
            spent = (
                (bad / total) / max(1e-9, 1.0 - o.target)
                if total > 0 else 0.0
            )
            out[(("slo", o.name),)] = max(0.0, min(1.0, 1.0 - spent))
        return out

    def _burn(window: float):
        def expr(ctx: Ctx) -> dict:
            out: dict[LabelSet, float] = {}
            for o in objectives:
                t = ctx.rate(o.total, window, **o.total_where)
                b = ctx.rate(o.bad, window, **o.bad_where)
                out[(("slo", o.name),)] = (
                    (b / t) / max(1e-9, 1.0 - o.target) if t > 0 else 0.0
                )
            return out
        return expr

    def _multiwindow(ctx: Ctx) -> dict:
        return {
            lbls: min(
                v,
                ctx.gauge("slo_burn_rate_slow", default=0.0,
                          **dict(lbls)),
            )
            for lbls, v in ctx.series("slo_burn_rate_fast").items()
        }

    return [
        RecordingRule("slo_budget_remaining_ratio", _remaining),
        RecordingRule("slo_burn_rate_fast", _burn(fast_window)),
        RecordingRule("slo_burn_rate_slow", _burn(slow_window)),
        AlertingRule(
            "SloBudgetBurn",
            _multiwindow,
            above=burn_threshold, for_s=for_s, severity="page",
            annotation=(
                "SLO {slo} burning its error budget {value:.1f}x too "
                "fast in BOTH burn windows (slo_budget_remaining_ratio "
                "shows what is left)"
            ),
        ),
    ]


def admission_rule_pack(
    *,
    quota_window: float = 300.0,
    quota_rate: float = 0.5,
    quota_for_s: float = 60.0,
    preempt_window: float = 300.0,
    preempt_rate: float = 1.0,
    preempt_for_s: float = 120.0,
    diverged_for_s: float = 60.0,
) -> list:
    """Gateway-fleet rules (ISSUE 18): the admission plane's abuse and
    divergence signals.

    - ``TenantQuotaStorm`` — sustained ``admission_quota_throttled``
      rate: some tenant is hammering past its token budget (the
      throttle is doing its job; the page is about the CLIENT, and
      ``obs gateways`` names the tenant).
    - ``AdmissionPreemptionChurn`` — batch work being revoked faster
      than ``preempt_rate``/s for minutes: interactive load is high
      enough that batch effectively never runs — capacity, not
      fairness, is the fix.
    - ``GatewayDiverged`` — ``gateway_converged`` stuck at 0: this
      gateway's reconstructed owner map disagrees with (or cannot
      reach) a peer, so affinity routing is split-brained.  Pages
      because the whole point of reconstructible state is that this
      should self-heal within one scrape cycle.

    Every family is absent-safe: missing metrics read as 0 rates and
    empty series, so the pack loads on any registry."""
    return [
        AlertingRule(
            "TenantQuotaStorm",
            lambda ctx: ctx.rate(
                "admission_quota_throttled_total", quota_window
            ),
            above=quota_rate, for_s=quota_for_s,
            annotation=(
                "tenants throttled at {value:.2f}/s — someone is "
                "sustained past their token quota (obs gateways shows "
                "per-tenant levels)"
            ),
        ),
        AlertingRule(
            "AdmissionPreemptionChurn",
            lambda ctx: ctx.rate(
                "admission_preemptions_total", preempt_window
            ),
            above=preempt_rate, for_s=preempt_for_s,
            annotation=(
                "batch admissions revoked at {value:.2f}/s — "
                "interactive load is starving batch; add capacity or "
                "lower interactive share"
            ),
        ),
        AlertingRule(
            "GatewayDiverged",
            lambda ctx: ctx.series("gateway_converged"),
            below=0.5, for_s=diverged_for_s, severity="page",
            annotation=(
                "gateway owner-map digest disagrees with a peer (or "
                "the peer is unreachable) — affinity routing is "
                "split-brained; POST /admin/ownermap to reconverge"
            ),
        ),
    ]


def replay_rule_pack(
    *,
    regression_x: float = 1.2,
    regression_for_s: float = 0.0,
    mismatch_window: float = 300.0,
) -> list:
    """Replay-harness rules (ISSUE 19): the A/B gate as alerts, for
    fleets that run a periodic replay canary instead of a one-shot
    ``obs replay diff``.

    - ``ReplayRegression`` — the last published diff's mean-TTFT
      ratio (``replay_ttft_regression_x``, written by
      ``serve.replay.export_gauges``) exceeds ``regression_x``: the
      candidate config is slower on the *same bytes* the baseline
      served, with ``/debug/replay`` holding the per-segment
      attribution.
    - ``ReplayMismatch`` — any ``replay_mismatch_total`` movement: a
      greedy replay produced different tokens than the recording.
      Pages, because wrong bytes are a correctness incident, not a
      latency one.

    Absent-safe like every pack: missing families read as empty
    series / 0 rates."""
    return [
        AlertingRule(
            "ReplayRegression",
            lambda ctx: ctx.series("replay_ttft_regression_x"),
            above=regression_x, for_s=regression_for_s,
            annotation=(
                "replayed workload TTFT at {value:.2f}x baseline — "
                "obs replay diff / /debug/replay attribute the "
                "regressed segments"
            ),
        ),
        AlertingRule(
            "ReplayMismatch",
            lambda ctx: ctx.rate("replay_mismatch_total", mismatch_window),
            above=0.0, severity="page",
            annotation=(
                "greedy replay produced tokens that differ from the "
                "recorded golden hashes — determinism or correctness "
                "broke; /debug/replay lists the mismatched requests"
            ),
        ),
    ]


def default_rule_pack(
    *,
    slo: float = 0.99,
    burn_window: float = 300.0,
    burn_threshold: float = 14.4,
    queue_depth: float = 10.0,
    queue_for_s: float = 30.0,
    kv_ratio: float = 0.9,
    kv_for_s: float = 10.0,
    breaker_for_s: float = 10.0,
    pool_for_s: float = 30.0,
    tenant_slo: float | None = None,
    tenant_burn_threshold: float | None = None,
    tenant_for_s: float = 60.0,
    replica_down_for_s: float = 0.0,
    compile_storm_rate: float = 0.1,
    compile_window: float = 60.0,
    compile_for_s: float = 30.0,
    goodput_ratio: float = 0.5,
    goodput_for_s: float = 30.0,
    checkpoint_stall_s: float = 120.0,
    checkpoint_for_s: float = 0.0,
    straggler_skew: float = 1.5,
    straggler_for_s: float = 30.0,
    slo_objectives: list[SloObjective] | None = None,
    slo_fast_window: float = 300.0,
    slo_slow_window: float = 3600.0,
    slo_burn_threshold: float = 14.4,
    slo_for_s: float = 60.0,
    canary_for_s: float = 30.0,
    replica_unhealthy_for_s: float = 0.0,
) -> list:
    """The platform's default recording + alerting rules.

    Recording: HTTP error ratio and SLO burn rate over ``burn_window``
    (from ``http_requests_total``), reconcile-duration and serve-TTFT
    p95s (exact, from the histogram reservoirs), and the per-tenant
    goodput burn rate (from ``serve_tenant_{goodput_,}tokens_total`` —
    serve/batcher.py's tenant accounting).  Alerting: QueueBacklog
    (per workqueue), KVCacheSaturation, HighErrorBurnRate (on the
    recorded burn rate — 14.4 is the standard fast-burn page threshold),
    BreakerOpen (per endpoint; state 2 = open), PoolDegraded (per pool;
    ratio 1.0 = all desired replicas ready), TenantSloBurnRate (per
    tenant, on the recorded goodput burn), and FleetReplicaDown (per
    replica, on ``fleet_replica_up`` — the federation collector drops
    it to 0 after M consecutive scrape failures, so the hold lives in
    the collector's ``down_after`` and ``replica_down_for_s`` defaults
    to 0: the M-th failed scrape walks pending→firing in one tick),
    and CompileStorm (rate of ``xla_compiles_total`` over
    ``compile_window`` — steady-state serving compiles zero new
    executables, so a sustained rate above ``compile_storm_rate``
    means shapes are churning on live traffic).

    Canary trio (ISSUE 14, fed by ``serve/canary.py``'s prober):
    CanaryFailing on ``probe_replica_healthy`` below 0.75 (the FSM's
    degraded state exports 0.5 — first hard failure, early warning),
    ReplicaUnhealthy below 0.25 (the FSM walked to unhealthy; the
    prober has already quarantined the replica in the router, so the
    page means "capacity lost", and ``replica_unhealthy_for_s``
    defaults to 0 because the K-of-N window IS the hold), and the
    ``slo_rule_pack`` appended last: per-objective budget gauges and
    the multi-window SloBudgetBurn page.

    Training-goodput trio (ISSUE 13, fed by ``utils/goodput.py`` and
    ``train/checkpoint.py``): GoodputDegraded on the windowed
    ``train_goodput_ratio`` below ``goodput_ratio`` (the gauge defaults
    to 1.0 when no trainer is running, so the rule is inert on
    serve-only registries), CheckpointStall on the per-op
    ``train_checkpoint_seconds`` p95 above ``checkpoint_stall_s``
    (saves are infrequent, so ``checkpoint_for_s`` defaults to 0 — one
    breaching tick walks pending→firing), and StragglerDetected on
    ``train_step_skew_ratio`` above ``straggler_skew`` (the slowest
    host is named by ``train_straggler_host`` — `obs goodput` shows
    it).

    ``tenant_slo``/``tenant_burn_threshold`` default to ``slo``/
    ``burn_threshold``.  Rules whose input families are absent (no
    tenants served yet, no federation collector feeding the registry)
    simply have no label-sets to evaluate — the pack is safe to run on
    any registry."""
    t_slo = slo if tenant_slo is None else tenant_slo
    t_burn = (
        burn_threshold if tenant_burn_threshold is None
        else tenant_burn_threshold
    )

    def _tenant_burn(ctx: Ctx) -> dict:
        # One FSM per tenant, replica dimension collapsed: in a
        # federated registry the token counters carry replica= labels,
        # and per-(tenant, replica) burn FSMs would page N times for
        # one tenant's breach.  ``ctx.rate(..., tenant=t)`` sums the
        # matching series whatever other labels ride along.
        out: dict[LabelSet, float] = {}
        tenants = {
            dict(lbls).get("tenant")
            for lbls in ctx.series("serve_tenant_tokens_total")
        }
        # Seed the goodput watch alongside the total watch so both
        # families have rate history from the same tick onward.
        ctx.rate("serve_tenant_goodput_tokens_total", burn_window)
        # "_"-prefixed tenants are reserved for synthetic traffic
        # (journal.PROBE_TENANT): canary probes must not page their own
        # tenant-SLO rule.  The batcher already keeps probes out of the
        # serve_tenant_* families; this guard makes the exclusion hold
        # even against a registry fed by an older replica.
        for t in sorted(t for t in tenants if t and not t.startswith("_")):
            key = (("tenant", t),)
            total = ctx.rate(
                "serve_tenant_tokens_total", burn_window, tenant=t
            )
            if total <= 0.0:
                out[key] = 0.0
                continue
            good = ctx.rate(
                "serve_tenant_goodput_tokens_total", burn_window,
                tenant=t,
            )
            bad_ratio = max(0.0, total - good) / total
            out[key] = bad_ratio / max(1e-9, 1.0 - t_slo)
        return out

    return [
        RecordingRule(
            "http_error_ratio",
            lambda ctx: ctx.ratio(
                ctx.rate("http_requests_total", burn_window, code=_is_5xx),
                ctx.rate("http_requests_total", burn_window),
            ),
        ),
        RecordingRule(
            "slo_burn_rate",
            lambda ctx: ctx.gauge("http_error_ratio") / max(1e-9, 1.0 - slo),
        ),
        RecordingRule(
            "reconcile_duration_p95",
            lambda ctx: ctx.percentiles("reconcile_duration_seconds", 0.95),
        ),
        RecordingRule(
            "serve_ttft_p95",
            lambda ctx: ctx.percentiles("serve_ttft_seconds", 0.95),
        ),
        RecordingRule("tenant_slo_burn_rate", _tenant_burn),
        AlertingRule(
            "QueueBacklog",
            lambda ctx: ctx.series("workqueue_depth"),
            above=queue_depth, for_s=queue_for_s,
            annotation="workqueue {queue} backlog at {value:.0f} items",
        ),
        AlertingRule(
            # The input gauge is PHYSICAL occupancy: the paged batcher
            # counts a block shared by N slots once and refcount-0
            # cached (reclaimable) blocks as free, so block-granular
            # prefix sharing can't double-count its way over the
            # threshold (serve/kv_blocks.py, docs/platform/kv-cache.md).
            "KVCacheSaturation",
            lambda ctx: ctx.series("serve_kv_occupancy_ratio"),
            above=kv_ratio, for_s=kv_for_s,
            annotation="KV cache {value:.0%} full — admissions will defer",
        ),
        AlertingRule(
            "HighErrorBurnRate",
            lambda ctx: ctx.gauge("slo_burn_rate"),
            above=burn_threshold, for_s=60.0, severity="page",
            annotation=(
                "error budget burning {value:.1f}x too fast over the "
                "short window"
            ),
        ),
        AlertingRule(
            "BreakerOpen",
            lambda ctx: ctx.series("circuit_breaker_state"),
            above=1.5, for_s=breaker_for_s,
            annotation="circuit breaker {endpoint} is open",
        ),
        AlertingRule(
            "PoolDegraded",
            lambda ctx: ctx.series("pool_ready_ratio"),
            below=1.0, for_s=pool_for_s,
            annotation="pool {pool} ({kind}) at {value:.0%} of desired",
        ),
        AlertingRule(
            "TenantSloBurnRate",
            lambda ctx: ctx.series("tenant_slo_burn_rate"),
            above=t_burn, for_s=tenant_for_s, severity="page",
            annotation=(
                "tenant {tenant} burning its goodput budget {value:.1f}x "
                "too fast"
            ),
        ),
        AlertingRule(
            "FleetReplicaDown",
            lambda ctx: ctx.series("fleet_replica_up"),
            below=0.5, for_s=replica_down_for_s, severity="page",
            annotation=(
                "replica {replica} unreachable — scrape failed for "
                "consecutive federation ticks"
            ),
        ),
        AlertingRule(
            # Steady-state serving/training compiles ZERO new XLA
            # executables after warmup (the conftest recompile guard
            # pins that in CI); a sustained nonzero compile rate in
            # production means a static-shape regression is minting
            # fresh programs on live traffic — seconds of dead air per
            # compile on a tunneled TPU.  The 30 s hold lets warmup
            # bursts (restart, new bucket ladder) pass without paging.
            "CompileStorm",
            lambda ctx: ctx.rate("xla_compiles_total", compile_window),
            above=compile_storm_rate, for_s=compile_for_s,
            severity="page",
            annotation=(
                "XLA recompiling at {value:.2f}/s in steady state — "
                "static-shape regression? (utils/compat.py compile "
                "telemetry; obs profile shows the compile counters)"
            ),
        ),
        AlertingRule(
            # Windowed goodput (productive step-seconds over the
            # ledger's rolling window), so the alert RESOLVES once a
            # recovered run refills the window — a cumulative ratio
            # would stay breached forever after one long outage.
            "GoodputDegraded",
            lambda ctx: ctx.gauge("train_goodput_ratio", default=1.0),
            below=goodput_ratio, for_s=goodput_for_s,
            annotation=(
                "training goodput at {value:.0%} of wall-clock — "
                "obs goodput shows where the time went"
            ),
        ),
        AlertingRule(
            "CheckpointStall",
            lambda ctx: ctx.percentiles("train_checkpoint_seconds", 0.95),
            above=checkpoint_stall_s, for_s=checkpoint_for_s,
            annotation=(
                "checkpoint {op} p95 at {value:.0f}s — the run stalls "
                "this long every interval (train_checkpoint_seconds)"
            ),
        ),
        AlertingRule(
            "StragglerDetected",
            lambda ctx: ctx.gauge("train_step_skew_ratio", default=1.0),
            above=straggler_skew, for_s=straggler_for_s,
            annotation=(
                "slowest host runs steps {value:.1f}x the median — the "
                "gang waits for it every step (train_straggler_host "
                "names it)"
            ),
        ),
        AlertingRule(
            # The prober exports healthy=1.0 / degraded=0.5 /
            # unhealthy=0.0, so one threshold per FSM state boundary:
            # below 0.75 catches degraded-or-worse (early warning),
            # below 0.25 catches the quarantine itself.
            "CanaryFailing",
            lambda ctx: ctx.series("probe_replica_healthy"),
            below=0.75, for_s=canary_for_s,
            annotation=(
                "canary probes failing on replica {replica} — "
                "probe_failures_total says why (obs probes)"
            ),
        ),
        AlertingRule(
            "ReplicaUnhealthy",
            lambda ctx: ctx.series("probe_replica_healthy"),
            below=0.25, for_s=replica_unhealthy_for_s, severity="page",
            annotation=(
                "replica {replica} failed the canary FSM and is "
                "quarantined — the router sends it no new traffic "
                "until probes recover"
            ),
        ),
        *slo_rule_pack(
            slo_objectives,
            fast_window=slo_fast_window,
            slow_window=slo_slow_window,
            burn_threshold=slo_burn_threshold,
            for_s=slo_for_s,
        ),
    ]
