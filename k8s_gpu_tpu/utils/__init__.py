from .alerts import (
    AlertingRule,
    RecordingRule,
    RuleEvaluator,
    default_rule_pack,
)
from .clock import Clock, RealClock, FakeClock
from .faults import FaultInjector, FaultPlan, InjectedFault, global_faults
from .federation import FleetCollector, bucket_quantile
from .metrics import MetricsRegistry, global_metrics, parse_exposition
from .logstore import LogEntry, LogStore, LogStoreHandler, global_logstore
from .obs import (
    MetricsServer,
    render_fleet,
    render_profile,
    render_replay,
    render_requests,
    render_route,
    render_top,
    render_top_columns,
)
from .profiler import PhaseProfiler, chrome_trace, profile_snapshot
from .profiling import profile_trainer, step_annotation, trace, trace_files
from .tracing import (
    SpanContext,
    Tracer,
    format_traceparent,
    global_tracer,
    parse_traceparent,
    render_trace,
)
from .waterfall import FleetTraceAssembler, split_by_process

__all__ = [
    "AlertingRule",
    "RecordingRule",
    "RuleEvaluator",
    "default_rule_pack",
    "Clock",
    "RealClock",
    "FakeClock",
    "FleetCollector",
    "bucket_quantile",
    "parse_exposition",
    "render_fleet",
    "render_replay",
    "render_requests",
    "render_route",
    "render_top",
    "render_top_columns",
    "FaultInjector",
    "FaultPlan",
    "InjectedFault",
    "global_faults",
    "MetricsRegistry",
    "global_metrics",
    "LogEntry",
    "LogStore",
    "LogStoreHandler",
    "global_logstore",
    "MetricsServer",
    "PhaseProfiler",
    "chrome_trace",
    "profile_snapshot",
    "render_profile",
    "SpanContext",
    "Tracer",
    "format_traceparent",
    "global_tracer",
    "parse_traceparent",
    "render_trace",
    "FleetTraceAssembler",
    "split_by_process",
    "trace",
    "step_annotation",
    "profile_trainer",
    "trace_files",
]
