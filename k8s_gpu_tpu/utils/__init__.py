from .clock import Clock, RealClock, FakeClock
from .metrics import MetricsRegistry, global_metrics

__all__ = ["Clock", "RealClock", "FakeClock", "MetricsRegistry", "global_metrics"]
