from .clock import Clock, RealClock, FakeClock
from .metrics import MetricsRegistry, global_metrics
from .logstore import LogEntry, LogStore, LogStoreHandler, global_logstore
from .obs import MetricsServer

__all__ = [
    "Clock",
    "RealClock",
    "FakeClock",
    "MetricsRegistry",
    "global_metrics",
    "LogEntry",
    "LogStore",
    "LogStoreHandler",
    "global_logstore",
    "MetricsServer",
]
