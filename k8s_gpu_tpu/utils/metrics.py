"""Minimal Prometheus-style metrics registry.

The reference specifies observability only in prose (Prometheus for GPU util /
queue length / PV usage, GPU调度平台搭建.md:798-807); the graded baseline metric
is a *reconcile wall-clock*, so first-party latency histograms are first-class
here rather than an afterthought.
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from dataclasses import dataclass, field


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 30, 60, 120, 300)


@dataclass
class Histogram:
    buckets: tuple = _DEFAULT_BUCKETS
    counts: list = field(default_factory=list)
    total: float = 0.0
    n: int = 0
    # Bounded rolling window of raw observations for EXACT percentiles —
    # bucket-bound estimates made the serving latency story read as
    # "p95 <= 5 s" when the true p95 was far lower.  4096 doubles are
    # 32 KB per histogram; recent behavior is what latency percentiles
    # are for, so overflow drops the oldest.
    raw: object = field(default_factory=lambda: deque(maxlen=4096))

    def __post_init__(self):
        if not self.counts:
            self.counts = [0] * (len(self.buckets) + 1)

    def observe(self, v: float) -> None:
        self.total += v
        self.n += 1
        self.raw.append(v)
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def percentile(self, q: float) -> float:
        """Exact q-quantile over the (rolling) reservoir; 0.0 if empty."""
        if not self.raw:
            return 0.0
        s = sorted(self.raw)
        k = min(len(s) - 1, max(0, int(q * len(s))))
        return s[k]

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0


class MetricsRegistry:
    """Thread-safe counters, gauges, histograms with label support."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, Histogram] = {}

    @staticmethod
    def _key(name: str, labels: dict | None) -> tuple:
        return (name, tuple(sorted((labels or {}).items())))

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        with self._lock:
            self._counters[self._key(name, labels)] += value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = value

    def observe(self, name: str, value: float, **labels) -> None:
        with self._lock:
            k = self._key(name, labels)
            if k not in self._hists:
                self._hists[k] = Histogram()
            self._hists[k].observe(value)

    def counter(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get(self._key(name, labels), 0.0)

    def gauge(self, name: str, **labels) -> float | None:
        with self._lock:
            return self._gauges.get(self._key(name, labels))

    def histogram(self, name: str, **labels) -> Histogram | None:
        with self._lock:
            return self._hists.get(self._key(name, labels))

    def render(self) -> str:
        """Prometheus text exposition format (scrape-compatible subset)."""
        lines = []
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                lines.append(f"{name}{_fmt(labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                lines.append(f"{name}{_fmt(labels)} {v}")
            for (name, labels), h in sorted(self._hists.items()):
                cum = 0
                for b, c in zip(h.buckets, h.counts):
                    cum += c
                    lines.append(
                        f"{name}_bucket{_fmt(labels + (('le', f'{b:g}'),))} {cum}"
                    )
                lines.append(
                    f"{name}_bucket{_fmt(labels + (('le', '+Inf'),))} {h.n}"
                )
                lines.append(f"{name}_count{_fmt(labels)} {h.n}")
                lines.append(f"{name}_sum{_fmt(labels)} {h.total}")
        return "\n".join(lines) + "\n"


def _fmt(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


global_metrics = MetricsRegistry()
