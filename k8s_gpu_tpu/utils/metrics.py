"""Minimal Prometheus-style metrics registry.

The reference specifies observability only in prose (Prometheus for GPU util /
queue length / PV usage, GPU调度平台搭建.md:798-807); the graded baseline metric
is a *reconcile wall-clock*, so first-party latency histograms are first-class
here rather than an afterthought.
"""

from __future__ import annotations

import re
import threading
from collections import defaultdict, deque
from dataclasses import dataclass, field


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 30, 60, 120, 300)


@dataclass
class Histogram:
    buckets: tuple = _DEFAULT_BUCKETS
    counts: list = field(default_factory=list)
    total: float = 0.0
    n: int = 0
    # Bounded rolling window of raw observations for EXACT percentiles —
    # bucket-bound estimates made the serving latency story read as
    # "p95 <= 5 s" when the true p95 was far lower.  4096 doubles are
    # 32 KB per histogram; recent behavior is what latency percentiles
    # are for, so overflow drops the oldest.
    raw: object = field(default_factory=lambda: deque(maxlen=4096))

    def __post_init__(self):
        if not self.counts:
            self.counts = [0] * (len(self.buckets) + 1)

    def observe(self, v: float) -> None:
        self.total += v
        self.n += 1
        self.raw.append(v)
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def percentile(self, q: float) -> float:
        """Exact q-quantile over the (rolling) reservoir; 0.0 if empty.

        Concurrency-safe access goes through ``MetricsRegistry.percentile``
        (which holds the registry lock that ``observe`` also holds); a bare
        call retries if a concurrent append mutates the deque mid-sort."""
        while True:
            try:
                s = sorted(self.raw)
                break
            except RuntimeError:
                # deque mutated during iteration — take a fresh snapshot
                continue
        if not s:
            return 0.0
        k = min(len(s) - 1, max(0, int(q * len(s))))
        return s[k]

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0


class MetricsRegistry:
    """Thread-safe counters, gauges, histograms with label support.

    ``max_series_per_name`` caps unique label-sets per metric name: writes
    past the cap collapse to the single series ``{other="true"}`` and bump
    ``metrics_series_dropped_total{metric}`` — the registry never evicts,
    so a direct ``inc()`` site fed attacker-controlled label values must
    not be able to mint unbounded series (the same property
    ``RequestMetricsMixin._route`` enforces for HTTP routes)."""

    _OVERFLOW = (("other", "true"),)

    # Lock contract (graftcheck lockcheck + utils.faults
    # guard_declared): every store is written by arbitrary caller
    # threads and read by scrape/rules threads; the percentile fix (PR
    # 4) exists because one read path skipped this lock.
    _GUARDED_BY = {
        "_lock": ("_counters", "_gauges", "_hists", "_series_seen"),
    }

    def __init__(self, max_series_per_name: int = 256):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, Histogram] = {}
        self.max_series_per_name = max(1, int(max_series_per_name))
        self._series_seen: dict[str, set] = defaultdict(set)

    @staticmethod
    def _key(name: str, labels: dict | None) -> tuple:
        return (name, tuple(sorted((labels or {}).items())))

    def _key_write(self, name: str, labels: dict | None) -> tuple:
        """The write-path key: tracks per-name label-set cardinality and
        collapses overflow.  Lock held by caller; reads use ``_key`` (a
        lookup must never mint a series)."""
        k = self._key(name, labels)
        lbls = k[1]
        if not lbls:
            return k
        seen = self._series_seen[name]
        if lbls in seen:
            return k
        if len(seen) >= self.max_series_per_name:
            # Bounded by the number of metric NAMES, so this counter's own
            # label can't itself explode.
            self._counters[
                ("metrics_series_dropped_total", (("metric", name),))
            ] += 1
            return (name, self._OVERFLOW)
        seen.add(lbls)
        return k

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        with self._lock:
            self._counters[self._key_write(name, labels)] += value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[self._key_write(name, labels)] = value

    def set_gauge_series(self, name: str, value: float,
                         labels: dict) -> None:
        """Explicit-dict variant of ``set_gauge`` for label keys the
        kwargs form reserves (``name``/``value``) — the path rebuilding a
        registry from a parsed exposition, where label keys are data."""
        with self._lock:
            self._gauges[self._key_write(name, labels)] = value

    def remove_gauge(self, name: str, **labels) -> None:
        """Delete one gauge series — the ONLY eviction the registry
        allows, for per-object gauges whose object is gone (a deleted
        pool's ready-ratio).  Counters/histograms stay append-only; a
        stale gauge would otherwise keep object-scoped alerts firing
        forever against nothing.  The label-set's cardinality slot is
        freed too (unless a counter/histogram still holds the same
        series): object churn must not ratchet toward the cap, or the
        N+1th pool's gauges would collapse into the overflow series —
        which nothing can ever clear."""
        with self._lock:
            k = self._key(name, labels)
            self._gauges.pop(k, None)
            if k not in self._counters and k not in self._hists:
                self._series_seen.get(name, set()).discard(k[1])

    def observe(self, name: str, value: float, **labels) -> None:
        with self._lock:
            k = self._key_write(name, labels)
            if k not in self._hists:
                self._hists[k] = Histogram()
            self._hists[k].observe(value)

    def counter(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get(self._key(name, labels), 0.0)

    def gauge(self, name: str, **labels) -> float | None:
        with self._lock:
            return self._gauges.get(self._key(name, labels))

    def histogram(self, name: str, **labels) -> Histogram | None:
        with self._lock:
            return self._hists.get(self._key(name, labels))

    def percentile(self, name: str, q: float, **labels) -> float:
        """Exact q-quantile of a histogram's reservoir, snapshotted UNDER
        the registry lock — ``observe`` holds the same lock, so the sort
        can never race a concurrent append (the ``RuntimeError: deque
        mutated during iteration`` hazard of sorting a live handle)."""
        with self._lock:
            h = self._hists.get(self._key(name, labels))
            return h.percentile(q) if h is not None else 0.0

    def series(self, name: str) -> dict[tuple, float]:
        """Snapshot every series of *name* across counters and gauges:
        ``{label_tuple: value}`` — the rules engine's read surface."""
        with self._lock:
            out: dict[tuple, float] = {}
            for (n, lbls), v in self._counters.items():
                if n == name:
                    out[lbls] = v
            for (n, lbls), v in self._gauges.items():
                if n == name:
                    out[lbls] = v
            return out

    def hist_percentiles(self, name: str, q: float) -> dict[tuple, float]:
        """Per-label-set exact percentiles for one histogram family,
        computed under the lock: ``{label_tuple: quantile}``."""
        with self._lock:
            return {
                lbls: h.percentile(q)
                for (n, lbls), h in self._hists.items()
                if n == name
            }

    def render(self) -> str:
        """Prometheus text exposition format (scrape-compatible subset)."""
        lines = []
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                lines.append(f"{name}{_fmt(labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                lines.append(f"{name}{_fmt(labels)} {v}")
            for (name, labels), h in sorted(self._hists.items()):
                cum = 0
                for b, c in zip(h.buckets, h.counts):
                    cum += c
                    lines.append(
                        f"{name}_bucket{_fmt(labels + (('le', f'{b:g}'),))} {cum}"
                    )
                lines.append(
                    f"{name}_bucket{_fmt(labels + (('le', '+Inf'),))} {h.n}"
                )
                lines.append(f"{name}_count{_fmt(labels)} {h.n}")
                lines.append(f"{name}_sum{_fmt(labels)} {h.total}")
        return "\n".join(lines) + "\n"


def escape_label_value(v) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline (the only three the spec escapes).  Without it a
    label value carrying a quote breaks the line's label block and a
    newline splits the sample across two unparseable lines — a tenant
    name is caller-supplied data, so the exposition must round-trip it."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def unescape_label_value(v: str) -> str:
    """Inverse of ``escape_label_value``; unknown escapes pass through
    backslash-dropped, matching Prometheus's lenient readers."""
    if "\\" not in v:
        return v
    out = []
    i, n = 0, len(v)
    while i < n:
        c = v[i]
        if c == "\\" and i + 1 < n:
            nxt = v[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _fmt(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


_EXPO_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$"
)
# Label values may contain any character; escaped sequences (\\, \",
# \n) ride as two-character pairs, so the value body is "anything but a
# bare quote or backslash, or an escape pair".
_EXPO_LABEL = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"'
)


def parse_exposition(text: str) -> dict[str, dict[tuple, float]]:
    """Parse the text exposition format ``render`` emits back into
    ``{name: {label_tuple: value}}`` — what lets ``obs top`` (and the
    fleet federation collector, utils/federation.py) render a
    fleet-utilization snapshot from ONE ``/metrics`` scrape (or the
    persisted ``metrics.prom``) without any client library.

    Hardened against the full text-format value range: escaped label
    values (``\\"``, ``\\\\``, ``\\n``) round-trip against ``render``'s
    own output, and ``NaN``/``+Inf``/``-Inf`` sample values parse to
    their float counterparts (Prometheus stale markers and unbounded
    buckets are real scrape content, not malformed lines)."""
    out: dict[str, dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _EXPO_LINE.match(line)
        if m is None:
            continue
        name, raw_labels, raw_value = m.groups()
        try:
            # float() accepts "NaN", "+Inf", "-Inf" (any case) natively.
            value = float(raw_value)
        except ValueError:
            continue
        labels = tuple(sorted(
            (k, unescape_label_value(v))
            for k, v in _EXPO_LABEL.findall(raw_labels or "")
        ))
        out.setdefault(name, {})[labels] = value
    return out


global_metrics = MetricsRegistry()
