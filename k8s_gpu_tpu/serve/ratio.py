"""Prefill:decode worker-split controller for disaggregated serving.

ISSUE 20: once prefill and decode run in separate worker pools
(serve/frontend.py classifies by prompt length and hands long prompts
prefill→export→import→decode over the migration wire), the pool SPLIT
becomes a control problem — a long-prompt-heavy mix starves for
prefill capacity while decode workers idle, and a short-prompt mix
does the opposite.  ``RatioController`` closes that loop the same way
``FleetAutoscaler`` closes the replica-count loop: a pure, Clock-driven
FSM whose ``decide`` is a deterministic function of (pool sizes,
observed token-arrival rates, clock time, last-action time) — the same
scripted sequence produces byte-identical decisions under ``FakeClock``,
which is what makes the reassignment testable and replayable.

The signal is the *traffic mix*, not utilization: ``prefill_tps`` is
the arrival rate of prompt tokens on disagg-classified (long) requests
and ``decode_tps`` the arrival rate of requested decode tokens — both
derived from the gateway's federated counters
(``disagg_prefill_tokens_total`` / ``disagg_decode_tokens_total``), so
any scraper can recompute the controller's input.  The desired prefill
share of the pool is the prefill share of the token flow; the
controller steps the split at most ``max_step`` worker(s) per action,
holds inside a hysteresis deadband so a noisy mix never flaps a worker
back and forth, and enforces a cooldown between actions exactly like
the autoscaler (reassignment costs a drain + role flip on a real
fleet).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.clock import Clock, RealClock
from ..utils.metrics import MetricsRegistry, global_metrics


@dataclass
class RatioDecision:
    target_prefill: int
    reason: str      # mix_shift | hold | cooldown | idle
    direction: int   # +1 grow prefill pool, -1 shrink, 0 hold


class RatioController:
    """Deterministic prefill:decode split FSM over the traffic mix.

    ``decide`` never moves more than ``max_step`` workers per action,
    never shrinks the decode pool below ``min_decode`` (decode owns the
    resident KV — a fleet with no decode workers serves nothing), and
    never acts twice inside ``cooldown_s``.  With no traffic at all it
    holds (``idle``): a quiet fleet keeps its last shape rather than
    collapsing to a default."""

    def __init__(
        self,
        *,
        min_prefill: int = 0,
        min_decode: int = 1,
        clock: Clock | None = None,
        cooldown_s: float = 30.0,
        max_step: int = 1,
        deadband: float = 0.15,
        metrics: MetricsRegistry | None = None,
    ):
        """``deadband``: minimum absolute gap between the observed
        prefill token share and the current prefill worker share
        before a move is worth a reassignment — the hysteresis that
        keeps a mix hovering near a pool boundary from flapping a
        worker every cooldown."""
        self.min_prefill = max(0, int(min_prefill))
        self.min_decode = max(1, int(min_decode))
        self.clock = clock or RealClock()
        self.cooldown_s = float(cooldown_s)
        self.max_step = max(1, int(max_step))
        self.deadband = max(0.0, float(deadband))
        self.metrics = metrics if metrics is not None else global_metrics
        self._last_action = float("-inf")

    def decide(
        self,
        *,
        prefill_workers: int,
        decode_workers: int,
        prefill_tps: float = 0.0,
        decode_tps: float = 0.0,
        now: float | None = None,
    ) -> RatioDecision:
        """``prefill_tps``/``decode_tps``: token-arrival rates over the
        gateway's observation window (tokens/second; any consistent
        unit works — only the RATIO enters the decision)."""
        now = self.clock.now() if now is None else now
        prefill = max(0, int(prefill_workers))
        decode = max(0, int(decode_workers))
        total = prefill + decode
        if total <= 0:
            return self._hold(prefill, "idle")
        flow = float(prefill_tps) + float(decode_tps)
        if flow <= 0.0:
            return self._hold(prefill, "idle")
        share = float(prefill_tps) / flow
        current = prefill / total
        if abs(share - current) <= self.deadband:
            return self._hold(prefill, "hold")
        # Deterministic round-half-up (round() would bank to even), then
        # clamp to the pool-shape floors.
        desired = int(share * total + 0.5)
        desired = min(max(desired, self.min_prefill), total - self.min_decode)
        if desired == prefill:
            return self._hold(prefill, "hold")
        if now - self._last_action < self.cooldown_s:
            return self._hold(prefill, "cooldown")
        step = min(self.max_step, abs(desired - prefill))
        target = prefill + step if desired > prefill else prefill - step
        return self._act(prefill, target, now)

    def _hold(self, prefill: int, reason: str) -> RatioDecision:
        self.metrics.set_gauge(
            "disagg_ratio_target_prefill", float(prefill)
        )
        return RatioDecision(
            target_prefill=prefill, reason=reason, direction=0
        )

    def _act(self, prefill: int, target: int, now: float) -> RatioDecision:
        self._last_action = now
        direction = 1 if target > prefill else -1
        self.metrics.inc(
            "disagg_ratio_actions_total",
            direction="grow" if direction > 0 else "shrink",
        )
        self.metrics.set_gauge(
            "disagg_ratio_target_prefill", float(target)
        )
        return RatioDecision(
            target_prefill=target, reason="mix_shift", direction=direction
        )
