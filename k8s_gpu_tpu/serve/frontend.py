"""Cross-process fleet front-end: the one door over N ``LmServer``s.

``FleetRouter`` (serve/router.py) made placement a policy; until now the
policy ran inside whatever process also owned the replicas.  This module
is the missing half of ROADMAP item 1 — a standalone ``FleetFrontend``
HTTP process that owns a ``FleetRouter`` + ``FleetCollector`` +
``CanaryProber`` over *remote* ``LmServer`` base URLs and speaks the
same ``POST /generate`` contract to clients, so replicas can come, go,
and die without the client-visible endpoint moving (the FlexNPU /
VirtualFlow decoupling: dispatch outlives any worker).

Per request the gateway tokenizes the prompt and routes on the
page-aligned chain hashes (``kv_blocks.shareable_chain`` through
``FleetRouter.route`` — the SAME helper the batcher's paged admission
keys on, so gateway routing and replica block caches can never skew),
then forwards downstream with the ``x-route-replica`` /
``x-route-reason`` stamp plus tenant / deadline / traceparent
propagation.  Failure handling reuses ``cloud/resilience.py`` — a
``BreakerBank`` gates contact per replica and a ``RetryPolicy`` paces
re-dispatch with deterministic jitter — not a new retry stack:

==================  =========================================  ==========
downstream outcome  gateway action                             client sees
==================  =========================================  ==========
connect error /     ``record_failure`` + ``mark_down`` +       200 from a
timeout / 5xx       ``serve_router_rehash_total``; re-route    survivor
429 Retry-After     retry elsewhere WITHOUT marking down       200, or the
                    (full is load, not death); last shed       last 429 +
                    passes through verbatim                    Retry-After
other 4xx           a REQUEST fault — identical on every       that 4xx
                    replica; passes through immediately
504                 the request's own deadline died downstream 504
                    — retrying would duplicate work it can
                    no longer use
no eligible         503 + Retry-After,                         503
replica             ``frontend_shed_total{reason=no_replica}``
==================  =========================================  ==========

Replica lifecycle is dynamic: ``POST /admin/replicas`` registers an
endpoint (gated on its ``/readyz`` — an unwarmed-but-alive replica is
warmed with one real ``/generate`` first, which doubles as an
end-to-end smoke test of the URL), ``DELETE`` retires it, and
``POST /admin/drain`` starts an ASYNCHRONOUS in-flight-aware drain:
``drain(name)`` stops new traffic immediately (``FleetRouter.drain``),
but the victim is only retired once its in-flight count reaches zero —
read gateway-locally first, then from the replica's ``/readyz``
``inflight`` field (the scrape-free fast path ``LmServer`` exports),
then from the federated ``serve_pending_requests`` /
``serve_slots_active`` gauges — or a deadline forces it
(``frontend_drains_total{outcome=forced}``).

Canary probes flow THROUGH the front-end: each replica's probe target
is the gateway's own ``POST /replica/<name>/generate`` pinned-dispatch
path, so the black-box health verdict covers the real client path
(gateway handling, header propagation, downstream HTTP) — and a
successful pinned contact is also the recovery path that ``mark_up``s
a replica the dispatch loop had marked down.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..cloud.resilience import BreakerBank, RetryPolicy
from ..utils.clock import Clock, RealClock
from ..utils.faults import global_faults
from ..utils.federation import FleetCollector
from ..utils.metrics import MetricsRegistry, global_metrics
from ..utils.obs import RequestMetricsMixin
from ..utils.tracing import (
    SpanContext,
    format_traceparent,
    global_tracer,
    new_span_id,
)
from .canary import CanaryProber
from .journal import RequestJournal
from .journal import RequestRecord as JournalRecord
from .kv_blocks import shareable_chain
from .migrate import BlockMigrator
from .ratio import RatioController, RatioDecision
from .router import FleetRouter

log = logging.getLogger("k8s_gpu_tpu.frontend")

# Advisory client backoff on gateway-minted 503s (matches LmServer's).
RETRY_AFTER_S = 1


def merge_owner_map(scrapes: dict) -> dict:
    """Pure merge of per-replica ``/debug/chains`` scrape bodies into
    ONE chain→owner map — the gateway fleet's reconstruction kernel
    (ROADMAP item 3): routing state is *reconstructible rather than
    replicated*, so N gateways started independently converge to the
    same map with no gossip, no consensus, and no shared store.

    ``scrapes`` maps replica name → list of hex chain hashes warm on
    it.  A chain warm on exactly one replica is owned by it; a chain
    warm on several (migration copies, fallback re-routes) tie-breaks
    by rendezvous hash on the CHAIN bytes over the sorted claimant set
    — the same HRW primitive brand-new chains route by, so every
    gateway computing this merge lands on the same owner.  Output is
    ``{hex: owner}`` over sorted hashes; malformed hashes are dropped
    (a corrupt scrape entry must not poison the whole map)."""
    claims: dict[str, list[str]] = {}
    for name in sorted(scrapes):
        for h in scrapes[name]:
            if not isinstance(h, str) or not h:
                continue
            try:
                bytes.fromhex(h)
            except ValueError:
                continue
            claims.setdefault(h, []).append(name)
    out: dict[str, str] = {}
    for h in sorted(claims):
        owners = sorted(set(claims[h]))
        if len(owners) == 1:
            out[h] = owners[0]
            continue
        out[h] = FleetRouter._rendezvous(bytes.fromhex(h), owners)
    return out


def owner_map_digest(mapping: dict) -> str:
    """The agreement fingerprint two gateways compare: blake2b over
    the canonical JSON of the chain→owner map.  Byte-identical maps —
    the reconstruction contract — give byte-identical digests."""
    blob = json.dumps(
        mapping, sort_keys=True, separators=(",", ":")
    ).encode()
    return hashlib.blake2b(blob, digest_size=8).hexdigest()


class FleetFrontend:
    """The gateway process (module docstring for the model).  ``port=0``
    binds ephemeral; ``.port`` is the bound one.  All collaborators are
    injectable and default to privately-owned instances on the shared
    ``clock`` — one time domain across router staleness, breaker reset,
    probe pacing, and drain deadlines, which is what makes the whole
    plane replayable under ``FakeClock``."""

    # Lock contract (graftcheck lockcheck): the replica URL map, the
    # gateway-local in-flight counters, the drain state table, and the
    # live-dispatch table (per-replica in-flight request info — the
    # forced-drain abandonment audit) are shared between request
    # handler threads, admin handlers, and the per-drain waiter
    # threads.
    _GUARDED_BY = {
        "_lock": ("_replicas", "_inflight", "_drains", "_live",
                  "_live_seq", "_peers", "_owner_map", "_owner_digest",
                  "_owner_seq", "_roles", "_mix"),
    }

    def __init__(
        self,
        tokenizer,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        page_size: int = 64,
        clock: Clock | None = None,
        metrics: MetricsRegistry | None = None,
        collector: FleetCollector | None = None,
        router: FleetRouter | None = None,
        prober: CanaryProber | None = None,
        retry_policy: RetryPolicy | None = None,
        breakers: BreakerBank | None = None,
        request_timeout_s: float = 30.0,
        drain_deadline_s: float = 30.0,
        drain_poll_s: float = 0.05,
        max_journal: int = 512,
        admission=None,
        admission_wait_s: float = 5.0,
        disagg_threshold: int = 0,
        ratio: RatioController | None = None,
    ):
        """``page_size`` must match the replicas' paged-KV page size —
        it is the router's chain-hash chunking, and the whole affinity
        win rides the gateway's chain equalling the block cache's.
        ``retry_policy`` / ``breakers`` are the ``cloud/resilience.py``
        primitives; the defaults are tuned for a serving hop (tens of
        milliseconds of backoff, a short breaker reset so canary
        recovery probes half-open quickly), not a cloud API.
        ``admission`` is an optional ``serve/admission.py``
        AdmissionController: when set, /generate consults it at the
        door (weighted-fair queueing, priority classes, per-tenant
        quotas) and a refused request sheds 429 — None (the default)
        keeps the PR 15 behavior, admission unconditional.
        ``admission_wait_s`` bounds how long a queued request waits
        for a grant when the client gave no deadline.

        ``disagg_threshold`` (ISSUE 20) > 0 enables disaggregated
        prefill/decode: a /generate prompt of at least that many
        tokens (floored to page_size+1 — shorter prompts have no
        page-aligned chain to hand over) prefills on a dedicated
        prefill worker (``register_replica(role="prefill")``), its KV
        chain ships over the migration wire to the routed decode
        owner's /admin/import, and only then does the normal dispatch
        run — the decode worker's paged admission acquires the warm
        chain and computes just the sub-page tail, never the full
        prefill.  0 (the default) disables classification entirely:
        every request takes the fused path and none of the disagg
        machinery runs.  ``ratio`` is an optional
        ``serve/ratio.py`` RatioController; ``ratio_tick()`` feeds it
        the observed traffic mix and applies its reassignment."""
        self.tokenizer = tokenizer
        self.clock = clock or RealClock()
        self.metrics = metrics if metrics is not None else global_metrics
        self.collector = collector or FleetCollector({}, clock=self.clock)
        # Mirror ContinuousBatcher's page-size floor: a replica given
        # page_size < 8 runs at 8, so the gateway must hash at 8 too or
        # every chain silently skews (test_frontend pins the equality).
        page_size = max(8, int(page_size))
        self.router = router or FleetRouter(
            page_size=page_size, collector=self.collector,
            metrics=self.metrics, clock=self.clock,
        )
        self.policy = retry_policy or RetryPolicy(
            max_attempts=3, budget=16,
            base_delay=0.02, max_delay=0.25, jitter=0.5,
        )
        self.breakers = breakers or BreakerBank(
            clock=self.clock, name="frontend",
            failure_threshold=3, reset_timeout=5.0,
            registry=self.metrics,
        )
        self.request_timeout_s = float(request_timeout_s)
        self.drain_deadline_s = float(drain_deadline_s)
        self.drain_poll_s = max(0.005, float(drain_poll_s))
        # The gateway's own request journal: one record per CLIENT
        # request with the final outcome and the routing evidence —
        # the zero-lost audit surface (/debug/requests).
        self.journal = RequestJournal(maxlen=max_journal)
        self._lock = threading.Lock()
        self._replicas: dict[str, str] = {}     # name -> base URL
        self._inflight: dict[str, int] = {}     # name -> gateway-local
        self._drains: dict[str, dict] = {}      # name -> drain state
        # Per-replica live-dispatch info: name -> {key -> request info}.
        # The forced-drain audit surface — when a deadline abandons a
        # replica's in-flight work, each entry becomes one gateway
        # journal record instead of silently vanishing.
        self._live: dict[str, dict[int, dict]] = {}
        self._live_seq = 0
        # The gateway fleet (ROADMAP item 3): peer gateways serving the
        # same replica pool, and this gateway's last reconstructed
        # chain→owner map + its agreement digest.  Peers never gossip
        # state — they only compare digests (/admin/ownermap), because
        # each rebuilds the same map from the same replica scrapes.
        self._peers: dict[str, str] = {}        # name -> base URL
        self._owner_map: dict[str, str] = {}    # hex chain -> owner
        self._owner_digest = ""
        self._owner_seq = 0
        self.admission = admission
        self.admission_wait_s = max(0.05, float(admission_wait_s))
        # Disaggregated prefill/decode (ISSUE 20): the classification
        # threshold, the per-worker role table (decode workers live in
        # the router; prefill workers only here), the ratio controller,
        # and the traffic-mix accumulator its decisions read.
        self._page = page_size
        self.disagg_threshold = max(0, int(disagg_threshold))
        self.ratio = ratio
        self._roles: dict[str, str] = {}        # name -> decode|prefill
        self._mix = {
            "prefill": 0.0, "decode": 0.0, "t0": self.clock.now(),
        }
        # The wire-level KV migration coordinator (serve/migrate.py):
        # drains hand a victim's warm chains to the router-chosen new
        # owner instead of letting them die with the process.
        self.migrator = BlockMigrator(
            clock=self.clock, metrics=self.metrics,
            timeout_s=request_timeout_s,
        )
        self._stop = threading.Event()
        self._drain_threads: list[threading.Thread] = []
        outer = self

        class Handler(RequestMetricsMixin, BaseHTTPRequestHandler):
            metrics_server_label = "fleet-frontend"
            known_routes = (
                "/generate", "/replica", "/admin/replicas",
                "/admin/drain", "/admin/ownermap", "/admin/peers",
                "/admin/admission", "/admin/ratio", "/healthz",
                "/readyz", "/metrics", "/debug/requests",
            )

            def _get(self):
                path = self.path.split("?")[0]
                if path == "/healthz":
                    with outer._lock:
                        n = len(outer._replicas)
                        d = sum(
                            1 for s in outer._drains.values()
                            if s["state"] == "draining"
                        )
                    return self._json(200, {
                        "ok": True, "replicas": n, "draining": d,
                    })
                if path == "/readyz":
                    snap = outer.router.snapshot()
                    eligible = [
                        r["replica"] for r in snap["replicas"]
                        if not (r["draining"] or r["down"]
                                or r["unhealthy"])
                    ]
                    return self._json(
                        200 if eligible else 503,
                        {
                            "ready": bool(eligible),
                            "replicas": len(snap["replicas"]),
                            "eligible": len(eligible),
                        },
                    )
                if path == "/metrics":
                    body = outer.metrics.render().encode()
                    self._last_code = 200
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/admin/replicas":
                    return self._json(
                        200, {"replicas": outer.replica_states()}
                    )
                if path == "/admin/drain":
                    return self._json(
                        200, {"drains": outer.drain_states()}
                    )
                if path == "/admin/ownermap":
                    # The agreement surface peers compare digests on;
                    # ?chains=0 skips the full map (the peer check
                    # only needs the digest).
                    return self._json(200, outer.owner_map_snapshot(
                        include_chains=(
                            self._query()("chains", "1") != "0"
                        ),
                    ))
                if path == "/admin/peers":
                    return self._json(
                        200, {"peers": outer.peer_states()}
                    )
                if path == "/admin/admission":
                    a = outer.admission
                    if a is None:
                        return self._json(200, {"enabled": False})
                    return self._json(
                        200, {"enabled": True, **a.snapshot()}
                    )
                if path == "/admin/ratio":
                    return self._json(200, outer.ratio_state())
                if path == "/debug/requests":
                    one = self._query()
                    try:
                        limit = int(one("limit", "100"))
                    except ValueError:
                        return self._json(
                            400, {"error": "limit must be an int"}
                        )
                    return self._json(200, {
                        "requests": outer.journal.snapshot(
                            limit=limit,
                            tenant=one("tenant"),
                            reason=one("reason"),
                            trace_id=one("trace_id"),
                            probes=one("probes", "1") != "0",
                        ),
                    })
                return self._json(404, {"error": "not found"})

            def _post(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    return self._json(400, {"error": "invalid JSON body"})
                if not isinstance(body, dict):
                    return self._json(
                        400, {"error": "body must be an object"}
                    )
                path = self.path.split("?")[0]
                if path == "/generate":
                    return self._generate(body, pinned=None)
                if path.startswith("/replica/"):
                    # Pinned dispatch: POST /replica/<name>/generate
                    # bypasses routing and contacts exactly that
                    # replica — the canary's per-replica probe path,
                    # and the recovery path for a marked-down one.
                    parts = path.split("/")
                    if len(parts) == 4 and parts[3] == "generate":
                        return self._generate(body, pinned=parts[2])
                    return self._json(404, {"error": "not found"})
                if path == "/admin/replicas":
                    return self._register(body)
                if path == "/admin/drain":
                    return self._drain(body)
                if path == "/admin/ownermap":
                    # Rebuild the owner map from replica scrapes NOW —
                    # the admin trigger for a freshly started gateway
                    # joining an already-warm fleet.
                    try:
                        got = outer.reconstruct(
                            check_peers=bool(
                                body.get("check_peers", True)
                            ),
                        )
                    except RuntimeError as e:
                        return self._json(
                            503, {"error": str(e)},
                            headers={
                                "Retry-After": str(RETRY_AFTER_S)
                            },
                        )
                    return self._json(200, got)
                if path == "/admin/ratio":
                    # Admin trigger for one controller evaluation —
                    # the same tick a periodic operator loop would
                    # run; returns what it decided and applied.
                    if outer.ratio is None:
                        return self._json(200, {"enabled": False})
                    return self._json(200, outer.ratio_tick())
                if path == "/admin/peers":
                    name = body.get("name", "")
                    url = body.get("url", "")
                    if not isinstance(name, str) or not name.strip():
                        return self._json(
                            400, {"error": "name (string) required"}
                        )
                    if not isinstance(url, str) or not url.strip():
                        return self._json(
                            400, {"error": "url (string) required"}
                        )
                    outer.add_peer(name.strip(), url.strip())
                    return self._json(200, {
                        "peer": name.strip(),
                        "peers": len(outer.peer_states()),
                    })
                return self._json(404, {"error": "not found"})

            def _delete(self):
                path = self.path.split("?")[0]
                if path == "/admin/peers":
                    name = self._query()("name")
                    if not name:
                        return self._json(
                            400, {"error": "name (query) required"}
                        )
                    if outer.remove_peer(name):
                        return self._json(200, {"removed": name})
                    return self._json(
                        404, {"error": f"unknown peer {name!r}"}
                    )
                if path != "/admin/replicas":
                    return self._json(404, {"error": "not found"})
                name = self._query()("name")
                if not name:
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n) or b"{}")
                        name = body.get("name", "")
                    except (ValueError, json.JSONDecodeError,
                            AttributeError):
                        name = ""
                if not name:
                    return self._json(
                        400, {"error": "name (query or body) required"}
                    )
                if outer.retire_replica(name):
                    return self._json(200, {"retired": name})
                return self._json(
                    404, {"error": f"unknown replica {name!r}"}
                )

            def do_DELETE(self):  # noqa: N802 (stdlib API name)
                self._timed("DELETE", self._delete)

            # -- admin bodies ---------------------------------------------
            def _register(self, body):
                name = body.get("name", "")
                url = body.get("url", "")
                if not isinstance(name, str) or not name.strip():
                    return self._json(
                        400, {"error": "name (string) required"}
                    )
                if not isinstance(url, str) or not url.strip():
                    return self._json(
                        400, {"error": "url (string) required"}
                    )
                role = body.get("role", "decode")
                if role not in ("decode", "prefill"):
                    return self._json(
                        400,
                        {"error": "role must be decode or prefill"},
                    )
                try:
                    r = outer.register_replica(
                        name.strip(), url.strip(),
                        metrics_target=body.get("metrics_url") or None,
                        role=role,
                    )
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                except RuntimeError as e:
                    # The /readyz gate failed: the caller retries once
                    # the replica is actually servable.
                    return self._json(
                        503, {"error": str(e)},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                return self._json(200, {
                    "registered": name.strip(),
                    "replicas": len(outer.replica_names()),
                    "readiness": r,
                })

            def _drain(self, body):
                name = body.get("name", "")
                if not isinstance(name, str) or not name.strip():
                    return self._json(
                        400, {"error": "name (string) required"}
                    )
                deadline_s = body.get("deadline_s")
                try:
                    st = outer.drain(
                        name.strip(),
                        deadline_s=(
                            float(deadline_s)
                            if deadline_s is not None else None
                        ),
                    )
                except KeyError:
                    return self._json(
                        404, {"error": f"unknown replica {name!r}"}
                    )
                except (TypeError, ValueError):
                    return self._json(
                        400, {"error": "deadline_s must be a number"}
                    )
                return self._json(202, {"draining": name.strip(), **st})

            # -- /generate ------------------------------------------------
            def _generate(self, body, pinned):
                # ``prompt_ids`` (pre-tokenized) is the CLIENT retry
                # contract for a dead gateway: a client whose stream
                # was cut re-issues ``original ids + tokens already
                # received`` to a SURVIVING gateway, which routes it by
                # the same chain hashes to the same replica — the
                # teacher-forced resume (serve/migrate.py) with the
                # client, not the relay, holding the prefix.
                prompt = body.get("prompt", "")
                prompt_ids = body.get("prompt_ids")
                if prompt_ids is not None:
                    if (not isinstance(prompt_ids, list)
                            or not prompt_ids
                            or not all(
                                isinstance(i, int)
                                and not isinstance(i, bool)
                                for i in prompt_ids
                            )):
                        return self._json(400, {
                            "error": "prompt_ids must be a non-empty "
                                     "list of ints"})
                elif not isinstance(prompt, str) or not prompt:
                    return self._json(
                        400, {"error": "prompt (string) required"}
                    )
                tenant = body.get("tenant")
                if tenant is None:
                    tenant = self.headers.get("x-tenant") or ""
                if not isinstance(tenant, str):
                    return self._json(
                        400, {"error": "tenant must be a string"}
                    )
                tenant = tenant.strip()[:64] or "default"
                # The deadline budget is validated HERE (same contract
                # as LmServer) and re-propagated downstream as the
                # REMAINING budget, so time spent routing and retrying
                # counts against the client's budget, not on top of it.
                deadline = None
                budget_ms = self.headers.get("x-request-deadline-ms")
                if budget_ms is not None:
                    try:
                        budget_ms = float(budget_ms)
                    except (TypeError, ValueError):
                        budget_ms = None
                    if budget_ms is None or not math.isfinite(budget_ms):
                        return self._json(400, {
                            "error": "x-request-deadline-ms must be a "
                                     "finite number"
                        })
                    if budget_ms <= 0:
                        outer.metrics.inc(
                            "frontend_shed_total", reason="deadline"
                        )
                        outer._journal(
                            tenant=tenant, trace_ctx=self.trace_ctx,
                            reason="deadline", code=504,
                            t0=outer.clock.now(),
                        )
                        return self._json(
                            504, {"error": "deadline exceeded"}
                        )
                    deadline = outer.clock.now() + budget_ms / 1000.0
                if prompt_ids is not None:
                    ids = [int(i) for i in prompt_ids]
                else:
                    ids = [
                        int(i)
                        for i in outer.tokenizer.encode(prompt).tolist()
                    ]
                try:
                    want_new = int(body.get("max_new_tokens", 32))
                except (TypeError, ValueError):
                    want_new = 32
                # A surviving gateway accepting a client retry stamps
                # the downstream submit with the replica/gateway the
                # request fled (x-resume-from → x-migrated-from), so
                # the destination journal carries the provenance.
                resume_from = (
                    self.headers.get("x-resume-from") or ""
                ).strip()[:64]
                # -- admission (serve/admission.py) -------------------
                # Pinned probes and reserved "_" tenants bypass: probe
                # traffic must measure the replica, not the queue, and
                # synthetic tenants carry no admission contract.
                ticket = None
                if (outer.admission is not None and pinned is None
                        and not tenant.startswith("_")):
                    ticket = outer.admission.offer(
                        tenant, len(ids) + max(1, want_new)
                    )
                    admitted = False
                    if ticket.state not in ("throttled", "shed"):
                        admitted = outer.admission.await_grant(
                            ticket,
                            deadline=(
                                deadline if deadline is not None
                                else outer.clock.now()
                                + outer.admission_wait_s
                            ),
                        )
                    if not admitted:
                        why = ticket.shed_reason or "admission"
                        outer.metrics.inc(
                            "frontend_shed_total", reason="admission"
                        )
                        outer._journal(
                            tenant=tenant, trace_ctx=self.trace_ctx,
                            reason="admission", code=429,
                            t0=ticket.t_offer,
                            extra={"admission": why},
                        )
                        return self._json(
                            429,
                            {"error": f"admission refused ({why})"},
                            headers={
                                "Retry-After": str(RETRY_AFTER_S)
                            },
                        )
                # -- disaggregated prefill/decode (ISSUE 20) ----------
                # Classification and handover happen AFTER admission
                # (a shed request must not burn prefill-pool work) and
                # before dispatch, so a successful handover's warm
                # chain is registered on the decode owner the instant
                # the normal dispatch routes there.  Every failure
                # between here and dispatch degrades to the fused path
                # — the request itself is never at risk.
                handover = None
                if outer.disagg_threshold > 0 and pinned is None:
                    long_prompt = outer._classify(ids)
                    outer._mix_account(
                        len(ids), max(1, want_new), long_prompt
                    )
                    if long_prompt:
                        handover = outer._disagg_handover(
                            ids, tenant=tenant, deadline=deadline,
                            trace_ctx=self.trace_ctx,
                            seed=body.get("seed", 0),
                            temperature=body.get("temperature", 0.0),
                            top_p=body.get("top_p", 0.0),
                        )
                        if handover is None:
                            outer.metrics.inc(
                                "disagg_requests_total",
                                path="fused_fallback",
                            )
                try:
                    out = outer.dispatch(
                        ids, body, tenant=tenant, deadline=deadline,
                        trace_ctx=self.trace_ctx,
                        stream=bool(body.get("stream", False)),
                        pinned=pinned, migrated_from=resume_from,
                        handover=handover,
                    )
                    if out["kind"] == "stream":
                        # Everything the relay needs to RESUME this
                        # stream on another replica if its owner dies
                        # or migrates mid-flight (serve/migrate.py):
                        # the original ids, the client body, and the
                        # remaining-budget inputs.  A PINNED stream
                        # never resumes elsewhere — the canary
                        # contract is that a dead replica fails its
                        # probe instead of silently succeeding on
                        # another.
                        if pinned is None:
                            out["resume_ctx"] = {
                                "ids": list(ids),
                                "body": body,
                                "tenant": tenant,
                                "deadline": deadline,
                                "trace_ctx": self.trace_ctx,
                                "max_new": max(1, want_new),
                            }
                        return self._relay(out)
                    hdrs = dict(out.get("headers") or {})
                    if out.get("replica"):
                        hdrs["x-route-replica"] = out["replica"]
                        hdrs["x-route-reason"] = out["reason"]
                    return self._json(
                        out["code"], out["payload"], hdrs
                    )
                finally:
                    if ticket is not None:
                        outer.admission.release(ticket)

            def _relay(self, out):
                """Relay a downstream ndjson stream event-by-event,
                with MID-STREAM FAILOVER: the relay parses each event,
                tracks the token ids already delivered to the client,
                and when the stream is cut — the replica died, or its
                drain migrated its KV state away (the ``"migrated"``
                truncation summary, serve/migrate.py) — it re-dispatches
                the request as ``prompt_ids = original + emitted`` with
                the REMAINING token budget, excluding the victim.  The
                client's ndjson stream continues seamlessly: same
                connection, same ``x-trace-id``, no duplicated and no
                lost tokens (greedy decode resumed from a teacher-forced
                prefix continues exactly).  Resume attempts are capped
                (``migrate.resume`` fault site); when they exhaust, the
                client gets an honest truncation summary — degraded,
                never wrong.  A deadline truncation is NOT resumed: the
                budget died, new work would be waste."""
                rctx = out.get("resume_ctx")
                resp0 = out["resp"]
                self._last_code = 200
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    resp0.headers.get(
                        "Content-Type", "application/x-ndjson"
                    ),
                )
                self.send_header("X-Accel-Buffering", "no")
                self.send_header("x-route-replica", out["replica"])
                self.send_header("x-route-reason", out["reason"])
                ctx = getattr(self, "trace_ctx", None)
                if ctx is not None:
                    self.send_header("x-trace-id", ctx.trace_id)
                self.end_headers()
                emitted: list[int] = []
                segments = 0
                cur = out
                while True:
                    segments += 1
                    resp = cur["resp"]
                    seg_tokens = 0
                    truncated = False
                    client_gone = False
                    finished = False
                    try:
                        while True:
                            try:
                                line = resp.readline()
                            except (OSError, ValueError,
                                    http.client.HTTPException):
                                # ValueError: a migrating drain closed
                                # this upstream under us
                                # (_cut_live_streams) — read-on-closed.
                                truncated = True
                                break
                            if not line:
                                truncated = True
                                break
                            ev = None
                            try:
                                ev = json.loads(line)
                            except ValueError:
                                pass
                            forward = line
                            if isinstance(ev, dict):
                                if "id" in ev and "done" not in ev:
                                    seg_tokens += 1
                                    emitted.append(int(ev["id"]))
                                elif ev.get("done") is True:
                                    finished = True
                                    if segments > 1:
                                        # The summary must describe the
                                        # WHOLE stream the client saw,
                                        # not the last segment.
                                        ev["generated_tokens"] = (
                                            len(emitted)
                                        )
                                        ev["text"] = (
                                            outer.tokenizer.decode(
                                                emitted
                                            )
                                        )
                                        ev["resumed"] = segments - 1
                                        forward = (
                                            json.dumps(ev) + "\n"
                                        ).encode()
                                elif ev.get("done") is False:
                                    if (ev.get("error")
                                            == "deadline exceeded"):
                                        finished = True
                                    else:
                                        # "migrated" / aborted: a
                                        # resumable truncation — do NOT
                                        # forward it to the client.
                                        truncated = True
                                        break
                            try:
                                self.wfile.write(forward)
                                self.wfile.flush()
                            except OSError:
                                client_gone = True
                                break
                            if finished:
                                break
                    finally:
                        try:
                            resp.close()
                        except OSError:
                            pass
                        cur["finish"](seg_tokens)
                    if finished or client_gone:
                        return
                    if not truncated:
                        return
                    # -- failover: resume on another replica ----------
                    if rctx is None:
                        self._stream_fail(len(emitted))
                        return
                    remaining = rctx["max_new"] - len(emitted)
                    if remaining <= 0:
                        # The budget is already fully delivered — the
                        # only thing lost was the summary event.
                        self._stream_done(rctx, emitted, segments)
                        return
                    nxt = None
                    for _ in range(2):
                        try:
                            # error/timeout only: no clock here to
                            # realize a "slow" decision as a delay.
                            global_faults.fire(
                                "migrate.resume",
                                error_type=RuntimeError,
                                only=("error", "timeout"),
                            )
                            got = outer.resume_stream(
                                rctx, emitted, victim=cur["replica"],
                            )
                        except RuntimeError:
                            outer.metrics.inc(
                                "migrate_failures_total",
                                stage="resume",
                            )
                            continue
                        if got["kind"] == "stream":
                            nxt = got
                            break
                        outer.metrics.inc(
                            "migrate_failures_total", stage="resume",
                        )
                    if nxt is None:
                        self._stream_fail(len(emitted))
                        return
                    cur = nxt

            def _stream_done(self, rctx, emitted, segments):
                """Synthesize the terminal summary for a resumed stream
                whose token budget was already fully delivered when its
                last owner died."""
                summary = {
                    "done": True,
                    "text": outer.tokenizer.decode(emitted),
                    "prompt_tokens": len(rctx["ids"]),
                    "generated_tokens": len(emitted),
                    "tokens_per_s": 0.0,
                    "resumed": max(0, segments - 1),
                }
                ctx = getattr(self, "trace_ctx", None)
                if ctx is not None:
                    summary["trace_id"] = ctx.trace_id
                try:
                    self.wfile.write(
                        (json.dumps(summary) + "\n").encode()
                    )
                    self.wfile.flush()
                except OSError:
                    pass

            def _stream_fail(self, n_emitted):
                """Honest truncation summary when every resume attempt
                failed: the tokens already streamed are a prefix, not a
                completion — never silently pretend otherwise."""
                summary = {
                    "done": False,
                    "error": "stream interrupted; resume failed",
                    "generated_tokens": int(n_emitted),
                }
                try:
                    self.wfile.write(
                        (json.dumps(summary) + "\n").encode()
                    )
                    self.wfile.flush()
                except OSError:
                    pass

            def _query(self):
                from urllib.parse import parse_qs, urlparse

                q = parse_qs(urlparse(self.path).query)

                def one(key, default=""):
                    v = q.get(key, [default])
                    return v[0] if v else default

                return one

            def _json(self, code: int, payload: dict,
                      headers: dict | None = None) -> None:
                self._last_code = code
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                # EVERY client-visible outcome — success, shed, 503,
                # 504, validation error — carries the trace id, so any
                # client-observed failure is findable in the waterfall
                # (/debug/waterfall, utils/waterfall.py).
                hdrs = dict(headers or {})
                ctx = getattr(self, "trace_ctx", None)
                if ctx is not None and "x-trace-id" not in hdrs:
                    hdrs["x-trace-id"] = ctx.trace_id
                for k, v in hdrs.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-frontend",
            daemon=True,
        )
        # The prober is built LAST so it can target the bound port:
        # probes go through the gateway's pinned-dispatch path, making
        # the black-box health verdict cover the real client path.
        self.prober = prober if prober is not None else CanaryProber(
            clock=self.clock, metrics=self.metrics, router=self.router,
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FleetFrontend":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self.prober.stop()
        except Exception:
            pass
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)
        for t in list(self._drain_threads):
            t.join(timeout=2)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- replica lifecycle -------------------------------------------------
    def register_replica(
        self,
        name: str,
        url: str,
        *,
        metrics_target=None,
        on_drain=None,
        warm: bool = True,
        role: str = "decode",
    ) -> dict:
        """Admit a replica behind the gateway, gated on its ``/readyz``:
        unreachable or draining raises RuntimeError; alive-but-unwarmed
        (``scheduler_alive`` and not ``draining`` but the first compile
        hasn't happened) is warmed with one real 1-token ``/generate``
        when ``warm`` — which is also an end-to-end smoke test that the
        URL serves — then re-gated.  ``metrics_target`` (a URL serving
        ``/metrics`` or a zero-arg callable returning an exposition) is
        federated for load-aware routing; without one the replica routes
        on affinity alone.  ``on_drain`` is forwarded to the router so a
        drain announcement can flip an in-process replica's own
        ``/readyz`` (``LmServer.drain``).  Returns the readiness body.

        ``role`` (ISSUE 20): ``"decode"`` (default) joins the routing
        pool exactly as before; ``"prefill"`` keeps the worker OUT of
        the router and the canary prober — it never receives routed
        /generate traffic, only the gateway's /prefill handovers — and
        a worker whose own ``/readyz`` reports a prefill-only batcher
        is refused as a decode replica (its 1-token-clamped streams
        would be silently wrong)."""
        name = str(name).strip()[:64]
        if not name:
            raise ValueError("replica name required")
        if role not in ("decode", "prefill"):
            raise ValueError(f"unknown replica role {role!r}")
        url = str(url).rstrip("/")
        # A prefill worker never serves a multi-token /generate, so
        # the 1-token warm probe is the ONLY warm it can take — which
        # is exactly what `warm` already sends.
        r = self._readyz(url)
        if r is None:
            raise RuntimeError(
                f"replica {name!r} at {url} is unreachable"
            )
        if not r.get("ready", False):
            if warm and r.get("scheduler_alive") and not r.get("draining"):
                self._warm(url)
                r = self._readyz(url)
            if r is None or not r.get("ready", False):
                raise RuntimeError(
                    f"replica {name!r} at {url} is not ready: "
                    f"{json.dumps(r, sort_keys=True)}"
                )
        claimed = r.get("replica", "")
        if claimed and claimed != name:
            raise RuntimeError(
                f"replica at {url} calls itself {claimed!r}; "
                f"refusing to register it as {name!r}"
            )
        if role == "decode" and r.get("role") == "prefill":
            raise RuntimeError(
                f"replica {name!r} reports a prefill-only batcher; "
                f"refusing to route decode traffic to it"
            )
        with self._lock:
            self._replicas[name] = url
            self._inflight.setdefault(name, 0)
            self._drains.pop(name, None)
            self._roles[name] = role
            count = len(self._replicas)
            prefill_n = sum(
                1 for v in self._roles.values() if v == "prefill"
            )
        if role == "prefill":
            # Out of the router, out of the prober: routed /generate
            # and canary probes are decode-pool concerns.
            self.metrics.set_gauge("frontend_replicas", float(count))
            self.metrics.set_gauge(
                "disagg_prefill_workers", float(prefill_n)
            )
            return r
        self.router.add_replica(name, submit=None, on_drain=on_drain)
        # A re-registered replica starts with a clean slate: the breaker
        # memory of its previous life would otherwise short-circuit the
        # first contacts of the new one.
        self.breakers.get(name).record_success()
        if metrics_target is not None:
            self.collector.add_target(name, metrics_target)
        self.prober.add_target(name, f"{self.url}/replica/{name}")
        self.metrics.set_gauge("frontend_replicas", float(count))
        self.metrics.set_gauge(
            "disagg_prefill_workers", float(prefill_n)
        )
        self.metrics.set_gauge(
            "frontend_inflight_requests", 0.0, replica=name
        )
        return r

    def retire_replica(self, name: str) -> bool:
        """Remove a replica from every plane (router, federation,
        prober, dispatch) immediately — the synchronous half a finished
        or forced drain calls, and the ``DELETE /admin/replicas``
        behavior for an already-dead endpoint."""
        with self._lock:
            url = self._replicas.pop(name, None)
            self._inflight.pop(name, None)
            self._live.pop(name, None)
            role = self._roles.pop(name, "decode")
            count = len(self._replicas)
            prefill_n = sum(
                1 for v in self._roles.values() if v == "prefill"
            )
        if url is None:
            return False
        if role != "prefill":
            self.router.remove_replica(name)
            self.collector.remove_target(name)
            self.prober.remove_target(name)
            self.metrics.remove_gauge(
                "frontend_inflight_requests", replica=name
            )
        self.metrics.set_gauge("frontend_replicas", float(count))
        self.metrics.set_gauge(
            "disagg_prefill_workers", float(prefill_n)
        )
        return True

    def replica_names(self) -> list[str]:
        with self._lock:
            return sorted(self._replicas)

    def replica_states(self) -> list[dict]:
        """The ``GET /admin/replicas`` body: router flags joined with
        the gateway's own URL / in-flight / drain bookkeeping."""
        snap = {
            r["replica"]: r for r in self.router.snapshot()["replicas"]
        }
        with self._lock:
            names = sorted(self._replicas)
            out = []
            for name in names:
                st = dict(snap.get(name) or {"replica": name})
                st["url"] = self._replicas[name]
                st["role"] = self._roles.get(name, "decode")
                st["inflight_gateway"] = self._inflight.get(name, 0)
                d = self._drains.get(name)
                if d is not None:
                    st["drain"] = d["state"]
                out.append(st)
        return out

    # -- disaggregated prefill/decode (ISSUE 20) -----------------------------
    def prefill_pool(self) -> list[str]:
        """Registered prefill workers with a live URL, sorted — the
        rendezvous candidate set."""
        with self._lock:
            return sorted(
                n for n, r in self._roles.items()
                if r == "prefill" and self._replicas.get(n)
            )

    def _classify(self, ids) -> bool:
        """Prompt-length classification: True routes the request
        through the disagg handover, False keeps the fused path.  The
        effective threshold is floored to ``page_size + 1`` — a prompt
        inside one page has no page-aligned chain to hand over.  The
        seeded ``disagg.classify`` fault site models a broken
        classifier: a fault counts
        (``disagg_handover_failures_total{stage="classify"}``) and
        degrades to the fused path — never a lost request."""
        try:
            global_faults.fire(
                "disagg.classify", error_type=RuntimeError,
                only=("error", "timeout"),
            )
        except (RuntimeError, TimeoutError):
            self.metrics.inc(
                "disagg_handover_failures_total", stage="classify"
            )
            return False
        # Deliberately NO prefill-pool check here: classification is
        # the DEMAND signal the ratio controller grows the pool from
        # (a long prompt with zero prefill workers still counts as
        # prefill flow); the handover itself degrades to the fused
        # path when no worker exists to take it.
        return len(ids) >= max(self.disagg_threshold, self._page + 1)

    def _mix_account(
        self, prompt_tokens: int, want_new: int, long_prompt: bool,
    ) -> None:
        """Traffic-mix accounting, the ratio controller's signal:
        prompt tokens of disagg-classified (long) requests are prefill
        flow, requested decode budgets are decode flow.  Mirrored into
        federated counters so any scraper can recompute the
        controller's input from ``/metrics``."""
        with self._lock:
            if long_prompt:
                self._mix["prefill"] += float(prompt_tokens)
            self._mix["decode"] += float(want_new)
        if long_prompt:
            self.metrics.inc(
                "disagg_prefill_tokens_total", float(prompt_tokens)
            )
        self.metrics.inc(
            "disagg_decode_tokens_total", float(want_new)
        )

    def _post_json(self, url: str, body: dict, timeout: float) -> dict:
        """POST a JSON body, return the decoded JSON response; any
        transport failure or non-2xx maps to RuntimeError so handover
        callers have ONE failure type to degrade on."""
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            url,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            try:
                detail = e.read().decode()[:200]
            except (OSError, ValueError):
                detail = ""
            finally:
                e.close()
            raise RuntimeError(
                f"POST {url} -> {e.code} {detail}"
            ) from None
        except (OSError, http.client.HTTPException, ValueError) as e:
            raise RuntimeError(
                f"POST {url} failed: {type(e).__name__}: {e}"
            ) from None

    def _disagg_handover(
        self, ids, *, tenant, deadline, trace_ctx,
        seed=0, temperature=0.0, top_p=0.0,
    ):
        """Prefill→export→wire→import for ONE long prompt; returns the
        handover summary ({"prefill", "replica", "seconds", "blocks"})
        or None to degrade to the fused path (the degradation matrix
        in docs/platform/serving.md — a handover failure costs
        re-prefill on the decode worker, never correctness).

        The prefill worker is chosen by rendezvous hash on the chain
        ROOT — the same HRW family the router uses — so repeated long
        prompts sharing a prefix prefill where their pages are already
        registered.  The decode destination is routed NOW (before
        dispatch): ``router.route`` records the chain→owner assignment,
        so the real dispatch moments later routes to the same owner by
        affinity and the import lands exactly where decode runs."""
        chain = shareable_chain(ids, self._page)
        if not chain:
            return None
        pool = self.prefill_pool()
        if not pool:
            return None
        with self._lock:
            purls = {n: self._replicas.get(n) for n in pool}
        pname = FleetRouter._rendezvous(chain[0], pool)
        try:
            dec = self.router.route(ids)
        except RuntimeError:
            return None          # no decode capacity: fused path sheds
        dest_url = self._url_of(dec.replica)
        if dest_url is None:
            return None
        timeout = self.request_timeout_s
        if deadline is not None:
            timeout = max(
                0.001, min(timeout, deadline - self.clock.now())
            )
        t0 = self.clock.now()
        s_at = global_tracer.clock.now()
        stage = "prefill"
        try:
            global_faults.fire(
                "disagg.handover", error_type=RuntimeError,
                only=("error", "timeout"),
            )
            payload = self._post_json(
                purls[pname] + "/prefill",
                {
                    "prompt_ids": [int(i) for i in ids],
                    "seed": int(seed or 0),
                    "temperature": float(temperature or 0.0),
                    "top_p": float(top_p or 0.0),
                    "tenant": tenant,
                },
                timeout,
            )
            stage = "import"
            global_faults.fire(
                "disagg.handover", error_type=RuntimeError,
                only=("error", "timeout"),
            )
            imported = self._post_json(
                dest_url + "/admin/import", payload, timeout
            )
        except (RuntimeError, TimeoutError) as e:
            self.metrics.inc(
                "disagg_handover_failures_total", stage=stage
            )
            log.warning(
                "disagg handover failed at %s (prefill=%s dest=%s): "
                "%s — degrading to fused path", stage, pname,
                dec.replica, e,
            )
            return None
        dt = self.clock.now() - t0
        blocks = int(imported.get("imported", 0) or 0)
        self.metrics.observe("disagg_handover_seconds", dt)
        self.metrics.inc("disagg_requests_total", path="disagg")
        if trace_ctx is not None:
            # Span boundaries on the tracer's own clock (the
            # _attempt_span discipline) so the waterfall's
            # ``kv_handover`` segment shares the root span's timeline.
            global_tracer.add_span(
                "gateway.handover",
                parent=trace_ctx,
                start=s_at,
                end=global_tracer.clock.now(),
                prefill=pname,
                replica=dec.replica,
                blocks=blocks,
            )
        return {
            "prefill": pname, "replica": dec.replica,
            "seconds": dt, "blocks": blocks,
        }

    def ratio_state(self) -> dict:
        """The ``GET /admin/ratio`` body: pools, threshold, and the
        current traffic-mix window."""
        with self._lock:
            prefill = sorted(
                n for n, r in self._roles.items() if r == "prefill"
            )
            decode = sorted(
                n for n, r in self._roles.items() if r != "prefill"
            )
            mix = dict(self._mix)
        return {
            "enabled": self.ratio is not None,
            "threshold": self.disagg_threshold,
            "prefill_pool": prefill,
            "decode_pool": decode,
            "mix_window": {
                "prefill_tokens": mix["prefill"],
                "decode_tokens": mix["decode"],
                "window_s": max(0.0, self.clock.now() - mix["t0"]),
            },
        }

    def ratio_tick(self) -> dict:
        """One controller evaluation: read-and-reset the traffic-mix
        window, feed the rates to ``RatioController.decide``, and apply
        a nonzero decision via ``reassign_replica``.  Deterministic
        given the window contents and the clock — the operator loop
        (or the ``POST /admin/ratio`` admin trigger, or a test) calls
        this periodically; the controller's own cooldown makes the call
        rate safe to choose freely."""
        if self.ratio is None:
            return {"enabled": False}
        now = self.clock.now()
        with self._lock:
            window = max(1e-9, now - self._mix["t0"])
            prefill_tps = self._mix["prefill"] / window
            decode_tps = self._mix["decode"] / window
            self._mix = {"prefill": 0.0, "decode": 0.0, "t0": now}
            prefill = sorted(
                n for n, r in self._roles.items() if r == "prefill"
            )
            decode = sorted(
                n for n, r in self._roles.items() if r != "prefill"
            )
        d = self.ratio.decide(
            prefill_workers=len(prefill),
            decode_workers=len(decode),
            prefill_tps=prefill_tps,
            decode_tps=decode_tps,
            now=now,
        )
        out = {
            "enabled": True,
            "target_prefill": d.target_prefill,
            "reason": d.reason,
            "direction": d.direction,
            "prefill_tps": prefill_tps,
            "decode_tps": decode_tps,
            "reassigned": "",
        }
        if d.direction > 0 and decode:
            # Grow prefill: the router's scale-down victim (fewest
            # resident chains → cheapest KV loss) flips role.
            victim = self.router.scale_down_victim()
            if victim is not None and self.reassign_replica(
                victim, "prefill"
            ):
                out["reassigned"] = victim
        elif d.direction < 0 and prefill:
            victim = prefill[0]
            if self.reassign_replica(victim, "decode"):
                out["reassigned"] = victim
        return out

    def reassign_replica(self, name: str, role: str) -> bool:
        """Flip one worker between the decode and prefill pools — the
        ratio controller's actuator.  →prefill removes the worker from
        the router and the prober FIRST (no new routed traffic), then
        best-effort flips the worker's own batcher role (a refusal —
        409 while requests are in flight — leaves it a
        gateway-side-only prefill worker until the next tick retries).
        →decode flips the worker's batcher role first and only joins
        it to the router once the worker CONFIRMS — a worker still
        clamping budgets to 1 token must never receive routed decode
        traffic."""
        if role not in ("decode", "prefill"):
            raise ValueError(f"unknown replica role {role!r}")
        with self._lock:
            url = self._replicas.get(name)
            current = self._roles.get(name)
        if url is None or current is None or current == role:
            return False
        if role == "prefill":
            self.router.remove_replica(name)
            self.prober.remove_target(name)
            with self._lock:
                self._roles[name] = "prefill"
                prefill_n = sum(
                    1 for v in self._roles.values() if v == "prefill"
                )
            self.metrics.set_gauge(
                "disagg_prefill_workers", float(prefill_n)
            )
            try:
                self._post_json(
                    url + "/admin/role", {"role": "prefill"},
                    self.request_timeout_s,
                )
            except RuntimeError as e:
                log.warning(
                    "role flip to prefill deferred on %s: %s", name, e
                )
            return True
        try:
            self._post_json(
                url + "/admin/role", {"role": "decode"},
                self.request_timeout_s,
            )
        except RuntimeError as e:
            log.warning(
                "role flip to decode refused on %s: %s", name, e
            )
            return False
        with self._lock:
            self._roles[name] = "decode"
            prefill_n = sum(
                1 for v in self._roles.values() if v == "prefill"
            )
        self.router.add_replica(name, submit=None)
        self.breakers.get(name).record_success()
        self.prober.add_target(name, f"{self.url}/replica/{name}")
        self.metrics.set_gauge(
            "disagg_prefill_workers", float(prefill_n)
        )
        return True

    # -- gateway fleet (ROADMAP item 3) --------------------------------------
    def add_peer(self, name: str, url: str) -> None:
        """Register a peer gateway serving the same replica pool.
        Peers are compared, never consulted: each gateway rebuilds its
        own owner map from replica scrapes, and the peer list only
        feeds the convergence check (digest agreement) and the
        client's failover target set."""
        name = str(name).strip()[:64]
        if not name:
            raise ValueError("peer name required")
        with self._lock:
            self._peers[name] = str(url).rstrip("/")

    def remove_peer(self, name: str) -> bool:
        with self._lock:
            return self._peers.pop(name, None) is not None

    def peer_states(self) -> list[dict]:
        with self._lock:
            return [
                {"peer": name, "url": self._peers[name]}
                for name in sorted(self._peers)
            ]

    def scrape_chains(self) -> dict[str, list[str]]:
        """One reconstruction pass's raw input: per registered replica
        (sorted), its ``/debug/chains`` body.  The ``gateway.scrape``
        fault site sits in front of every fetch so chaos runs can drop
        scrapes deterministically; an unreachable or faulted replica
        is SKIPPED (``gateway_scrape_failures_total{replica=}``) — a
        partial scrape yields a smaller map, never a wrong one, and
        the next pass re-converges."""
        with self._lock:
            targets = sorted(self._replicas.items())
        out: dict[str, list[str]] = {}
        for name, url in targets:
            got = None
            try:
                global_faults.fire(
                    "gateway.scrape", error_type=RuntimeError,
                    only=("error", "timeout"),
                )
                got = self._get_json(url + "/debug/chains")
            except RuntimeError:
                got = None
            if got is None or not isinstance(got.get("chains"), list):
                self.metrics.inc(
                    "gateway_scrape_failures_total", replica=name
                )
                continue
            out[name] = [h for h in got["chains"] if isinstance(h, str)]
        return out

    def reconstruct(self, check_peers: bool = True) -> dict:
        """Rebuild the chain→owner map purely from replica scrapes
        (``merge_owner_map``) and install it on the router — the
        tentpole contract: a gateway started five minutes late, or
        rebooted with empty state, converges to the SAME owner map as
        every peer, because the map is a pure function of (replica
        set, replica pool contents, rendezvous hash) and none of those
        live in any gateway.  Updates ``gateway_owner_map_hash`` (the
        digest's leading 48 bits — exactly representable in the float
        gauge) and, with ``check_peers``, ``gateway_converged``.
        Raises RuntimeError when no replica could be scraped."""
        scrapes = self.scrape_chains()
        with self._lock:
            have_replicas = bool(self._replicas)
        if have_replicas and not scrapes:
            raise RuntimeError(
                "reconstruction scraped no replica (all unreachable "
                "or faulted)"
            )
        mapping = merge_owner_map(scrapes)
        installed = self.router.install_chains({
            bytes.fromhex(h): owner for h, owner in mapping.items()
        })
        digest = owner_map_digest(mapping)
        with self._lock:
            self._owner_map = mapping
            self._owner_digest = digest
            self._owner_seq += 1
            seq = self._owner_seq
        self.metrics.inc("gateway_reconstructions_total")
        self.metrics.set_gauge(
            "gateway_owner_map_hash", float(int(digest[:12], 16))
        )
        out = {
            "digest": digest,
            "seq": seq,
            "chains": len(mapping),
            "installed": installed,
            "scraped": sorted(scrapes),
        }
        if check_peers:
            out["peers"] = self.check_convergence()
        return out

    def check_convergence(self) -> list[dict]:
        """Compare this gateway's owner-map digest against every
        peer's (``GET /admin/ownermap?chains=0`` — digests only, the
        map itself never travels).  ``gateway_converged`` reads 1.0
        when every reachable peer agrees; an unreachable peer counts
        as disagreement (a fleet that cannot prove convergence must
        not claim it).  The ``gateway.peer`` fault site lets chaos
        runs sever gateways deterministically."""
        with self._lock:
            mine = self._owner_digest
            peers = sorted(self._peers.items())
        out = []
        agree = True
        for name, url in peers:
            got = None
            try:
                global_faults.fire(
                    "gateway.peer", error_type=RuntimeError,
                    only=("error", "timeout"),
                )
                got = self._get_json(url + "/admin/ownermap?chains=0")
            except RuntimeError:
                got = None
            if got is None:
                out.append(
                    {"peer": name, "digest": None, "agree": False}
                )
                agree = False
                continue
            d = str(got.get("digest") or "")
            ok = bool(mine) and d == mine
            out.append({"peer": name, "digest": d, "agree": ok})
            agree = agree and ok
        self.metrics.set_gauge(
            "gateway_converged", 1.0 if agree else 0.0
        )
        return out

    def owner_map_snapshot(self, include_chains: bool = True) -> dict:
        """The ``GET /admin/ownermap`` body: digest, generation, and
        (unless suppressed) the full chain→owner map — the byte string
        the N-gateway identity test compares."""
        with self._lock:
            snap = {
                "gateway": self.url,
                "digest": self._owner_digest,
                "seq": self._owner_seq,
                "tracked": len(self._owner_map),
                "peers": sorted(self._peers),
                "replicas": sorted(self._replicas),
            }
            if include_chains:
                snap["chains"] = dict(self._owner_map)
        return snap

    # -- drain -------------------------------------------------------------
    def drain(
        self, name: str, deadline_s: float | None = None,
        on_retired=None,
    ) -> dict:
        """Asynchronous LIVE-MIGRATING drain: new traffic stops NOW
        (``FleetRouter.drain``), the victim's warm KV blocks and
        mid-stream requests hand over to a surviving replica
        (``_migrate_for_drain`` / serve/migrate.py), and the replica is
        retired once its in-flight count reaches zero
        (``_replica_inflight``'s three-step read) or ``deadline_s``
        forces it — a forced retirement journals every abandoned
        request.  Idempotent per replica; returns the drain state.
        ``on_retired(name)`` fires after retirement — the operator's
        signal that the pod behind the replica may die."""
        deadline_s = (
            self.drain_deadline_s if deadline_s is None
            else float(deadline_s)
        )
        with self._lock:
            if name not in self._replicas:
                raise KeyError(name)
            st = self._drains.get(name)
            if st is not None:
                return dict(st)
            st = {
                "replica": name,
                "state": "draining",
                "forced": False,
                "deadline_s": deadline_s,
                "inflight": self._inflight.get(name, 0),
            }
            self._drains[name] = st
        self.router.drain(name)
        t = threading.Thread(
            target=self._drain_worker,
            args=(name, self.clock.now() + deadline_s, on_retired),
            name=f"frontend-drain-{name}", daemon=True,
        )
        self._drain_threads.append(t)
        t.start()
        return dict(st)

    def drain_states(self) -> list[dict]:
        with self._lock:
            return [
                dict(self._drains[name])
                for name in sorted(self._drains)
            ]

    def _drain_worker(self, name, deadline, on_retired) -> None:
        """Live-migrates the victim's warm KV state to a surviving
        replica (serve/migrate.py), then waits for its in-flight work
        and retires it.  The migration runs FIRST: export → import →
        re-home → cut the victim's live streams — a cut stream's relay
        failover then re-dispatches onto a destination that is already
        warm, so the in-flight wait below converges fast instead of
        babysitting long decodes on a dying process.  A failed
        migration degrades to the old behavior (wait; resumed requests
        re-prefill from scratch).  The wait paces on the stop event (so
        ``stop()`` interrupts it) but judges the deadline on the
        injected clock.  At a forced deadline, every request still in
        the live ledger is journaled as abandoned — a forced drain must
        be distinguishable from a graceful one in the evidence."""
        t0 = self.clock.now()
        moved = self._migrate_for_drain(name)
        forced = False
        while not self._stop.is_set():
            if self._replica_inflight(name) <= 0:
                break
            if self.clock.now() >= deadline:
                forced = True
                break
            self._stop.wait(self.drain_poll_s)
        if self._stop.is_set():
            return
        abandoned = self._abandon_live(name) if forced else 0
        waited = self.clock.now() - t0
        self.metrics.observe("frontend_drain_wait_seconds", waited)
        self.metrics.inc(
            "frontend_drains_total",
            outcome="forced" if forced else "graceful",
        )
        with self._lock:
            st = self._drains.get(name)
            if st is not None:
                st["state"] = "retired"
                st["forced"] = forced
                st["waited_s"] = round(waited, 4)
                st["abandoned"] = abandoned
                if moved is not None:
                    st["migrated"] = {
                        "dest": moved["dest"],
                        "blocks": moved["blocks"],
                        "bytes": moved["bytes"],
                        "rehomed": moved["rehomed"],
                        "resumed": moved["resumed"],
                    }
        self.retire_replica(name)
        if on_retired is not None:
            try:
                on_retired(name)
            except Exception:
                log.exception("on_retired hook failed for %s", name)

    def _migrate_for_drain(self, name: str) -> dict | None:
        """The drain's migration leg: pick the destination (the
        healthiest replica owning the FEWEST warm chains — the mirror
        of ``scale_down_victim``, it has the most free pool to accept
        state), move the victim's registered blocks, re-home the chains
        on the router, and only THEN cut the victim's live streams —
        the relay failover re-dispatches the instant a stream is cut,
        and that re-route must find the destination warm and owning.
        None when there is nowhere to migrate or a stage exhausted its
        retries (``BlockMigrator`` already minted the failure metrics);
        the caller degrades to the plain wait-and-retire drain."""
        victim_url = self._url_of(name)
        if victim_url is None:
            return None
        snap = {
            r["replica"]: r for r in self.router.snapshot()["replicas"]
        }
        with self._lock:
            cands = [n for n in self._replicas if n != name]
        eligible = [
            n for n in sorted(cands)
            if not any(
                (snap.get(n) or {}).get(flag)
                for flag in ("draining", "down", "unhealthy")
            )
        ]
        if not eligible:
            return None
        dest = min(
            eligible, key=lambda n: (self.router.chains_owned(n), n)
        )
        dest_url = self._url_of(dest)
        if dest_url is None:
            return None
        result = self.migrator.migrate(victim_url, dest_url, victim=name)
        if result is None:
            return None
        rehomed = self.router.rehome(
            [bytes.fromhex(h) for h in result["hashes"]], dest
        )
        # Cut order matters: the GATEWAY cut first (each relay's
        # failover re-dispatches immediately, and the destination is
        # already warm and owning), then the victim-side abort, which
        # frees the victim's compute — it alone is not a reliable cut,
        # because a batcher with the whole budget pipelined retires the
        # stream at the quiesce barrier before the abort sees it.
        cut = self._cut_live_streams(name)
        aborted = self.migrator.abort_live(victim_url)
        out = dict(result)
        out.update({
            "dest": dest, "rehomed": rehomed,
            "resumed": cut, "aborted": aborted,
        })
        log.info(
            "drain %s: migrated %d blocks (%d bytes) to %s, "
            "re-homed %d chains, cut %d live streams (%d aborted)",
            name, out["blocks"], out["bytes"], dest, rehomed, cut,
            aborted,
        )
        return out

    def _abandon_live(self, name: str) -> int:
        """The forced drain's honest ledger: one ``path="gateway"``
        journal record per in-flight request abandoned at the deadline.
        Without this a forced drain looks identical to a graceful one
        in the evidence — the SLO plane would never see the requests
        the deadline killed."""
        with self._lock:
            reqs = self._live.pop(name, None) or {}
        n = len(reqs)
        for info in reqs.values():
            self._journal(
                tenant=info["tenant"], trace_ctx=info["trace_ctx"],
                reason="aborted", code=503, t0=info["t0"],
                replica=name, route_reason=info["route_reason"],
                prompt_tokens=info["prompt_tokens"],
                extra={"drain_forced": True, "abandoned": n},
            )
        return n

    def _replica_inflight(self, name: str) -> int:
        """The drain signal, cheapest source first: (1) the gateway's
        own outstanding-dispatch count (authoritative for traffic that
        came through this door), (2) the replica's ``/readyz``
        ``inflight`` field — the scrape-free fast path, served even
        while the body says NotReady, (3) the federated
        ``serve_pending_requests`` + ``serve_slots_active`` gauges.
        All three unobservable means the replica is dead or mute —
        nothing left to wait for."""
        with self._lock:
            local = self._inflight.get(name, 0)
            url = self._replicas.get(name)
        if local > 0:
            return local
        if url is not None:
            got = self._readyz(url)
            if got is not None and "inflight" in got:
                try:
                    return int(got["inflight"])
                except (TypeError, ValueError):
                    pass
        reg = self.collector.registry
        pend = reg.gauge("serve_pending_requests", replica=name)
        act = reg.gauge("serve_slots_active", replica=name)
        if pend is None and act is None:
            return 0
        return int((pend or 0.0) + (act or 0.0))

    # -- downstream I/O ----------------------------------------------------
    def _readyz(self, url: str) -> dict | None:
        """GET {url}/readyz — the body parses the same whether the
        verdict was 200 or 503 (a draining replica still reports its
        in-flight count there).  None means unreachable."""
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(
                url + "/readyz", timeout=self.request_timeout_s
            ) as r:
                return json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read().decode() or "{}")
            except (ValueError, OSError):
                return None
            finally:
                e.close()
        except (OSError, http.client.HTTPException, ValueError):
            return None

    def _get_json(self, url: str) -> dict | None:
        """GET ``url``, parse JSON; None on any transport/parse error.
        The scrape and peer-digest fetches ride this — both treat None
        as "skip and count", never as fatal."""
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(
                url, timeout=self.request_timeout_s
            ) as r:
                got = json.loads(r.read().decode() or "{}")
        except (
            urllib.error.HTTPError, OSError,
            http.client.HTTPException, ValueError,
        ):
            return None
        return got if isinstance(got, dict) else None

    def _warm(self, url: str) -> None:
        """One real 1-token ``/generate`` against a fresh replica: the
        first compile happens HERE, at registration, instead of inside
        the first client's latency budget."""
        import urllib.request

        req = urllib.request.Request(
            url + "/generate",
            data=json.dumps({
                "prompt": self.prober.prompt_text,
                "max_new_tokens": 1,
                "temperature": 0.0,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.request_timeout_s
            ) as r:
                r.read()
        except (OSError, http.client.HTTPException):
            pass  # the re-gated /readyz delivers the verdict

    def _forward(self, url, body, headers, timeout, stream):
        """One downstream POST {url}/generate attempt, classified:
        ("ok", code, payload) | ("stream", resp) |
        ("shed", payload, retry_after) | ("reject", code, payload) |
        ("deadline", payload) | ("fail", detail)."""
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            url + "/generate", data=json.dumps(body).encode(),
            headers=headers, method="POST",
        )
        try:
            resp = urllib.request.urlopen(req, timeout=timeout)
        except urllib.error.HTTPError as e:
            code = e.code
            try:
                payload = json.loads(e.read().decode() or "{}")
            except (ValueError, OSError):
                payload = {"error": f"upstream status {code}"}
            retry_after = e.headers.get("Retry-After") if e.headers else None
            e.close()
            if code == 429:
                return ("shed", payload, retry_after)
            if code == 504:
                return ("deadline", payload)
            if 400 <= code < 500:
                return ("reject", code, payload)
            return ("fail", f"upstream status {code}")
        except (OSError, http.client.HTTPException) as e:
            return ("fail", type(e).__name__)
        if stream:
            return ("stream", resp)
        try:
            payload = json.loads(resp.read().decode() or "{}")
            code = resp.status
        except (ValueError, OSError):
            return ("fail", "unparseable upstream body")
        finally:
            resp.close()
        return ("ok", code, payload)

    def _headers_for(self, replica, reason, tenant, deadline, trace_ctx):
        """The dispatch stamp: the routing decision
        (``x-route-replica``/``x-route-reason`` — the downstream
        journal's placement evidence), the tenant, the REMAINING
        deadline budget, and the gateway span's traceparent so the
        downstream trace joins the client's."""
        h = {
            "Content-Type": "application/json",
            "x-tenant": tenant,
            "x-route-replica": replica[:64],
            "x-route-reason": reason[:16],
        }
        if deadline is not None:
            remaining_ms = (deadline - self.clock.now()) * 1000.0
            h["x-request-deadline-ms"] = str(max(1, int(remaining_ms)))
        if trace_ctx is not None:
            h["traceparent"] = format_traceparent(trace_ctx)
        return h

    def _attempt_span(
        self, trace_ctx, attempt_ctx, replica, attempt, outcome, s_at,
    ) -> None:
        """Record one ``gateway.dispatch`` span per downstream contact —
        the waterfall plane's evidence (utils/waterfall.py).  Its
        pre-minted id was the traceparent the attempt propagated, so
        the replica's server span nests INSIDE it: that containment is
        the cross-process clock-pinning anchor, and a failed attempt's
        span bounds the ``retry_hop`` segment.  Boundaries come from
        the tracer's own clock so dispatch spans share the mixin root
        span's timeline (the injected ``self.clock`` may be a test
        FakeClock on a different time line)."""
        if attempt_ctx is None:
            return
        global_tracer.add_span(
            "gateway.dispatch",
            parent=trace_ctx,
            start=s_at,
            end=global_tracer.clock.now(),
            status="error" if outcome == "fail" else "ok",
            span_id=attempt_ctx.span_id,
            replica=replica,
            attempt=attempt,
            outcome=outcome,
        )

    def _track(self, name: str, delta: int) -> int:
        with self._lock:
            if name not in self._inflight:
                return 0
            cur = max(0, self._inflight[name] + delta)
            self._inflight[name] = cur
        self.metrics.set_gauge(
            "frontend_inflight_requests", float(cur), replica=name
        )
        return cur

    def _live_add(self, name: str, info: dict) -> int:
        """Register an outstanding downstream contact in the per-replica
        live ledger — the forced drain's abandonment evidence (each
        entry it still holds at the deadline becomes one journal
        record).  Returns the ledger key, -1 for an unknown replica."""
        with self._lock:
            if name not in self._replicas:
                return -1
            self._live_seq += 1
            key = self._live_seq
            self._live.setdefault(name, {})[key] = info
        return key

    def _live_drop(self, name: str, key: int) -> None:
        with self._lock:
            reqs = self._live.get(name)
            if reqs is not None:
                reqs.pop(key, None)
                if not reqs:
                    self._live.pop(name, None)

    def _live_attach(self, name: str, key: int, resp) -> None:
        """Attach a cuttable upstream stream handle to a live-ledger
        entry (routed streams only — a pinned stream is an explicit
        this-replica contract, so a drain never cuts it)."""
        with self._lock:
            info = self._live.get(name, {}).get(key)
            if info is not None:
                info["resp"] = resp

    def _cut_live_streams(self, name: str) -> int:
        """Cut ``name``'s live routed streams at the GATEWAY: closing
        the upstream response makes each relay see a truncation and run
        its failover (resume on a surviving replica).  The authoritative
        mid-stream cut for a migrating drain — the victim's own
        ``abort_live`` only frees compute, and a pipelined batcher may
        have the whole token budget in flight before its quiesce barrier
        runs, which would let the stream finish on the victim instead of
        handing over."""
        with self._lock:
            resps = [
                info["resp"]
                for info in self._live.get(name, {}).values()
                if info.get("resp") is not None
            ]
        for resp in resps:
            try:
                resp.close()
            except OSError:
                pass
        return len(resps)

    def _url_of(self, name: str) -> str | None:
        with self._lock:
            return self._replicas.get(name)

    def _journal(
        self, *, tenant, trace_ctx, reason, code, t0,
        replica="", route_reason="", prompt_tokens=0, tokens=0,
        attempts=1, extra=None, req_ids=None, req_body=None,
        prefill_replica="", handover_s=0.0,
    ) -> None:
        e = {"status": int(code), "attempts": int(attempts)}
        e.update(extra or {})
        # Replay plane (serve/replay.py): when the dispatch path hands
        # us the request itself, the gateway record becomes a complete
        # reproduction record — a gateway journal alone is then a
        # capturable workload (arrival offsets are stamped by
        # journal.append from t_submit=t0).
        body = req_body or {}
        self.journal.append(JournalRecord(
            tenant=tenant,
            trace_id=trace_ctx.trace_id if trace_ctx else "",
            reason=reason,
            path="gateway",
            prompt_ids=(
                [int(t) for t in req_ids] if req_ids is not None else []
            ),
            max_new=int(body.get("max_new_tokens", 0) or 0),
            temperature=float(body.get("temperature", 0.0) or 0.0),
            top_p=float(body.get("top_p", 0.0) or 0.0),
            seed=int(body.get("seed", 0) or 0),
            replica=replica,
            route_reason=route_reason,
            prefill_replica=prefill_replica,
            handover=float(handover_s),
            prompt_tokens=int(prompt_tokens),
            tokens=int(tokens),
            deadline_expired=(reason == "deadline"),
            t_submit=t0,
            t_done=self.clock.now(),
            extra=e,
        ))

    # -- dispatch ----------------------------------------------------------
    def dispatch(
        self, ids, body, *, tenant, deadline=None, trace_ctx=None,
        stream=False, pinned=None, exclude=None, migrated_from="",
        handover=None,
    ) -> dict:
        """Route → breaker-gate → forward → classify, retrying per the
        failure matrix (module docstring).  Returns a response outcome
        for the handler: {"kind": "json", code, payload, headers,
        replica, reason} or {"kind": "stream", resp, replica, reason,
        finish}.  ``pinned`` skips routing and contacts exactly that
        replica — no rehash, a pinned failure IS the answer (the canary
        contract: a dead replica must fail its probe, not silently
        succeed elsewhere).  ``exclude`` pre-blacklists replicas (the
        stream-failover path must not resume on the victim it just
        lost); ``migrated_from`` stamps the downstream submit as a
        migration resume (``x-migrated-from`` — the replica journals
        and counts it).  ``handover`` is a completed disagg handover's
        summary ({"prefill", "seconds", ...}) — journaled onto the
        request's record, never re-attempted here: if routing lands
        somewhere other than the import destination, the decode worker
        simply misses the warm chain and re-prefills (fused path)."""
        t0 = self.clock.now()
        h_rep = (handover or {}).get("prefill", "")
        h_s = float((handover or {}).get("seconds", 0.0) or 0.0)
        body = dict(body)
        body["tenant"] = tenant
        if pinned is not None:
            return self._dispatch_pinned(
                pinned, ids, body, tenant, deadline, trace_ctx,
                stream, t0,
            )
        max_tries = max(1, len(self.router.replica_names()))
        budget = self.policy.budget
        tried: set[str] = set(exclude or ())
        shed = None           # (payload, retry_after) of the last 429
        last_fail = ""
        contacts = 0
        attempt = 0
        while attempt < max_tries:
            if deadline is not None and self.clock.now() >= deadline:
                return self._shed_out(
                    "deadline", 504, {"error": "deadline exceeded"},
                    tenant, trace_ctx, t0, contacts,
                )
            try:
                dec = self.router.route(ids, exclude=tried)
            except RuntimeError:
                break
            replica, reason = dec.replica, dec.reason
            br = self.breakers.get(replica)
            if not br.allow():
                # Open breaker: known-bad, don't even contact — spend
                # the attempt on the next candidate.
                tried.add(replica)
                attempt += 1
                continue
            url = self._url_of(replica)
            if url is None:
                # Retired between route and contact.
                br.release()
                tried.add(replica)
                attempt += 1
                continue
            if contacts > 0:
                self.metrics.inc("frontend_retries_total")
            contacts += 1
            # Pre-mint the attempt span's identity and propagate THAT
            # downstream: the replica's server span then parents to
            # this attempt, not the whole request — the structural
            # pairing utils/waterfall.py aligns clocks by.
            attempt_ctx = (
                SpanContext(trace_ctx.trace_id, new_span_id())
                if trace_ctx is not None else None
            )
            headers = self._headers_for(
                replica, reason, tenant, deadline,
                attempt_ctx or trace_ctx,
            )
            if migrated_from:
                headers["x-migrated-from"] = migrated_from[:64]
            timeout = self.request_timeout_s
            if deadline is not None:
                timeout = max(
                    0.001, min(timeout, deadline - self.clock.now())
                )
            self._track(replica, +1)
            live_key = self._live_add(replica, {
                "tenant": tenant, "trace_ctx": trace_ctx, "t0": t0,
                "prompt_tokens": len(ids), "route_reason": reason,
            })
            t_at = self.clock.now()
            s_at = global_tracer.clock.now()
            out = self._forward(url, body, headers, timeout, stream)
            kind = out[0]
            if kind != "stream":
                self._track(replica, -1)
                self._live_drop(replica, live_key)
                self.metrics.observe(
                    "frontend_upstream_seconds",
                    self.clock.now() - t_at, replica=replica,
                )
                self._attempt_span(
                    trace_ctx, attempt_ctx, replica, contacts, kind,
                    s_at,
                )
            if kind == "ok":
                br.record_success()
                self.router.mark_up(replica)
                code, payload = out[1], out[2]
                self._journal(
                    tenant=tenant, trace_ctx=trace_ctx, reason="ok",
                    code=code, t0=t0, replica=replica,
                    route_reason=reason, prompt_tokens=len(ids),
                    tokens=int(payload.get("generated_tokens", 0) or 0),
                    attempts=contacts, req_ids=ids, req_body=body,
                    prefill_replica=h_rep, handover_s=h_s,
                )
                return {
                    "kind": "json", "code": code, "payload": payload,
                    "headers": {}, "replica": replica, "reason": reason,
                }
            if kind == "stream":
                br.record_success()
                self.router.mark_up(replica)
                resp = out[1]
                self._live_attach(replica, live_key, resp)
                n_prompt = len(ids)

                def finish(tokens, _r=replica, _reason=reason,
                           _t_at=t_at, _n=n_prompt, _c=contacts,
                           _actx=attempt_ctx, _s_at=s_at,
                           _lk=live_key):
                    self._track(_r, -1)
                    self._live_drop(_r, _lk)
                    self.metrics.observe(
                        "frontend_upstream_seconds",
                        self.clock.now() - _t_at, replica=_r,
                    )
                    self._attempt_span(
                        trace_ctx, _actx, _r, _c, "stream", _s_at,
                    )
                    self._journal(
                        tenant=tenant, trace_ctx=trace_ctx,
                        reason="ok", code=200, t0=t0, replica=_r,
                        route_reason=_reason, prompt_tokens=_n,
                        tokens=tokens, attempts=_c,
                        extra={"stream": True},
                        req_ids=ids, req_body=body,
                        prefill_replica=h_rep, handover_s=h_s,
                    )

                return {
                    "kind": "stream", "resp": resp, "replica": replica,
                    "reason": reason, "finish": finish,
                }
            if kind == "shed":
                # 429: the replica is alive and telling us it is full —
                # a load signal, never a death.  Retry elsewhere; if the
                # whole fleet sheds, the LAST 429 (and its Retry-After)
                # passes through verbatim.
                br.record_success()
                shed = (out[1], out[2])
                tried.add(replica)
                self.metrics.inc("serve_router_rehash_total")
                attempt += 1
                continue
            if kind == "reject":
                # A request fault (bad adapter, prompt too long): it
                # would fail identically on every replica.
                br.record_success()
                code, payload = out[1], out[2]
                self._journal(
                    tenant=tenant, trace_ctx=trace_ctx,
                    reason="rejected", code=code, t0=t0,
                    replica=replica, route_reason=reason,
                    prompt_tokens=len(ids), attempts=contacts,
                    req_ids=ids, req_body=body,
                )
                return {
                    "kind": "json", "code": code, "payload": payload,
                    "headers": {}, "replica": replica, "reason": reason,
                }
            if kind == "deadline":
                # The request's own budget died downstream; a retry
                # would duplicate work the client can no longer use.
                br.record_success()
                payload = out[1]
                self._journal(
                    tenant=tenant, trace_ctx=trace_ctx,
                    reason="deadline", code=504, t0=t0,
                    replica=replica, route_reason=reason,
                    prompt_tokens=len(ids), attempts=contacts,
                    req_ids=ids, req_body=body,
                )
                return {
                    "kind": "json", "code": 504, "payload": payload,
                    "headers": {}, "replica": replica, "reason": reason,
                }
            # kind == "fail": connection refused / timeout / 5xx — the
            # replica is observed dead.  Mark it down (its chains
            # re-home), rehash, and retry the next candidate after a
            # deterministic-jitter backoff.
            br.record_failure()
            last_fail = out[1]
            tried.add(replica)
            self.router.mark_down(replica)
            self.metrics.inc("serve_router_rehash_total")
            attempt += 1
            budget -= 1
            if budget <= 0:
                break
            if attempt < max_tries:
                self.clock.sleep(
                    self.policy.delay(attempt, key=replica)
                )
        if shed is not None:
            payload, retry_after = shed
            return self._shed_out(
                "overloaded", 429, payload, tenant, trace_ctx, t0,
                contacts,
                headers={
                    "Retry-After": retry_after or str(RETRY_AFTER_S)
                },
            )
        detail = last_fail or "none eligible"
        return self._shed_out(
            "no_replica", 503,
            {"error": f"no replica available ({detail})"},
            tenant, trace_ctx, t0, contacts,
            headers={"Retry-After": str(RETRY_AFTER_S)},
        )

    def resume_stream(self, rctx, emitted, *, victim: str) -> dict:
        """Re-dispatch a truncated stream on a surviving replica: the
        prompt becomes ``original ids + tokens already delivered`` (a
        teacher-forced prefix — greedy decode continues exactly where
        the victim stopped) and the token budget shrinks to what the
        client is still owed.  The victim is excluded from routing and
        the submit is stamped ``x-migrated-from`` so the destination's
        journal carries the provenance.  When the victim's KV chains
        were wire-migrated first (serve/migrate.py), the new owner
        prefix-hits the moved blocks and the resume costs one extend,
        not a re-prefill."""
        body = dict(rctx["body"])
        body.pop("prompt", None)
        prompt_ids = list(rctx["ids"]) + [int(t) for t in emitted]
        body["prompt_ids"] = prompt_ids
        body["max_new_tokens"] = int(rctx["max_new"] - len(emitted))
        body["stream"] = True
        return self.dispatch(
            prompt_ids, body,
            tenant=rctx["tenant"], deadline=rctx["deadline"],
            trace_ctx=rctx["trace_ctx"], stream=True,
            exclude={victim}, migrated_from=victim,
        )

    def _dispatch_pinned(
        self, name, ids, body, tenant, deadline, trace_ctx, stream, t0
    ) -> dict:
        """Pinned single-replica dispatch (``/replica/<name>/generate``):
        no routing, no rehash — the canary probe path, and the recovery
        path (a successful contact ``mark_up``s a downed replica and
        closes its breaker)."""
        url = self._url_of(name)
        if url is None:
            return {
                "kind": "json", "code": 404,
                "payload": {"error": f"unknown replica {name!r}"},
                "headers": {}, "replica": "", "reason": "",
            }
        br = self.breakers.get(name)
        if not br.allow():
            return {
                "kind": "json", "code": 503,
                "payload": {"error": f"circuit open for {name!r}"},
                "headers": {"Retry-After": str(RETRY_AFTER_S)},
                "replica": name, "reason": "pinned",
            }
        attempt_ctx = (
            SpanContext(trace_ctx.trace_id, new_span_id())
            if trace_ctx is not None else None
        )
        headers = self._headers_for(
            name, "pinned", tenant, deadline, attempt_ctx or trace_ctx
        )
        timeout = self.request_timeout_s
        if deadline is not None:
            timeout = max(
                0.001, min(timeout, deadline - self.clock.now())
            )
        self._track(name, +1)
        live_key = self._live_add(name, {
            "tenant": tenant, "trace_ctx": trace_ctx, "t0": t0,
            "prompt_tokens": len(ids), "route_reason": "pinned",
        })
        t_at = self.clock.now()
        s_at = global_tracer.clock.now()
        out = self._forward(url, body, headers, timeout, stream)
        kind = out[0]
        if kind != "stream":
            self._track(name, -1)
            self._live_drop(name, live_key)
            self.metrics.observe(
                "frontend_upstream_seconds",
                self.clock.now() - t_at, replica=name,
            )
            self._attempt_span(
                trace_ctx, attempt_ctx, name, 1, kind, s_at
            )
        if kind == "ok":
            br.record_success()
            self.router.mark_up(name)
            code, payload = out[1], out[2]
            self._journal(
                tenant=tenant, trace_ctx=trace_ctx, reason="ok",
                code=code, t0=t0, replica=name, route_reason="pinned",
                prompt_tokens=len(ids),
                tokens=int(payload.get("generated_tokens", 0) or 0),
                req_ids=ids, req_body=body,
            )
            return {
                "kind": "json", "code": code, "payload": payload,
                "headers": {}, "replica": name, "reason": "pinned",
            }
        if kind == "stream":
            br.record_success()
            self.router.mark_up(name)
            n_prompt = len(ids)

            def finish(tokens, _t_at=t_at, _actx=attempt_ctx,
                       _s_at=s_at, _lk=live_key):
                self._track(name, -1)
                self._live_drop(name, _lk)
                self.metrics.observe(
                    "frontend_upstream_seconds",
                    self.clock.now() - _t_at, replica=name,
                )
                self._attempt_span(
                    trace_ctx, _actx, name, 1, "stream", _s_at
                )
                self._journal(
                    tenant=tenant, trace_ctx=trace_ctx, reason="ok",
                    code=200, t0=t0, replica=name,
                    route_reason="pinned", prompt_tokens=n_prompt,
                    tokens=tokens, extra={"stream": True},
                    req_ids=ids, req_body=body,
                )

            return {
                "kind": "stream", "resp": out[1], "replica": name,
                "reason": "pinned", "finish": finish,
            }
        if kind == "shed":
            br.record_success()
            payload, retry_after = out[1], out[2]
            self._journal(
                tenant=tenant, trace_ctx=trace_ctx,
                reason="overloaded", code=429, t0=t0, replica=name,
                route_reason="pinned", prompt_tokens=len(ids),
                req_ids=ids, req_body=body,
            )
            return {
                "kind": "json", "code": 429, "payload": payload,
                "headers": {
                    "Retry-After": retry_after or str(RETRY_AFTER_S)
                },
                "replica": name, "reason": "pinned",
            }
        if kind == "reject":
            br.record_success()
            code, payload = out[1], out[2]
            self._journal(
                tenant=tenant, trace_ctx=trace_ctx, reason="rejected",
                code=code, t0=t0, replica=name, route_reason="pinned",
                prompt_tokens=len(ids), req_ids=ids, req_body=body,
            )
            return {
                "kind": "json", "code": code, "payload": payload,
                "headers": {}, "replica": name, "reason": "pinned",
            }
        if kind == "deadline":
            br.record_success()
            self._journal(
                tenant=tenant, trace_ctx=trace_ctx, reason="deadline",
                code=504, t0=t0, replica=name, route_reason="pinned",
                prompt_tokens=len(ids), req_ids=ids, req_body=body,
            )
            return {
                "kind": "json", "code": 504, "payload": out[1],
                "headers": {}, "replica": name, "reason": "pinned",
            }
        br.record_failure()
        self.router.mark_down(name)
        self._journal(
            tenant=tenant, trace_ctx=trace_ctx, reason="error",
            code=502, t0=t0, replica=name, route_reason="pinned",
            prompt_tokens=len(ids), extra={"detail": out[1]},
            req_ids=ids, req_body=body,
        )
        return {
            "kind": "json", "code": 502,
            "payload": {"error": f"replica {name!r} failed: {out[1]}"},
            "headers": {}, "replica": name, "reason": "pinned",
        }

    def _shed_out(
        self, reason, code, payload, tenant, trace_ctx, t0, contacts,
        headers=None,
    ) -> dict:
        self.metrics.inc("frontend_shed_total", reason=reason)
        self._journal(
            tenant=tenant, trace_ctx=trace_ctx,
            reason="deadline" if reason == "deadline" else reason,
            code=code, t0=t0, attempts=max(1, contacts),
        )
        return {
            "kind": "json", "code": code, "payload": payload,
            "headers": dict(headers or {}), "replica": "", "reason": "",
        }
