"""Refcounted paged-KV block pool with content-hash prefix sharing.

The allocator behind the batcher's paged mode (vLLM-style *automatic
prefix caching*, TPU-shaped): physical blocks of ``page_size`` positions
are the unit of both allocation and reuse.  A prompt's page-aligned
chunks are hashed as a **chain** — chunk i's hash covers every token
before it, because a block's K/V content depends on the whole prefix
through attention, not just its own tokens — and full prompt blocks are
registered ``hash → block id`` after prefill.  A later request whose
chain matches maps its page table to the *same* physical blocks and
only computes its suffix.

Lifecycle of a block:

- **free**: on the free list, content meaningless;
- **pinned** (refcount >= 1): referenced by one or more live slots'
  page tables.  Never evicted, never re-allocated; shared prefix
  blocks are read-only by construction (decode writes land at
  positions past the prompt, which always map to a request's private
  tail blocks);
- **cached** (refcount 0, registered hash): retired but kept — sits in
  an LRU so the next request with the same prefix can re-acquire it.
  Evicted (hash dropped, block back to the free list) only when an
  allocation needs the space, oldest first.

Occupancy accounting counts **physical** blocks: a block shared by N
slots is one pinned block, not N — per-request block lists would
double-count shared prefixes and false-fire KVCacheSaturation.

Host-side only, single-threaded (the batcher's scheduler thread owns
every call); device safety of immediate block reuse rides the batcher's
dispatch-FIFO argument (serve/batcher.py paged-KV comments).
"""

from __future__ import annotations

import collections
import hashlib

import numpy as np


def chunk_hashes(ids: np.ndarray, page: int) -> list[bytes]:
    """Chained hashes of the FULL page-aligned chunks of ``ids``:
    h_i = H(h_{i-1} || tokens[i*page:(i+1)*page]).  Only full chunks —
    a partial tail block is never shared (its content would change as
    decode writes into it); the partial tail is instead recomputed into
    a private block, which is this cache's copy-on-write."""
    ids = np.ascontiguousarray(ids, np.int32)
    out: list[bytes] = []
    h = b""
    for i in range(int(ids.size) // page):
        m = hashlib.blake2b(digest_size=16)
        m.update(h)
        m.update(ids[i * page:(i + 1) * page].tobytes())
        h = m.digest()
        out.append(h)
    return out


def shareable_depth(n: int, page: int) -> int:
    """How many leading full pages of an ``n``-token prompt are
    SHAREABLE: full pages only, capped so at least one suffix token
    remains (the extend must produce first-token logits).  This is the
    one definition of "the chain" — the batcher's paged admission, the
    in-process router, and the HTTP front-end all key on it, so a
    request hashed by the gateway lands on the replica whose block
    cache registered the very same chain."""
    return max(0, int(n) - 1) // max(1, int(page))


def shareable_chain(ids, page: int) -> list[bytes]:
    """The page-aligned chain hashes of a prompt's shareable prefix —
    ``chunk_hashes`` truncated to ``shareable_depth``.  The routing key
    (serve/router.py, serve/frontend.py) and the acquire chain of paged
    admission (serve/batcher.py) are byte-identical by construction
    because both come from here."""
    ids = np.ascontiguousarray(ids, np.int32)
    depth = shareable_depth(int(ids.size), page)
    return chunk_hashes(ids, page)[:depth] if depth else []


class BlockPool:
    """Block allocator: free list + refcounts + hash table + LRU.

    ``n_blocks`` counts the whole pool including block 0 — the trash
    block, which is never allocated (retired page-table rows point at
    it so in-flight garbage writes land somewhere harmless)."""

    def __init__(self, n_blocks: int, page_size: int):
        self.n_blocks = int(n_blocks)
        self.page = int(page_size)
        self._free: list[int] = list(range(1, self.n_blocks))
        self._ref: dict[int, int] = {}
        self._blk_of: dict[bytes, int] = {}       # hash -> block
        self._hash_of: dict[int, bytes] = {}      # block -> hash
        # refcount-0 registered blocks, oldest first (the eviction order)
        self._lru: "collections.OrderedDict[int, bool]" = (
            collections.OrderedDict()
        )
        self.evictions = 0

    # -- queries -----------------------------------------------------------
    @property
    def usable(self) -> int:
        return self.n_blocks - 1

    @property
    def allocatable_count(self) -> int:
        """Blocks an alloc() could hand out: free + evictable-cached."""
        return len(self._free) + len(self._lru)

    @property
    def pinned_count(self) -> int:
        """Physical blocks held by live slots — shared blocks count ONCE
        (the occupancy number KVCacheSaturation must see)."""
        return self.usable - self.allocatable_count

    @property
    def shared_count(self) -> int:
        """Physical blocks referenced by >= 2 live slots."""
        return sum(1 for r in self._ref.values() if r >= 2)

    @property
    def cached_count(self) -> int:
        """Refcount-0 blocks kept for reuse (evictable)."""
        return len(self._lru)

    def refcount(self, blk: int) -> int:
        return self._ref.get(blk, 0)

    def allocatable_blocks(self) -> list[int]:
        """Sorted ids of every block an alloc() could hand out — the
        post-shutdown leak-check surface (a clean pool returns all
        blocks here, whether plain-free or cached)."""
        return sorted(list(self._free) + list(self._lru))

    def contains(self, h: bytes) -> bool:
        """Whether ``h`` is registered (pinned or cached) — the
        migration import's duplicate gate, checked WITHOUT touching
        refcounts or LRU order."""
        return h in self._blk_of

    def registered(self) -> list[tuple[bytes, int]]:
        """Every registered ``(hash, block)`` pair, sorted by hash —
        the deterministic enumeration the wire-level export serializes.
        Covers pinned and cached blocks alike: both are content the
        chain addresses, and the destination decides what it lacks."""
        return sorted(self._blk_of.items(), key=lambda kv: kv[0])

    def chain_hashes(self) -> list[bytes]:
        """Sorted registered content hashes, no block ids — the
        read-only enumeration the gateway's owner-map reconstruction
        scrapes (``GET /debug/chains``, serve/frontend.py).  The sort
        makes the scrape body a deterministic function of pool content,
        which is what lets N gateways rebuild the SAME owner map from
        independent scrapes."""
        return sorted(self._blk_of)

    # -- sharing -----------------------------------------------------------
    def acquire(self, h: bytes) -> int | None:
        """Pin the block registered under ``h`` (refcount++), pulling it
        out of the LRU if it was resting there.  None on miss."""
        blk = self._blk_of.get(h)
        if blk is None:
            return None
        if self._ref.get(blk, 0) == 0:
            self._lru.pop(blk, None)
        self._ref[blk] = self._ref.get(blk, 0) + 1
        return blk

    def register(self, blk: int, h: bytes) -> None:
        """Record ``blk``'s content hash so later prompts can share it.
        First writer wins: a hash already mapped (or a block already
        registered) keeps its existing entry — admissions are serialized
        on the scheduler thread, so a would-be duplicate writer would
        have matched instead."""
        if h in self._blk_of or blk in self._hash_of:
            return
        self._blk_of[h] = blk
        self._hash_of[blk] = h

    # -- allocation --------------------------------------------------------
    def alloc(self, n: int) -> list[int] | None:
        """Take ``n`` fresh blocks (refcount 1 each), evicting LRU
        cached blocks as needed.  None when even full eviction cannot
        cover — the caller defers (or fails) without side effects."""
        if n <= 0:
            return []
        if len(self._free) + len(self._lru) < n:
            return None
        while len(self._free) < n:
            blk, _ = self._lru.popitem(last=False)  # oldest first
            del self._blk_of[self._hash_of.pop(blk)]
            self._free.append(blk)
            self.evictions += 1
        taken = self._free[:n]
        del self._free[:n]
        for b in taken:
            self._ref[b] = 1
        return taken

    def release(self, blk: int) -> None:
        """Drop one reference.  At refcount 0 a registered block parks
        in the LRU (content kept for the next sharer); an unregistered
        one returns straight to the free list."""
        r = self._ref.get(blk, 0) - 1
        if r > 0:
            self._ref[blk] = r
            return
        self._ref.pop(blk, None)
        if blk in self._hash_of:
            self._lru[blk] = True
            self._lru.move_to_end(blk)
        else:
            self._free.append(blk)
