"""Serving: KV-cache inference engine + the LM HTTP server."""

from .engine import DecodeOutput, InferenceEngine, SamplingConfig
from .server import LmServer

__all__ = ["InferenceEngine", "SamplingConfig", "DecodeOutput", "LmServer"]
