"""Inference/serving subsystem: KV-cache autoregressive decoding.

The reference serves its LLM through a local Ollama server
(智能风控解决方案.md:196, 219-223 — `qwen:72b` behind an OpenAI-compatible
client); this package is the TPU-native equivalent: the flagship
TransformerLM compiled into a prefill + single-token decode loop with a
static-shape KV cache, suitable for jit on one chip or pjit over a mesh.
"""

from .engine import DecodeOutput, InferenceEngine, SamplingConfig

__all__ = ["InferenceEngine", "SamplingConfig", "DecodeOutput"]
