"""Serving: KV-cache inference engine, continuous batcher, LM HTTP server."""

from .batcher import ContinuousBatcher, RequestHandle
from .engine import DecodeOutput, InferenceEngine, SamplingConfig
from .server import LmServer

__all__ = [
    "InferenceEngine", "SamplingConfig", "DecodeOutput", "LmServer",
    "ContinuousBatcher", "RequestHandle",
]
