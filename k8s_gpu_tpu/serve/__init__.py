"""Serving: KV-cache engine, continuous batcher, speculative decoding,
int8 weight-only quantization, LM HTTP server."""

from .admission import AdmissionController, TenantPolicy
from .batcher import ContinuousBatcher, Overloaded, RequestHandle
from .bundle import export_servable, load_servable
from .canary import CanaryProber
from .constrain import RegexConstraint, compile_constraint
from .disagg import DisaggregatedLm
from .engine import DecodeOutput, InferenceEngine, SamplingConfig
from .frontend import FleetFrontend, merge_owner_map, owner_map_digest
from .journal import PROBE_TENANT, RequestJournal, RequestRecord
from .jsonschema import SchemaError, schema_to_regex
from .quant import quantize_params
from .ratio import RatioController, RatioDecision
from .replay import (
    ReplayState,
    WorkloadRecorder,
    WorkloadReplayer,
    diff_reports,
    load_workload,
    workload_report,
)
from .router import (
    FleetAutoscaler,
    FleetRouter,
    RouteDecision,
    ScaleDecision,
    router_rule_pack,
)
from .server import LmServer
from .speculative import distill_draft, int8_draft, rejection_sample

__all__ = [
    "InferenceEngine", "SamplingConfig", "DecodeOutput", "LmServer",
    "ContinuousBatcher", "Overloaded", "RequestHandle",
    "RequestJournal", "RequestRecord",
    "CanaryProber", "PROBE_TENANT", "FleetFrontend",
    "merge_owner_map", "owner_map_digest",
    "AdmissionController", "TenantPolicy",
    "FleetRouter", "RouteDecision", "FleetAutoscaler", "ScaleDecision",
    "router_rule_pack", "RatioController", "RatioDecision",
    "quantize_params", "export_servable", "load_servable",
    "DisaggregatedLm", "RegexConstraint", "compile_constraint",
    "distill_draft", "int8_draft", "rejection_sample",
    "schema_to_regex", "SchemaError",
    "WorkloadRecorder", "WorkloadReplayer", "ReplayState",
    "diff_reports", "load_workload", "workload_report",
]
