"""JSON-schema constrained decoding: schema → regex → token DFA.

The reference's serving story delegates structure to prompt engineering
(智能风控解决方案.md:250-266 asks the LLM nicely); modern serving stacks
offer schema-constrained output (OpenAI ``response_format``, vLLM
guided decoding).  Here the schema compiles to a regex over the
CANONICAL JSON serialization, and the existing regex→DFA pipeline
(serve/constrain.py) does the rest — one code path enforces both plain
regex and JSON-schema constraints, banked per request in shared decode
rounds.

Canonical form (what the DFA admits — also what ``json.dumps(...,
separators=(",", ":"))`` emits):

- no whitespace outside strings;
- object properties in DECLARATION order, all present (constrained
  generation must decide the next token greedily — optional/reordered
  keys would make the automaton ambiguous about which key comes next;
  callers mark truly-optional fields as nullable instead);
- strings admit any character except ``"``, ``\\`` and control chars,
  plus ``\\"`` ``\\\\`` ``\\/`` ``\\b`` ``\\f`` ``\\n`` ``\\r`` ``\\t``
  and ``\\uXXXX`` escapes.

Supported schema subset: ``type`` ∈ {string, integer, number, boolean,
null, array, object}, ``enum`` (JSON scalars), ``properties`` (fixed
order), ``items``, ``minItems`` ∈ {0, 1}, string ``pattern`` (the
author's regex replaces the default string body, INTERSECTED with the
legal JSON-string alphabet so it can never emit a raw quote/backslash/
control character).  Keyword support is an allowlist: anything else
(``maxItems``, ``required``, ``minimum``, ``$ref``, ...) is rejected
loudly — a constraint that silently under-constrains is worse than none.
"""

from __future__ import annotations

import json

__all__ = ["schema_to_regex", "SchemaError"]


class SchemaError(ValueError):
    pass


def _lit(text: str) -> str:
    """Regex matching *text* literally (escape every non-alphanumeric —
    constrain.py's parser treats ``\\X`` as literal X for non-alnum)."""
    return "".join(c if c.isalnum() else "\\" + c for c in text)


# One JSON string character: anything but quote/backslash/the full
# control range 0x00-0x1F (json.loads rejects raw controls), or a
# sanctioned escape.  The control characters are embedded RAW in the
# class — constrain.py's class parser takes any character literally.
_CTRL = "".join(chr(i) for i in range(0x20))
_STRING_CHAR = (
    '([^"\\\\' + _CTRL + ']'
    '|\\\\(["\\\\/bfnrt]|u[0-9a-fA-F][0-9a-fA-F][0-9a-fA-F][0-9a-fA-F]))'
)
_STRING = '"' + _STRING_CHAR + '*"'
_INTEGER = "\\-?(0|[1-9][0-9]*)"
_NUMBER = _INTEGER + "(\\.[0-9]+)?([eE][\\-\\+]?[0-9]+)?"


# The full supported keyword surface.  An ALLOWLIST, not a denylist: any
# keyword outside it (minimum, maxLength, required, $ref, ...) would be
# silently ignored by this compiler, i.e. the DFA would under-constrain
# relative to the declared schema — the exact failure mode the module
# docstring calls worse than none.  Annotation-only keys that constrain
# nothing (title, description, ...) are tolerated.
_SUPPORTED_KEYS = frozenset(
    {"type", "enum", "properties", "items", "minItems", "pattern", "nullable"}
)
_ANNOTATION_KEYS = frozenset({"title", "description", "default", "examples", "$schema"})


# Characters no JSON string body may contain raw: the framing quote, the
# escape introducer, and the full control range.  A pattern atom that can
# match one of them would let the DFA emit output that is not valid JSON
# (a raw quote inside the string body), so every atom is INTERSECTED with
# the legal body alphabet rather than embedded verbatim:
#
#   .          → [^"\<ctrl>]        dot, narrowed to the legal alphabet
#   [^...]     → [^..."\<ctrl>]     widening the negation = intersection
#   [a-z"]     → SchemaError        a member outside the legal alphabet
#   \s \n \t…  → SchemaError        would emit raw control characters
#
# The { } $ rejections (no bounded reps/anchors in the DFA dialect) and
# the top-level ^ rejection stay; ^ right after an unescaped [ is class
# negation and is supported by constrain.py, so it passes through.
_ILLEGAL_ORDS = frozenset({0x22, 0x5C} | set(range(0x20)))
_NEG_EXTRA = '"\\\\' + _CTRL  # regex text: quote, escaped backslash, raw ctrls
_LEGAL_DOT = "[^" + _NEG_EXTRA + "]"


def _pattern_to_string_body(pat: str) -> str:
    """Rewrite an author regex so it can only emit legal JSON string bodies."""

    def fail(msg: str):
        raise SchemaError(f"string pattern {pat!r}: {msg}")

    out: list[str] = []
    i, n = 0, len(pat)
    in_class = False          # inside [...]
    class_negated = False
    at_class_start = False    # immediately after [ (where ^ negates)
    prev_ord: int | None = None  # last concrete class member (range lo)
    range_open = False        # saw 'lo-' and await the range hi

    def member(o: int, text: str):
        """Append one concrete class member, enforcing legality/ranges."""
        nonlocal prev_ord, range_open
        if text == "-":
            # Always escape a literal dash member: raw, it could abut the
            # _NEG_EXTRA flush in a negated class and form a `-"` range —
            # `[^a-]*` compiled to `[^a-"\\…]*`, whose dash-range ate the
            # exclusion and let a raw quote leak into constrained JSON
            # output (ADVICE medium).
            text = "\\-"
        if range_open:
            lo = prev_ord
            if lo is None or lo > o:
                fail(f"bad class range ending at {text!r}")
            if not class_negated and any(lo <= x <= o for x in _ILLEGAL_ORDS):
                fail(f"class range {chr(lo)!r}-{text!r} covers characters "
                     "illegal in a JSON string body")
            range_open = False
            prev_ord = None
        else:
            if not class_negated and o in _ILLEGAL_ORDS:
                fail(f"class member {text!r} is illegal in a JSON string body")
            prev_ord = o
        out.append(text)

    while i < n:
        c = pat[i]
        if c == "\\":
            if i + 1 >= n:
                fail("trailing backslash")
            e = pat[i + 1]
            if e in "sntrfv0":
                fail(f"'\\{e}' can emit a raw control character, which is "
                     "illegal inside a JSON string body")
            if e in '"\\':
                fail(f"a literal {e!r} cannot appear raw inside a JSON "
                     "string body (it would break the framing)")
            if in_class:
                if e in "dw":  # shorthand sets; both fully body-legal
                    if range_open:
                        fail(f"class range cannot end in '\\{e}'")
                    prev_ord = None
                    out.append("\\" + e)
                else:
                    member(ord(e), "\\" + e)
            else:
                out.append("\\" + e)
            i += 2
            at_class_start = False
            continue
        if in_class:
            if c == "]":
                if range_open:
                    member(ord("-"), "-")  # trailing '-' is a literal member
                if class_negated:
                    out.append(_NEG_EXTRA)
                out.append("]")
                in_class = False
            elif c == '"':
                fail("'\"' in a character class would break the JSON framing")
            elif c == "^" and at_class_start:
                class_negated = True
                out.append("^")
            elif c == "-" and prev_ord is not None and i + 1 < n and pat[i + 1] != "]":
                range_open = True
                out.append("-")
            elif ord(c) < 0x20:
                if class_negated:
                    out.append(c)  # excluding a control char is fine
                else:
                    fail("raw control character in class")
            else:
                member(ord(c), c)
        else:
            if c == "[":
                in_class, class_negated = True, False
                at_class_start = True
                prev_ord, range_open = None, False
                out.append("[")
                i += 1
                continue
            if c == ".":
                out.append(_LEGAL_DOT)
            elif c == '"':
                fail("a literal '\"' cannot appear raw inside a JSON "
                     "string body (it would break the framing)")
            elif c in "{}$" or c == "^":
                fail(f"uses {c!r}: the DFA regex dialect has no bounded "
                     "repetition or anchors (it would match the character "
                     "literally)")
            elif ord(c) < 0x20:
                fail("raw control character")
            else:
                out.append(c)
        i += 1
        at_class_start = False
    if in_class:
        fail("unterminated character class")
    return "".join(out)


def schema_to_regex(schema: dict) -> str:
    """Compile a JSON-schema subset to a regex over canonical JSON."""
    if not isinstance(schema, dict):
        raise SchemaError(f"schema must be an object, got {type(schema).__name__}")
    unsupported = set(schema) - _SUPPORTED_KEYS - _ANNOTATION_KEYS
    if unsupported:
        raise SchemaError(
            f"unsupported schema keyword(s) {sorted(unsupported)!r} — the "
            "DFA would silently under-constrain (supported: "
            f"{sorted(_SUPPORTED_KEYS)})"
        )
    if schema.get("nullable"):
        # Honored at EVERY level (top-level, array items, object
        # properties): an allowlisted keyword that only worked in one
        # position would silently under-constrain elsewhere.
        inner = schema_to_regex(
            {k: v for k, v in schema.items() if k != "nullable"}
        )
        return f"({inner}|null)"
    if "enum" in schema:
        opts = []
        for v in schema["enum"]:
            if isinstance(v, (dict, list)):
                raise SchemaError("enum values must be JSON scalars")
            opts.append(_lit(json.dumps(v, separators=(",", ":"))))
        if not opts:
            raise SchemaError("empty enum")
        return "(" + "|".join(opts) + ")"
    t = schema.get("type")
    if t == "string":
        if "pattern" in schema:
            # Wrapping group: a top-level alternation must not escape
            # the surrounding quotes ('"yes|no"' parses as '"yes'|'no"').
            return '"(' + _pattern_to_string_body(schema["pattern"]) + ')"'
        return _STRING
    if t == "integer":
        return _INTEGER
    if t == "number":
        return _NUMBER
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        items = schema.get("items")
        if items is None:
            raise SchemaError("array schema needs 'items'")
        item = schema_to_regex(items)
        min_items = int(schema.get("minItems", 0))
        if min_items not in (0, 1):
            raise SchemaError(
                "minItems > 1 needs bounded repetition the DFA regex "
                "dialect does not have; nest required items explicitly"
            )
        non_empty = f"\\[{item}(,{item})*\\]"
        if min_items == 1:
            return non_empty
        return f"(\\[\\]|{non_empty})"
    if t == "object":
        props = schema.get("properties")
        if not props:
            raise SchemaError("object schema needs non-empty 'properties'")
        parts = []
        for name, sub in props.items():
            # nullable is handled by the recursive call (every level).
            parts.append(_lit(json.dumps(name)) + ":" + schema_to_regex(sub))
        return "\\{" + ",".join(parts) + "\\}"
    raise SchemaError(f"unsupported schema type {t!r}")
