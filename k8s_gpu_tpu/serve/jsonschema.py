"""JSON-schema constrained decoding: schema → regex → token DFA.

The reference's serving story delegates structure to prompt engineering
(智能风控解决方案.md:250-266 asks the LLM nicely); modern serving stacks
offer schema-constrained output (OpenAI ``response_format``, vLLM
guided decoding).  Here the schema compiles to a regex over the
CANONICAL JSON serialization, and the existing regex→DFA pipeline
(serve/constrain.py) does the rest — one code path enforces both plain
regex and JSON-schema constraints, banked per request in shared decode
rounds.

Canonical form (what the DFA admits — also what ``json.dumps(...,
separators=(",", ":"))`` emits):

- no whitespace outside strings;
- object properties in DECLARATION order, all present (constrained
  generation must decide the next token greedily — optional/reordered
  keys would make the automaton ambiguous about which key comes next;
  callers mark truly-optional fields as nullable instead);
- strings admit any character except ``"``, ``\\`` and control chars,
  plus ``\\"`` ``\\\\`` ``\\/`` ``\\b`` ``\\f`` ``\\n`` ``\\r`` ``\\t``
  and ``\\uXXXX`` escapes.

Supported schema subset: ``type`` ∈ {string, integer, number, boolean,
null, array, object}, ``enum`` (JSON scalars), ``properties`` (fixed
order), ``items``, ``minItems`` ∈ {0, 1}, string ``pattern`` (embedded
verbatim — the author's regex replaces the default string body).
``maxItems``/``additionalProperties``/``$ref`` are rejected loudly:
a constraint that silently under-constrains is worse than none.
"""

from __future__ import annotations

import json

__all__ = ["schema_to_regex", "SchemaError"]


class SchemaError(ValueError):
    pass


def _lit(text: str) -> str:
    """Regex matching *text* literally (escape every non-alphanumeric —
    constrain.py's parser treats ``\\X`` as literal X for non-alnum)."""
    return "".join(c if c.isalnum() else "\\" + c for c in text)


# One JSON string character: anything but quote/backslash/the full
# control range 0x00-0x1F (json.loads rejects raw controls), or a
# sanctioned escape.  The control characters are embedded RAW in the
# class — constrain.py's class parser takes any character literally.
_CTRL = "".join(chr(i) for i in range(0x20))
_STRING_CHAR = (
    '([^"\\\\' + _CTRL + ']'
    '|\\\\(["\\\\/bfnrt]|u[0-9a-fA-F][0-9a-fA-F][0-9a-fA-F][0-9a-fA-F]))'
)
_STRING = '"' + _STRING_CHAR + '*"'
_INTEGER = "\\-?(0|[1-9][0-9]*)"
_NUMBER = _INTEGER + "(\\.[0-9]+)?([eE][\\-\\+]?[0-9]+)?"


def schema_to_regex(schema: dict) -> str:
    """Compile a JSON-schema subset to a regex over canonical JSON."""
    if not isinstance(schema, dict):
        raise SchemaError(f"schema must be an object, got {type(schema).__name__}")
    for unsupported in ("$ref", "maxItems", "additionalProperties",
                        "anyOf", "oneOf", "allOf"):
        if unsupported in schema:
            raise SchemaError(
                f"unsupported schema keyword {unsupported!r} — the DFA "
                "would silently under-constrain"
            )
    if "enum" in schema:
        opts = []
        for v in schema["enum"]:
            if isinstance(v, (dict, list)):
                raise SchemaError("enum values must be JSON scalars")
            opts.append(_lit(json.dumps(v, separators=(",", ":"))))
        if not opts:
            raise SchemaError("empty enum")
        return "(" + "|".join(opts) + ")"
    t = schema.get("type")
    if t == "string":
        if "pattern" in schema:
            pat = schema["pattern"]
            # The constrain.py dialect has no bounded reps or anchors:
            # an unescaped { } ^ $ would silently match LITERALLY (e.g.
            # [0-9]{3} admits '5{3}') — reject loudly instead.
            esc = False
            for c in pat:
                if esc:
                    esc = False
                elif c == "\\":
                    esc = True
                elif c in "{}^$":
                    raise SchemaError(
                        f"string pattern uses {c!r}: the DFA regex "
                        "dialect has no bounded repetition or anchors "
                        "(it would match the character literally)"
                    )
            # Wrapping group: a top-level alternation must not escape
            # the surrounding quotes ('"yes|no"' parses as '"yes'|'no"').
            return '"(' + pat + ')"'
        return _STRING
    if t == "integer":
        return _INTEGER
    if t == "number":
        return _NUMBER
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        items = schema.get("items")
        if items is None:
            raise SchemaError("array schema needs 'items'")
        item = schema_to_regex(items)
        min_items = int(schema.get("minItems", 0))
        if min_items not in (0, 1):
            raise SchemaError(
                "minItems > 1 needs bounded repetition the DFA regex "
                "dialect does not have; nest required items explicitly"
            )
        non_empty = f"\\[{item}(,{item})*\\]"
        if min_items == 1:
            return non_empty
        return f"(\\[\\]|{non_empty})"
    if t == "object":
        props = schema.get("properties")
        if not props:
            raise SchemaError("object schema needs non-empty 'properties'")
        parts = []
        for name, sub in props.items():
            nullable = isinstance(sub, dict) and sub.get("nullable")
            body = schema_to_regex(sub)
            if nullable:
                body = f"({body}|null)"
            parts.append(_lit(json.dumps(name)) + ":" + body)
        return "\\{" + ",".join(parts) + "\\}"
    raise SchemaError(f"unsupported schema type {t!r}")
