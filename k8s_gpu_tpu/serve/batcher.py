"""Continuous batching: admit requests into a *running* decode.

The serving path the reference delegates to Ollama (智能风控解决方案.md:196)
is rebuilt here TPU-style: one statically-shaped decode program over a fixed
pool of batch slots, with requests admitted at round boundaries instead of
queueing behind each other (the vLLM/Orca scheduling idea, re-done for XLA's
static-shape world):

- the KV cache is allocated once at [L, slots, H, max_seq, Dh]; a request
  occupies one slot row from admission to completion;
- **prefill** runs per request at a bucketed prompt length (O(log max_seq)
  compiles) on a [1, bucket] shape; the row is spliced into the pool cache
  and the slot's decode state is set — all inside one donated jit, so
  admission never blocks the scheduler on a host fetch;
- **decode** runs ``steps_per_round`` steps per dispatch as one on-device
  ``lax.scan`` over ``InferenceEngine.decode_step_multi`` — every row sits
  at its own position, so rows admitted at different times interleave in
  the same program.  Idle rows compute garbage that is never read — the
  price of static shapes, and far cheaper than a retrace;
- **latency hiding**: all decode state (cache, next-token, positions, PRNG
  keys) lives on the device and flows from one dispatch to the next, so
  the scheduler can keep ``pipeline_depth`` rounds in flight and only
  block when *fetching tokens for emission* — the round-trip cost of the
  fetch overlaps the next round's compute (essential on a tunneled TPU,
  where each host<->device trip costs ~100 ms).

Host-side bookkeeping (emitted counts, budgets, EOS) trails the device by
up to ``pipeline_depth`` rounds: a finished request's slot keeps computing
garbage for those rounds before it is noticed and freed.  That is the
standard price of speculation and costs capacity, never correctness.

Sharded serving: pass ``mesh`` — the pool cache is constrained to
P(None, 'dp', 'tp', None, None) and tp-sharded params make every projection
matmul tp-parallel (engine docstring).  ``params`` should already carry the
mesh shardings (shard_params / Trainer.init do this).
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..utils.metrics import global_metrics
from .engine import InferenceEngine, _empty_cache, nucleus_mask

log = logging.getLogger("k8s_gpu_tpu.serve")


def _suffix_bucket(n: int) -> int:
    """Compile bucket for a prefix-cached prompt's suffix: smallest power
    of two >= n (floor 8).  Right-padded, so no decode-room coupling."""
    b = 8
    while b < n:
        b *= 2
    return b


def prompt_bucket(n_tokens: int, max_seq: int) -> int | None:
    """Smallest compile bucket >= n_tokens that still leaves decode room.

    Power-of-two buckets up to max_seq/2 keep the compile count
    O(log max_seq); two fixed long-prompt buckets (3/4·max_seq and
    max_seq-8) extend serving capacity to max_seq-8 tokens.  Returns None
    when the prompt can't fit with at least 8 tokens of decode room."""
    candidates = []
    b = 8
    while b <= max_seq // 2:
        candidates.append(b)
        b *= 2
    candidates.append((3 * max_seq // 4) // 8 * 8)
    candidates.append(max_seq - 8)
    for c in sorted(set(candidates)):
        if c >= n_tokens and c < max_seq:
            return c
    return None


@dataclass
class _Request:
    ids: np.ndarray          # prompt token ids, unpadded
    max_new: int
    temperature: float
    top_p: float
    seed: int
    out: queue.Queue = field(default_factory=queue.Queue)
    slot: int = -1
    aidx: int = 0            # adapter bank index (0 = base model)
    cidx: int = 0            # constraint bank index (0 = unconstrained)
    # (row_cache, last_logits, pos, rope, start): K/V computed by a
    # prefill worker (serve/disagg.py); admission splices, no forward.
    precomputed: tuple | None = None
    # Called once when the row is spliced into the pool (the precomputed
    # K/V's HBM lifetime ends there) — disagg backpressure hook.
    on_admit: object = None
    emitted: int = 0
    # True when the stream ended because the batcher crashed/stopped, not
    # because of EOS/budget — servers map this to a 5xx, not a 200.
    aborted: bool = False


class RequestHandle:
    """Caller's view of an in-flight request: iterate tokens as they
    stream; ``result()`` blocks for the full list.  Tokens are cached, so
    re-iterating (or calling result() after iterating) replays them
    instead of deadlocking on the consumed queue.  Single consuming
    thread at a time."""

    def __init__(self, req: _Request):
        self._req = req
        self._tokens: list[int] = []
        self._lps: list[float] = []
        self._done = False

    def __iter__(self):
        yield from self._tokens  # replay what was already consumed
        while not self._done:
            item = self._req.out.get()
            if item is None:
                self._done = True
                return
            tok, lp = item
            self._tokens.append(tok)
            self._lps.append(lp)
            yield tok

    def result(self) -> list[int]:
        return list(self)

    @property
    def aborted(self) -> bool:
        """True when the stream was cut by batcher shutdown/crash — the
        token list is then a truncation, not a completed generation."""
        return self._req.aborted

    @property
    def logprobs(self) -> list:
        """Per-token log-probabilities, parallel to result().  Complete
        only after the stream finishes (same contract as result());
        requires the batcher's ``logprobs=True`` (zeros otherwise)."""
        return list(self._lps)

    @property
    def last_logprob(self) -> float:
        """Logprob of the most recently consumed token (streaming)."""
        return self._lps[-1] if self._lps else 0.0


class ContinuousBatcher:
    """Fixed-slot continuous batching over one InferenceEngine.

    ``eos_id`` retires a request early; ``slots`` bounds concurrent decode
    width (the static batch of the decode program).  ``top_k`` is global
    (per-request top_k would make the sampling shape request-dependent).
    """

    def __init__(
        self,
        model,
        params,
        *,
        slots: int = 8,
        mesh: Mesh | None = None,
        max_seq: int | None = None,
        eos_id: int = -1,
        steps_per_round: int = 8,
        pipeline_depth: int = 2,
        adapters: dict | None = None,
        constraints=None,
        logprobs: bool = False,
    ):
        """``adapters``: name → (lora_params, LoraConfig) — serves every
        adapter and the base model from ONE decode program; requests pick
        an adapter by name at submit (serve/lora_bank.py).

        ``constraints``: a serve.constrain.ConstraintBank — requests pick
        a pattern by name and decode under its token-DFA mask in the
        same shared rounds.  Constrained serving wants ``eos_id`` set:
        a dead-ended row emits EOS to retire cleanly (otherwise it pads
        until budget)."""
        from .lora_bank import AdapterBank

        self.engine = InferenceEngine(model, max_seq=max_seq, mesh=mesh)
        self.bank = AdapterBank(adapters or {})
        self.cbank = constraints
        if (
            constraints is not None
            and constraints.banked is not None
            and int(constraints.allowed.shape[2]) != model.cfg.vocab_size
        ):
            raise ValueError(
                f"ConstraintBank built over {constraints.allowed.shape[2]} "
                f"token strings but the model's vocab is "
                f"{model.cfg.vocab_size} — compile the bank against this "
                "model's tokenizer"
            )
        self.params = params
        self.slots = slots
        self.eos_id = eos_id
        # Collect per-token logprobs: a full-vocab log_softmax per decode
        # step plus an extra host fetch per round — off by default; the
        # LM server turns it on (its API exposes "logprobs").
        self.collect_logprobs = bool(logprobs)
        self.steps_per_round = max(1, int(steps_per_round))
        self.pipeline_depth = max(1, int(pipeline_depth))
        cfg = self.engine.cfg

        # Device-resident decode state: flows dispatch-to-dispatch without
        # touching the host (the latency-hiding invariant).
        self._dev = {
            "cache": self.engine._constrain_cache(
                _empty_cache(cfg, slots, self.engine.max_seq)
            ),
            "token": jnp.zeros(slots, jnp.int32),
            "pos": jnp.zeros(slots, jnp.int32),
            "rope": jnp.zeros(slots, jnp.int32),
            "start": jnp.zeros(slots, jnp.int32),
            "temps": jnp.zeros(slots, jnp.float32),
            "top_p": jnp.zeros(slots, jnp.float32),
            "keys": jax.vmap(jax.random.PRNGKey)(
                jnp.zeros(slots, jnp.uint32)
            ),
            "aidx": jnp.zeros(slots, jnp.int32),
            "cidx": jnp.zeros(slots, jnp.int32),
            "cstate": jnp.zeros(slots, jnp.int32),
        }
        # Host-side scheduler state.  No position mirror is needed: submit
        # clamps max_new to the decode room, so the budget always retires a
        # slot before its writes could run past max_seq (out-of-bounds
        # scatter writes in a final round's garbage tail are dropped by
        # XLA's scatter semantics and never emitted).
        self._active: list[_Request | None] = [None] * slots
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._dead = False
        # Serializes submit() against the end-of-life drain: either a
        # request lands in _pending before the drain empties it, or submit
        # sees _dead and raises — never an undrained orphan.
        self._lifecycle = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._round_count = 0
        # (round, slot) per emitted token; bounded — it's interleaving
        # observability, not an audit log.
        self._interleave_log: collections.deque = collections.deque(
            maxlen=4096
        )
        self._admit_jit = jax.jit(self._admit_dev, donate_argnums=(1,))
        # use_top_p is static: two compiled round variants, and the
        # common no-nucleus traffic never pays the full-vocab sort.
        self._round_jit = jax.jit(
            self._round_dev, donate_argnums=(1,), static_argnums=(4,)
        )
        self._admit_prefix_jit = jax.jit(
            self._admit_prefix_dev, donate_argnums=(1,)
        )
        self._admit_exact_jit = jax.jit(
            self._admit_exact_dev, donate_argnums=(0,)
        )
        # One wrapper → jit's own executable cache; width comes bucketed
        # from precache_prefix (a fresh jax.jit per call would retrace
        # every time, and unbucketed widths would compile per length).
        self._precache_jit = jax.jit(
            lambda params, cache, padded: self.engine.extend_multi(
                params, cache, padded,
                jnp.asarray([0]), jnp.asarray([0]), jnp.asarray([0]),
            )
        )
        # Prefix cache: prompt-prefix bytes → prefilled device cache row.
        # Entries are read-only after insert; LRU-bounded (each entry owns
        # a full [L,1,H,max_seq,Dh] K/V row — HBM, not host RAM).
        self._prefix: "collections.OrderedDict[bytes, dict]" = (
            collections.OrderedDict()
        )
        self._prefix_cap = 4
        self._prefix_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, name="continuous-batcher", daemon=True
        )

    # -- device programs ---------------------------------------------------
    def _constrained_first(self, logits, temp, key, ctab, cidx,
                           top_p=None):
        """First-token sampling under the constraint bank: mask at the
        start state (0), then advance the DFA by the chosen token."""
        if ctab is None:
            first, key, lp = self._first_token(
                logits, temp, key, top_p=top_p
            )
            return first, key, jnp.int32(0), lp
        mask = ctab["allowed"][cidx, 0]
        dead = self.eos_id if self.eos_id >= 0 else 0
        first, key, lp = self._first_token(
            logits, temp, key, mask, dead, top_p=top_p
        )
        cstate = jnp.where(
            mask.any(), ctab["next"][cidx, 0, first], jnp.int32(0)
        )
        return first, key, cstate, lp

    def _admit_dev(self, params, dev, padded, slot, temp, key, pad, bank,
                   aidx, ctab, cidx, top_p):
        """Prefill one request on a [1, bucket] shape, splice its cache row
        into the pool, seat its decode state at *slot*, and sample the
        first token — all on device (no host fetch on the admit path).
        ``pad`` is traced: prompts of every length within a bucket share
        one compiled program (the O(log max_seq) compile story)."""
        row_cache, last_logits = self.engine.prefill(
            params, padded, pad_left=pad,
            adapters=bank, adapter_idx=aidx[None] if bank else None,
        )
        bucket = padded.shape[1]
        first, key, cstate, lp = self._constrained_first(
            last_logits[0], temp, key, ctab, cidx, top_p=top_p
        )
        return self._seat(
            dev, row_cache, slot, first, bucket, bucket - pad, pad, temp,
            key, aidx, cidx, cstate, top_p,
        ), first, lp

    @staticmethod
    def _first_token(logits, temp, key, mask=None, dead_tok=0,
                     top_p=None):
        """``mask`` [V] bool: constrained sampling — disallowed logits go
        to -inf; a fully-masked row emits ``dead_tok`` (EOS by
        convention) so the scheduler retires it.  Returns
        (token, key, logprob) — the chosen token's log-probability under
        the (masked, unscaled) distribution, the OpenAI-style per-token
        logprob surface."""
        any_ok = None
        if mask is not None:
            any_ok = mask.any()
            logits = jnp.where(mask, logits, -jnp.inf)
        key, sub = jax.random.split(key)
        greedy = jnp.argmax(logits).astype(jnp.int32)
        scaled = logits / jnp.maximum(temp, 1e-6)
        if top_p is not None:
            scaled = nucleus_mask(scaled, top_p)
        sampled = jax.random.categorical(sub, scaled).astype(jnp.int32)
        first = jnp.where(temp > 0, sampled, greedy)
        if mask is not None:
            first = jnp.where(any_ok, first, jnp.int32(dead_tok))
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))[first]
        if mask is not None:
            # all--inf logits → NaN log_softmax; a dead-end row's logprob
            # must stay finite (it would otherwise serialize as invalid
            # JSON in the /generate response).
            lp = jnp.where(any_ok, lp, 0.0)
        return first, key, lp

    def _seat(self, dev, row, slot, first, pos, rope, start, temp, key,
              aidx, cidx=0, cstate=0, top_p=0.0):
        """Splice a prefilled K/V row into the pool and seat a slot's
        decode state — the single owner of the per-slot field list (a
        field added here reaches all three admission paths at once)."""
        cache = jax.tree.map(
            lambda p, r: jax.lax.dynamic_update_slice(
                p, r.astype(p.dtype), (0, slot, 0, 0, 0)
            ),
            dev["cache"], row,
        )
        return {
            "cache": cache,
            "token": dev["token"].at[slot].set(first),
            "pos": dev["pos"].at[slot].set(pos),
            "rope": dev["rope"].at[slot].set(rope),
            "start": dev["start"].at[slot].set(start),
            "temps": dev["temps"].at[slot].set(temp),
            "top_p": dev["top_p"].at[slot].set(top_p),
            "keys": dev["keys"].at[slot].set(key),
            "aidx": dev["aidx"].at[slot].set(aidx),
            "cidx": dev["cidx"].at[slot].set(cidx),
            "cstate": dev["cstate"].at[slot].set(cstate),
        }

    def _admit_prefix_dev(self, params, dev, base, suffix, n_real, slot,
                          temp, key, base_pos, ctab, cidx, top_p):
        """Admit on top of a cached prefix: extend the prefix's K/V row
        with the RIGHT-padded suffix (one extend_multi, width = suffix
        bucket) instead of prefilling the whole prompt.

        Right-padding is the safety trick: pad slots write garbage K/V at
        positions past the live length, which the decode masks
        (t <= pos) never attend and the decode loop overwrites in order —
        left-padding would instead clobber the real prefix tail."""
        row, logits = self.engine.extend_multi(
            params, base, suffix,
            jnp.asarray([base_pos]), jnp.asarray([base_pos]),
            jnp.asarray([0]),
        )
        first, key, cstate, lp = self._constrained_first(
            logits[0, n_real - 1], temp, key, ctab, cidx, top_p=top_p
        )
        pos = base_pos + n_real
        return self._seat(
            dev, row, slot, first, pos, pos, 0, temp, key, 0, cidx, cstate,
            top_p,
        ), first, lp

    def _admit_exact_dev(self, dev, base, base_logits, pos, rope, start,
                         slot, temp, key, aidx, ctab, cidx, top_p):
        """Seat a row whose K/V were computed elsewhere: splice + sample,
        no model forward on THIS program.  Two callers: a prompt that IS
        a cached prefix (pos=rope=n, start=0), and disaggregated-prefill
        admission (serve/disagg.py — a prefill worker hands over the row
        with its bucketing geometry intact)."""
        first, key, cstate, lp = self._constrained_first(
            base_logits[0], temp, key, ctab, cidx, top_p=top_p
        )
        return self._seat(
            dev, base, slot, first, pos, rope, start, temp, key, aidx,
            cidx, cstate, top_p,
        ), first, lp

    def _round_dev(self, params, dev, bank, ctab, use_top_p):
        """One scheduler round: ``steps_per_round`` batched decode steps as
        a single on-device scan.  Returns (new_dev, tokens [T, B]).  Rows
        that hit EOS/budget mid-round produce garbage tails the host drops
        when it retires the slot."""
        temps = dev["temps"]
        kv_start = dev["start"]

        def one(carry, _):
            cache, token, pos, rope, keys, cstate = carry
            cache, logits = self.engine.decode_step_multi(
                params, cache, token, pos, rope, kv_start,
                adapters=bank,
                adapter_idx=dev["aidx"] if bank else None,
            )
            if ctab is not None:
                mask = ctab["allowed"][dev["cidx"], cstate]   # [B, V]
                logits = jnp.where(mask, logits, -jnp.inf)
                any_ok = mask.any(-1)
            split = jax.vmap(jax.random.split)(keys)     # [B, 2, 2]
            new_keys, subs = split[:, 0], split[:, 1]
            greedy = jnp.argmax(logits, axis=-1)
            scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
            if use_top_p:
                scaled = nucleus_mask(scaled, dev["top_p"])
            sampled = jax.vmap(
                lambda k, l: jax.random.categorical(k, l)
            )(subs, scaled)
            nxt = jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
            if ctab is not None:
                # Dead end: emit EOS so the scheduler retires the row.
                dead = self.eos_id if self.eos_id >= 0 else 0
                nxt = jnp.where(any_ok, nxt, jnp.int32(dead))
                cstate = jnp.where(
                    any_ok, ctab["next"][dev["cidx"], cstate, nxt], cstate
                )
            if self.collect_logprobs:
                lp = jax.nn.log_softmax(
                    logits.astype(jnp.float32), axis=-1
                )[jnp.arange(nxt.shape[0]), nxt]
                if ctab is not None:
                    lp = jnp.where(any_ok, lp, 0.0)  # dead end: finite
            else:
                lp = jnp.zeros(nxt.shape[0], jnp.float32)
            return (cache, nxt, pos + 1, rope + 1, new_keys, cstate), (
                nxt, lp,
            )

        (cache, token, pos, rope, keys, cstate), (toks, lps) = jax.lax.scan(
            one,
            (dev["cache"], dev["token"], dev["pos"], dev["rope"],
             dev["keys"], dev["cstate"]),
            length=self.steps_per_round,
        )
        return {
            "cache": cache, "token": token, "pos": pos, "rope": rope,
            "start": kv_start, "temps": temps, "top_p": dev["top_p"],
            "keys": keys,
            "aidx": dev["aidx"], "cidx": dev["cidx"], "cstate": cstate,
        }, (toks, lps)

    # -- public surface ----------------------------------------------------
    def start(self) -> "ContinuousBatcher":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)

    def submit(
        self,
        ids,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_p: float = 0.0,
        seed: int = 0,
        adapter: str | None = None,
        constraint: str | None = None,
    ) -> RequestHandle:
        """Queue a request; returns a handle streaming generated ids.
        Raises ValueError when the prompt cannot fit, KeyError for an
        unknown ``adapter``/``constraint`` name."""
        aidx = self.bank.index(adapter)
        cidx = self._constraint_index(constraint)
        ids = np.asarray(ids, np.int32).ravel()
        bucket = prompt_bucket(int(ids.size), self.engine.max_seq)
        if bucket is None:
            raise ValueError(
                f"prompt too long ({ids.size} tokens, "
                f"max {self.engine.max_seq - 8})"
            )
        room = self.engine.max_seq - bucket
        req = _Request(
            ids=ids,
            max_new=max(1, min(int(max_new_tokens), room)),
            temperature=float(temperature),
            top_p=float(top_p),
            seed=int(seed),
            aidx=aidx,
            cidx=cidx,
        )
        with self._lifecycle:
            if self._dead:
                raise RuntimeError(
                    "batcher scheduler is stopped; restart the server"
                )
            self._pending.put(req)
        self._wake.set()
        return RequestHandle(req)

    def submit_precomputed(
        self, row_cache, last_logits, n_tokens: int, pad: int,
        max_new_tokens: int = 32, temperature: float = 0.0,
        top_p: float = 0.0, seed: int = 0,
        adapter: str | None = None, on_admit=None,
        constraint: str | None = None,
    ) -> RequestHandle:
        """Admit a request whose prefill ran elsewhere (serve/disagg.py):
        ``row_cache`` is a [L, 1, H, max_seq, Dh] K/V tree computed at a
        [1, n_tokens] bucket with ``pad`` leading pad slots;
        ``last_logits`` [1, V] are the logits at the final prompt
        position.  The decode side only splices and samples."""
        aidx = self.bank.index(adapter)
        cidx = self._constraint_index(constraint)
        room = self.engine.max_seq - n_tokens
        if room < 1:
            raise ValueError("precomputed prompt fills max_seq")
        # Validate shapes HERE, in the caller's thread: a mis-shaped tree
        # would otherwise explode inside the scheduler loop and take the
        # whole batcher (and every tenant's stream) down with it.
        cfg = self.engine.cfg
        want = (cfg.n_layers, 1, cfg.kv_heads, self.engine.max_seq,
                cfg.d_head)
        for leaf in jax.tree.leaves(row_cache):
            if tuple(leaf.shape) != want:
                raise ValueError(
                    f"row_cache leaf shape {tuple(leaf.shape)} != {want} "
                    "(was it prefilled by an engine with a different "
                    "max_seq?)"
                )
        if tuple(last_logits.shape) != (1, cfg.vocab_size):
            raise ValueError(
                f"last_logits shape {tuple(last_logits.shape)} != "
                f"(1, {cfg.vocab_size})"
            )
        req = _Request(
            ids=np.zeros(0, np.int32),
            max_new=max(1, min(int(max_new_tokens), room)),
            temperature=float(temperature),
            top_p=float(top_p),
            seed=int(seed),
            aidx=aidx,
            cidx=cidx,
            precomputed=(
                row_cache, last_logits, n_tokens, n_tokens - pad, pad,
            ),
            on_admit=on_admit,
        )
        with self._lifecycle:
            if self._dead:
                raise RuntimeError(
                    "batcher scheduler is stopped; restart the server"
                )
            self._pending.put(req)
        self._wake.set()
        return RequestHandle(req)

    def precache_prefix(self, ids) -> None:
        """Prefill *ids* once and keep the K/V row for reuse: any later
        submit whose prompt starts with *ids* only computes its suffix
        (one extend over the suffix bucket), and a prompt that IS a
        cached prefix admits with no model forward at all.  The classic
        use is a shared system prompt / few-shot preamble.

        Exact-shape prefill: one compile per distinct prefix length —
        prefixes are few and long-lived, so that trade is right (bucketed
        prefixes would burn cache slots on pad garbage).  LRU-bounded at
        4 entries; each entry owns a full K/V row in HBM."""
        if self.engine.cfg.moe:
            # Capacity-capped Switch dispatch couples every token in the
            # dispatch group: a chunked (prefix + suffix) prefill computes
            # caps over different group sizes than the one-shot prefill
            # and silently drops different tokens — chunking cannot match
            # the oracle, so refuse rather than serve diverging streams.
            raise ValueError(
                "prefix caching is unavailable for MoE models: "
                "capacity-capped expert dispatch makes chunked prefill "
                "diverge from the one-shot path"
            )
        ids = np.asarray(ids, np.int32).ravel()
        if ids.size == 0 or ids.size > self.engine.max_seq - 8:
            raise ValueError(f"prefix length {ids.size} unusable")
        # Bucketed width via extend_multi (RIGHT-padded, logits gathered
        # at the last real position): one compile per power-of-2 bucket.
        # Exact-shape prefill would hand the unauthenticated /precache
        # endpoint an unbounded per-length XLA compile cache.  Pad K/V
        # garbage lands at positions >= n — the suffix/decode writes
        # overwrite it in order and position masks never attend it.
        n = int(ids.size)
        w = min(_suffix_bucket(n), self.engine.max_seq)
        padded = jnp.zeros((1, w), jnp.int32).at[0, :n].set(jnp.asarray(ids))
        cache, all_logits = self._precache_jit(
            self.params, _empty_cache(self.engine.cfg, 1, self.engine.max_seq),
            padded,
        )
        logits = all_logits[:, n - 1]
        with self._prefix_lock:
            self._prefix[ids.tobytes()] = {
                "cache": cache, "logits": logits, "n": int(ids.size),
            }
            self._prefix.move_to_end(ids.tobytes())
            while len(self._prefix) > self._prefix_cap:
                self._prefix.popitem(last=False)

    def _match_prefix(self, ids: np.ndarray):
        """Longest cached prefix of *ids* (LRU-touched), or None."""
        best_key = None
        best = None
        with self._prefix_lock:
            for key, entry in self._prefix.items():
                n = entry["n"]
                if (
                    n <= ids.size
                    and (best is None or n > best["n"])
                    and ids[:n].tobytes() == key
                ):
                    best, best_key = entry, key
            if best_key is not None:
                self._prefix.move_to_end(best_key)
        return best

    def _constraint_index(self, name: str | None) -> int:
        if name is None:
            return 0
        if self.cbank is None:
            raise KeyError(
                f"unknown constraint {name!r}; no ConstraintBank configured"
            )
        return self.cbank.index(name)

    @property
    def steps_taken(self) -> int:
        return self._round_count

    @property
    def interleave_log(self) -> list[tuple[int, int]]:
        """(round, slot) per emitted token — lets tests prove two requests
        shared the same decode rounds."""
        return list(self._interleave_log)

    # -- scheduler ---------------------------------------------------------
    def _free_slot(self) -> int:
        for i, r in enumerate(self._active):
            if r is None:
                return i
        return -1

    def _dispatch_admit(self, req: _Request, slot: int) -> tuple:
        ctab = self.cbank.banked if self.cbank else None
        if req.precomputed is not None:
            row, logits, pos, rope, start = req.precomputed
            self._dev, first, lp = self._admit_exact_jit(
                self._dev, row, logits, jnp.int32(pos), jnp.int32(rope),
                jnp.int32(start), jnp.int32(slot),
                jnp.float32(req.temperature), jax.random.PRNGKey(req.seed),
                jnp.int32(req.aidx), ctab, jnp.int32(req.cidx),
                jnp.float32(req.top_p),
            )
            # Drop the row reference (it lives on in the pool cache) and
            # signal the prefill pool that its HBM is reclaimable.
            req.precomputed = None
            if req.on_admit is not None:
                req.on_admit()
            return self._seated(req, slot, first, lp, "precomputed")
        # Prefix-cache entries hold BASE-model K/V; an adapter row must
        # cold-prefill (its prefix K/V differ) — correctness over reuse.
        entry = self._match_prefix(req.ids) if req.aidx == 0 else None
        if entry is not None and entry["n"] == req.ids.size:
            # The prompt IS a cached prefix: splice + sample, zero forward.
            self._dev, first, lp = self._admit_exact_jit(
                self._dev, entry["cache"], entry["logits"],
                jnp.int32(entry["n"]), jnp.int32(entry["n"]), jnp.int32(0),
                jnp.int32(slot),
                jnp.float32(req.temperature), jax.random.PRNGKey(req.seed),
                jnp.int32(0), ctab, jnp.int32(req.cidx),
                jnp.float32(req.top_p),
            )
        elif entry is not None and (
            entry["n"] + _suffix_bucket(req.ids.size - entry["n"])
            <= self.engine.max_seq
        ):
            p = entry["n"]
            n_real = int(req.ids.size) - p
            w = _suffix_bucket(n_real)
            suffix = jnp.zeros((1, w), jnp.int32).at[0, :n_real].set(
                jnp.asarray(req.ids[p:])
            )
            self._dev, first, lp = self._admit_prefix_jit(
                self.params, self._dev, entry["cache"], suffix,
                jnp.int32(n_real), jnp.int32(slot),
                jnp.float32(req.temperature),
                jax.random.PRNGKey(req.seed), jnp.int32(p),
                ctab, jnp.int32(req.cidx), jnp.float32(req.top_p),
            )
        else:
            bucket = prompt_bucket(int(req.ids.size), self.engine.max_seq)
            pad = bucket - int(req.ids.size)
            padded = jnp.zeros((1, bucket), jnp.int32).at[0, pad:].set(
                jnp.asarray(req.ids)
            )
            self._dev, first, lp = self._admit_jit(
                self.params, self._dev, padded, jnp.int32(slot),
                jnp.float32(req.temperature),
                jax.random.PRNGKey(req.seed), jnp.int32(pad),
                self.bank.banked, jnp.int32(req.aidx),
                ctab, jnp.int32(req.cidx), jnp.float32(req.top_p),
            )
        path = (
            "prefix_exact" if entry is not None and entry["n"] == req.ids.size
            else "prefix_suffix" if entry is not None
            else "cold"
        )
        return self._seated(req, slot, first, lp, path)

    def _seated(self, req: _Request, slot: int, first, lp,
                path: str) -> tuple:
        """Common tail of every admission: bookkeeping + C32 counters
        (admissions by path, live-slot gauge, pending-queue gauge)."""
        req.slot = slot
        self._active[slot] = req
        global_metrics.inc("serve_admissions_total", path=path)
        global_metrics.set_gauge(
            "serve_slots_active",
            float(sum(r is not None for r in self._active)),
        )
        global_metrics.set_gauge(
            "serve_pending_requests", float(self._pending.qsize())
        )
        return ("admit", req, first, lp)

    def _dispatch_round(self) -> tuple:
        # Snapshot (slot, request) identity: by the time this round is
        # processed the slot may have been retired AND re-admitted to a new
        # request, whose stream must not receive this round's tokens.
        live = [(i, r) for i, r in enumerate(self._active) if r is not None]
        use_top_p = any(
            r is not None and 0.0 < r.top_p < 1.0 for r in self._active
        )
        self._dev, (toks, lps) = self._round_jit(
            self.params, self._dev, self.bank.banked,
            self.cbank.banked if self.cbank else None,
            use_top_p,
        )
        self._round_count += 1
        return ("round", self._round_count, live, toks, lps)

    def _emit(self, req: _Request, tok: int, round_id: int,
              lp: float = 0.0) -> None:
        req.emitted += 1
        self._interleave_log.append((round_id, req.slot))
        # One queue item carries both — the handle collects logprobs on
        # ITS side of the thread boundary (no per-token list snapshots).
        req.out.put((int(tok), float(lp)))

    def _retire(self, slot: int) -> None:
        req = self._active[slot]
        if req is not None:
            req.out.put(None)  # completion sentinel
            global_metrics.inc("serve_completions_total")
            global_metrics.observe(
                "serve_generated_tokens", float(req.emitted)
            )
        self._active[slot] = None
        global_metrics.set_gauge(
            "serve_slots_active",
            float(sum(r is not None for r in self._active)),
        )

    def _process(self, item: tuple) -> None:
        """Consume one in-flight item — the only place the scheduler blocks
        on the device."""
        if item[0] == "admit":
            _, req, first_dev, lp_dev = item
            if self._active[req.slot] is not req:
                return  # already retired
            first = int(np.asarray(first_dev))
            hit_eos = self.eos_id >= 0 and first == self.eos_id
            if not hit_eos:
                self._emit(req, first, self._round_count,
                           float(np.asarray(lp_dev)))
            if hit_eos or req.emitted >= req.max_new:
                self._retire(req.slot)
            return
        _, round_id, live, toks_dev, lps_dev = item
        toks = np.asarray(toks_dev)  # [T, B] — the blocking fetch
        lps = (np.asarray(lps_dev) if self.collect_logprobs
               else np.zeros_like(toks, np.float32))
        n_steps = toks.shape[0]
        for i, req in live:
            if self._active[i] is not req:
                continue  # retired (or slot re-admitted) mid-flight
            done = False
            for t in range(n_steps):
                tok = int(toks[t, i])
                if self.eos_id >= 0 and tok == self.eos_id:
                    done = True
                    break
                self._emit(req, tok, round_id, float(lps[t, i]))
                if req.emitted >= req.max_new:
                    done = True
                    break
            if done:
                self._retire(i)

    def _loop(self) -> None:
        inflight: collections.deque = collections.deque()
        try:
            while not self._stop.is_set():
                any_active = any(r is not None for r in self._active)
                if not any_active and self._pending.empty() and not inflight:
                    self._wake.wait(timeout=0.1)
                    self._wake.clear()
                    continue
                # Admission: fill free slots from the pending queue.  When
                # all slots are busy, catching up on in-flight work below
                # is what eventually frees one.
                while True:
                    slot = self._free_slot()
                    if slot < 0:
                        break
                    try:
                        req = self._pending.get_nowait()
                    except queue.Empty:
                        break
                    try:
                        inflight.append(self._dispatch_admit(req, slot))
                    except BaseException:
                        # The popped request is in neither _pending nor
                        # _active yet — the crash drain below would miss
                        # it and its caller would block forever.
                        req.aborted = True
                        if req.on_admit is not None:
                            req.on_admit()
                        req.out.put(None)
                        raise
                # Keep the device busy: dispatch the next round before
                # fetching results of previous ones.
                if any(r is not None for r in self._active):
                    inflight.append(self._dispatch_round())
                # Catch up to the pipeline depth (or fully, when idle).
                while inflight and (
                    len(inflight) > self.pipeline_depth
                    or not any(r is not None for r in self._active)
                ):
                    self._process(inflight.popleft())
        except Exception:
            log.exception("batcher scheduler died; draining requests")
        finally:
            # Drain on ANY exit — crashed/stopped schedulers must not
            # leave callers blocked on .result() forever, and drained
            # requests are marked aborted so servers report 5xx, not a
            # silently truncated 200.
            with self._lifecycle:
                self._dead = True
                for r in self._active:
                    if r is not None:
                        r.aborted = True
                        r.out.put(None)
                while True:
                    try:
                        r = self._pending.get_nowait()
                    except queue.Empty:
                        break
                    r.aborted = True
                    # A drained precomputed request will never be seated:
                    # fire its admit hook so the prefill pool's inflight
                    # semaphore doesn't leak a permit.
                    if r.on_admit is not None:
                        r.on_admit()
                    r.out.put(None)
