"""Continuous batching: admit requests into a *running* decode.

The serving path the reference delegates to Ollama (智能风控解决方案.md:196)
is rebuilt here TPU-style: one statically-shaped decode program over a fixed
pool of batch slots, with requests admitted at round boundaries instead of
queueing behind each other (the vLLM/Orca scheduling idea, re-done for XLA's
static-shape world):

- the KV cache is allocated once at [L, slots, H, max_seq, Dh]; a request
  occupies one slot row from admission to completion;
- **prefill** runs per request at a bucketed prompt length (O(log max_seq)
  compiles) on a [1, bucket] shape; the row is spliced into the pool cache
  and the slot's decode state is set — all inside one donated jit, so
  admission never blocks the scheduler on a host fetch;
- **decode** runs ``steps_per_round`` steps per dispatch as one on-device
  ``lax.scan`` over ``InferenceEngine.decode_step_multi`` — every row sits
  at its own position, so rows admitted at different times interleave in
  the same program.  Idle rows compute garbage that is never read — the
  price of static shapes, and far cheaper than a retrace;
- **latency hiding**: all decode state (cache, next-token, positions, PRNG
  keys) lives on the device and flows from one dispatch to the next, so
  the scheduler can keep ``pipeline_depth`` rounds in flight and only
  block when *fetching tokens for emission* — the round-trip cost of the
  fetch overlaps the next round's compute (essential on a tunneled TPU,
  where each host<->device trip costs ~100 ms).

Host-side bookkeeping (emitted counts, budgets, EOS) trails the device by
up to ``pipeline_depth`` rounds: a finished request's slot keeps computing
garbage for those rounds before it is noticed and freed.  That is the
standard price of speculation and costs capacity, never correctness.

Sharded serving: pass ``mesh`` — the pool cache is constrained to
P(None, 'dp', 'tp', None, None) and tp-sharded params make every projection
matmul tp-parallel (engine docstring).  ``params`` should already carry the
mesh shardings (shard_params / Trainer.init do this).
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..utils.compat import install_compile_telemetry, serialize_xla_compiles
from ..utils.metrics import MetricsRegistry, global_metrics
from ..utils.profiler import PhaseProfiler
from .allocator import AllocatorMixin
from .engine import InferenceEngine, _empty_cache, _empty_cache_paged
from .executor import ExecutorMixin, ngram_propose
from .journal import RequestJournal
from .kv_blocks import BlockPool
from .scheduler import (
    Overloaded,
    RequestHandle,
    SchedulerMixin,
    _Request,
    _suffix_bucket,
    prompt_bucket,
)

__all__ = [
    "ContinuousBatcher", "Overloaded", "RequestHandle",
    "ngram_propose", "prompt_bucket",
]

log = logging.getLogger("k8s_gpu_tpu.serve")


def _param_bytes(tree) -> int:
    """Total param-tree BYTES — the relative-decode-cost proxy
    speculative round sizing uses.  Decode is HBM-bound: every weight
    byte streams once per step, so cost scales with bytes, not element
    count — an int8-quantized draft against a bf16 target really does
    cost half per element, and sizing by elements would overstate the
    draft/target ratio 2x and undersize spec_rounds."""
    return sum(int(x.size) * np.dtype(getattr(x, "dtype", np.float32)).itemsize
               for x in jax.tree.leaves(tree))


class ContinuousBatcher(SchedulerMixin, AllocatorMixin, ExecutorMixin):
    """Fixed-slot continuous batching over one InferenceEngine.

    ``eos_id`` retires a request early; ``slots`` bounds concurrent decode
    width (the static batch of the decode program).  ``top_k`` is global
    (per-request top_k would make the sampling shape request-dependent).
    """

    # Lock contract (graftcheck lockcheck + utils.faults
    # guard_declared).  Everything else host-side is single-owner
    # scheduler-thread state: ``_pending`` is a thread-safe Queue whose
    # maxsize IS the admission bound, and ``_lifecycle`` exists for
    # exactly one shared flag — submit-vs-drain on ``_dead`` (either a
    # request lands before the drain empties the queue, or submit sees
    # _dead and raises).  ``_prefix`` is the dense prefill cache shared
    # between precache callers and the scheduler.
    _GUARDED_BY = {
        "_lifecycle": ("_dead",),
        "_prefix_lock": ("_prefix",),
    }

    def __init__(
        self,
        model,
        params,
        *,
        slots: int = 8,
        mesh: Mesh | None = None,
        max_seq: int | None = None,
        eos_id: int = -1,
        steps_per_round: int = 8,
        pipeline_depth: int = 2,
        adapters: dict | None = None,
        constraints=None,
        logprobs: bool = False,
        draft=None,
        spec_k: int = 4,
        draft_int8: bool = False,
        kv_quant: bool = False,
        attn_impl: str | None = None,
        paged_blocks: int = 0,
        page_size: int = 64,
        prefix_cache: bool = True,
        max_pending: int = 0,
        metrics: MetricsRegistry | None = None,
        journal: RequestJournal | None = None,
        profiler: PhaseProfiler | None = None,
        role: str = "both",
    ):
        """``metrics``: the registry this batcher's serve-plane
        telemetry lands in (default: the process-global one).  A
        multi-replica process gives each batcher its OWN registry so
        per-replica gauges don't clobber each other — the federation
        collector (utils/federation.py) then scrapes each replica's
        exposition and relabels with ``replica=``.  ``journal``: the
        per-request lifecycle ring (serve/journal.py); one is created
        when not supplied.

        ``max_pending`` > 0 bounds the unadmitted-request queue:
        ``submit`` raises ``Overloaded`` at the bound (admission control —
        the server's 429 path) instead of queueing unboundedly.  0 keeps
        the historical unbounded behavior for direct embedders.

        ``adapters``: name → (lora_params, LoraConfig) — serves every
        adapter and the base model from ONE decode program; requests pick
        an adapter by name at submit (serve/lora_bank.py).

        ``constraints``: a serve.constrain.ConstraintBank — requests pick
        a pattern by name and decode under its token-DFA mask in the
        same shared rounds.  Constrained serving wants ``eos_id`` set:
        a dead-ended row emits EOS to retire cleanly (otherwise it pads
        until budget).

        ``draft``: ``(draft_model, draft_params)`` — turns every decode
        round into a *speculative* round: ``spec_k`` cheap draft steps
        propose a window per slot and one target ``extend_multi`` verifies
        all slots' windows at their own positions (engine.py:extend_multi).
        Greedy rows stay bit-exact (accepted tokens ARE target argmaxes);
        sampled rows run per-row Leviathan rejection sampling, exact in
        distribution for any draft.  The draft maintains its own KV pool,
        one position behind the target (speculative.py module docstring —
        same prev/cur bookkeeping, per-slot).  Cold admissions prefill the
        draft alongside the target; prefix-cache and disaggregated
        admissions seat a zeroed draft row — the draft then re-warms from
        the decode stream, costing acceptance, never correctness.
        Incompatible with ``constraints`` (the DFA advance is sequential
        in the accepted prefix, which is unknown until after the verify —
        masking draft proposals by a state that far ahead has no
        well-defined trace).

        ``draft_int8``: quantize the neural draft's weights int8
        (serve/quant.py) and run its matmuls as true int8 × int8
        (engine int8_compute) — the draft streams half the bytes and
        computes at integer width, so every speculative round's drafting
        half gets cheaper.  Draft quantization error can only lower the
        acceptance rate, never correctness: the target verify is exact
        for ANY draft distribution.  ``_param_bytes`` sees the quantized
        tree, so the byte-ratio round sizing adjusts automatically.

        ``kv_quant``: int8 pool KV cache with per-(head, position) scales
        (engine.__init__) — ~1.9× the slots at fixed HBM.  The draft's
        (much smaller) cache stays at model dtype.

        ``attn_impl``: paged attention read implementation for the
        TARGET engine — "gather" (default) or "paged_kernel" (the fused
        Pallas kernel, ops/paged_attention.py).  Ignored for dense
        pools; on non-TPU backends the kernel runs in the Pallas
        interpreter (parity, not speed).

        ``paged_blocks`` > 0: paged KV — the pool is ``paged_blocks``
        physical blocks of ``page_size`` positions shared by all slots
        through page tables, so a request's cache bytes scale with the
        tokens it USES instead of reserving slots×max_seq (VERDICT r4
        weak #6).  Composes with ``kv_quant`` (int8 blocks), with
        speculative drafting (the verify extend runs directly on the
        paged pool; the neural draft's own small cache stays dense),
        with disaggregated prefill (the handed-over dense row splices
        into blocks page by page), and with prefix caching — which in
        paged mode is BLOCK-granular and automatic: page-aligned prompt
        chunks are chain-hashed and full prompt blocks registered in a
        refcounted content cache (serve/kv_blocks.py), so N requests
        sharing a system prompt map their page tables to the SAME
        physical blocks and only compute their suffixes; a partial tail
        block is recomputed into a private block (copy-on-write), and
        eviction is LRU over refcount-0 blocks.  Admission allocates
        fresh blocks for the unshared tail and defers the request under
        block pressure; retirement releases references (refcount-0
        registered blocks stay cached until evicted).  MoE models and
        adapter (LoRA) requests don't share blocks — MoE chunked
        prefill diverges from the one-shot oracle and adapter K/V
        differ from base-model K/V — but both still serve on the paged
        pool via the dense-row splice path."""
        from .lora_bank import AdapterBank

        self.engine = InferenceEngine(
            model, max_seq=max_seq, mesh=mesh, kv_quant=kv_quant,
            attn_impl=attn_impl,
        )
        self.bank = AdapterBank(adapters or {})
        self.cbank = constraints
        if (
            constraints is not None
            and constraints.banked is not None
            and int(constraints.allowed.shape[2]) != model.cfg.vocab_size
        ):
            raise ValueError(
                f"ConstraintBank built over {constraints.allowed.shape[2]} "
                f"token strings but the model's vocab is "
                f"{model.cfg.vocab_size} — compile the bank against this "
                "model's tokenizer"
            )
        if constraints is not None and constraints.banked is not None and eos_id < 0:
            # Without an EOS a dead-ended constrained row pads token 0 as
            # if generated until budget; the CLI already guards this —
            # enforce it at the constructor so every embedder does too.
            raise ValueError(
                "ContinuousBatcher with a ConstraintBank requires eos_id >= 0: "
                "a dead-ended constrained row retires by emitting EOS"
            )
        self.draft_engine = None
        self.draft_params = None
        self.spec_mode = None
        self.spec_k = max(1, int(spec_k))
        if draft is not None:
            if constraints is not None and constraints.banked is not None:
                raise ValueError(
                    "speculative decoding and a ConstraintBank cannot be "
                    "combined: the DFA advances token-by-token through the "
                    "ACCEPTED prefix, which only exists after the verify"
                )
            if isinstance(draft, str):
                if draft != "ngram":
                    raise ValueError(
                        f"unknown draft mode {draft!r}: pass 'ngram' or a "
                        "(draft_model, draft_params) pair"
                    )
                # Prompt-lookup drafting: proposals come from the row's
                # own token history (ngram_propose) — no draft model, no
                # draft KV pool; a spec round costs ONE K+1-wide target
                # forward, barely more than a plain decode step on the
                # MXU, so any measured acceptance is pure speedup.
                self.spec_mode = "ngram"
            else:
                draft_model, draft_params = draft
                if draft_model.cfg.vocab_size != model.cfg.vocab_size:
                    raise ValueError(
                        "draft and target must share a vocabulary "
                        f"({draft_model.cfg.vocab_size} != "
                        f"{model.cfg.vocab_size})"
                    )
                # Same max_seq: the draft pool mirrors the target pool's
                # geometry so positions line up row-for-row.
                self.draft_engine = InferenceEngine(
                    draft_model, max_seq=self.engine.max_seq, mesh=mesh,
                    int8_compute=draft_int8,
                )
                if draft_int8:
                    from .speculative import int8_draft

                    draft_params = int8_draft(draft_params)
                self.draft_params = draft_params
                self.spec_mode = "neural"
        self.params = params
        self.slots = slots
        self.eos_id = eos_id
        self.metrics = metrics if metrics is not None else global_metrics
        self.journal = journal if journal is not None else RequestJournal()
        # Continuous phase attribution (ISSUE 9): scheduler-thread seams
        # recorded as disjoint self-time phases — admission, paged_plan,
        # prefill_dispatch, decode_dispatch, decode_consume, spec_draft,
        # spec_verify, retire — exported as serve_phase_seconds{phase}
        # histograms + serve_phase_share{phase} gauges so "where does a
        # decode round spend its time" is a number on /metrics, not a
        # one-shot offline study (utils/profiler.py).
        self.profiler = (
            profiler if profiler is not None
            else PhaseProfiler(plane="serve", registry=self.metrics)
        )
        # Steady-state recompiles are the silent killer the zero-
        # recompile CI test only catches offline; xla_compiles_total /
        # xla_compile_seconds make them a live rate CompileStorm pages on.
        install_compile_telemetry()
        # Collect per-token logprobs: a full-vocab log_softmax per decode
        # step plus an extra host fetch per round — off by default; the
        # LM server turns it on (its API exposes "logprobs").
        self.collect_logprobs = bool(logprobs)
        # Disaggregated serving role (ISSUE 20).  "prefill": this
        # batcher only admits — submit clamps every budget to the one
        # admission-sampled token (discarded by the handover; the
        # decode side recomputes it from the imported chain) and the
        # executor refuses decode-round dispatch outright.  "decode"
        # and "both" behave identically at this layer; the gateway's
        # classifier is what keeps long prefills off a decode worker.
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"unknown batcher role {role!r}")
        self.role = role
        self.steps_per_round = max(1, int(steps_per_round))
        self.pipeline_depth = max(1, int(pipeline_depth))
        cfg = self.engine.cfg

        # Paged-KV bookkeeping (host side: the allocator and the page
        # tables; the device sees the tables as a per-dispatch operand,
        # so a retired slot's mapping is corrected at the NEXT dispatch
        # and device dispatch-order FIFO makes immediate block reuse
        # safe — any stale-mapping round was dispatched before the
        # reusing admission and therefore completes before it).
        self.page_size = max(8, int(page_size))
        self.paged = int(paged_blocks) > 0
        if self.paged:
            if self.engine.max_seq % self.page_size:
                raise ValueError(
                    f"max_seq {self.engine.max_seq} must be a multiple "
                    f"of page_size {self.page_size}"
                )
            self._max_pages = self.engine.max_seq // self.page_size
            if int(paged_blocks) < 1 + self._max_pages:
                raise ValueError(
                    f"paged_blocks={paged_blocks} cannot hold one "
                    f"max-length request plus the trash block "
                    f"(need >= {1 + self._max_pages})"
                )
            self.paged_blocks = int(paged_blocks)
            # Block 0 is the trash block: retired slots' tables point at
            # it so in-flight garbage writes land somewhere harmless.
            # The pool owns refcounts, the content-hash table, and LRU
            # eviction of refcount-0 cached blocks (serve/kv_blocks.py).
            self._pool = BlockPool(self.paged_blocks, self.page_size)
            self._pages = np.zeros(
                (slots, self._max_pages), np.int32
            )
            self._overflow: collections.deque = collections.deque()
            # Block-granular prefix sharing: base-model, non-MoE only
            # (MoE chunked prefill diverges from the one-shot oracle —
            # same refusal as the dense prefix cache; adapter requests
            # are excluded per-request, their K/V differ from base).
            self._paged_share = prefix_cache and not self.engine.cfg.moe

        # Device-resident decode state: flows dispatch-to-dispatch without
        # touching the host (the latency-hiding invariant).
        self._dev = {
            "cache": (
                self._constrain_cache_paged(
                    _empty_cache_paged(
                        cfg, self.paged_blocks, self.page_size,
                        self.engine.kv_quant,
                    )
                )
                if self.paged else
                self.engine._constrain_cache(
                    _empty_cache(
                        cfg, slots, self.engine.max_seq,
                        self.engine.kv_quant,
                    )
                )
            ),
            "token": jnp.zeros(slots, jnp.int32),
            "pos": jnp.zeros(slots, jnp.int32),
            "rope": jnp.zeros(slots, jnp.int32),
            "start": jnp.zeros(slots, jnp.int32),
            "temps": jnp.zeros(slots, jnp.float32),
            "top_p": jnp.zeros(slots, jnp.float32),
            "keys": jax.vmap(jax.random.PRNGKey)(
                jnp.zeros(slots, jnp.uint32)
            ),
            "aidx": jnp.zeros(slots, jnp.int32),
            "cidx": jnp.zeros(slots, jnp.int32),
            "cstate": jnp.zeros(slots, jnp.int32),
        }
        if self.draft_engine is not None:
            # Draft KV pool + the `prev` stream token: the draft stays one
            # position behind the target and re-ingests prev each round
            # (speculative.py docstring), per slot.
            self._dev["d_cache"] = self.draft_engine._constrain_cache(
                _empty_cache(
                    self.draft_engine.cfg, slots, self.engine.max_seq
                )
            )
            self._dev["prev"] = jnp.zeros(slots, jnp.int32)
        if self.spec_mode == "ngram":
            # Per-slot token history: hist[slot, p] = the stream token at
            # position p (-1 unwritten) — the ngram draft's entire state.
            self._dev["hist"] = jnp.full(
                (slots, self.engine.max_seq), -1, jnp.int32
            )
        # Spec sub-rounds per dispatch are sized in _dispatch_round for
        # per-dispatch COMPUTE parity with a plain round — not token
        # parity.  A sub-round's target cost is one (K+1)-wide forward
        # ≈ one width-1 decode step (both HBM-bound on the params), so
        # ngram runs steps_per_round sub-rounds per dispatch and always
        # emits >= steps_per_round tokens — strictly dominating the
        # plain round even at acceptance 0 (measured: token-parity
        # sizing put ngram at 0.24x plain on v5e purely on dispatch
        # overhead).  A neural draft adds K draft forwards per
        # sub-round, each costing ~(draft bytes / target bytes) of a
        # target step, so a sub-round costs ~ 1 + K*r target-steps; K
        # itself adapts to measured acceptance (_adaptive_k).
        # Host-side scheduler state.  No position mirror is needed: submit
        # clamps max_new to the decode room, so the budget always retires a
        # slot before its writes could run past max_seq (out-of-bounds
        # scatter writes in a final round's garbage tail are dropped by
        # XLA's scatter semantics and never emitted).
        self._active: list[_Request | None] = [None] * slots
        # maxsize IS the admission bound: put_nowait's queue.Full is the
        # atomic load-shedding signal (a qsize() pre-check would race
        # concurrent HTTP handler threads and overshoot the bound).
        # maxsize=0 means unbounded, matching the max_pending=0 contract.
        self.max_pending = max(0, int(max_pending))
        self._pending: "queue.Queue[_Request]" = queue.Queue(
            maxsize=self.max_pending
        )
        self._dead = False
        # Serializes submit() against the end-of-life drain: either a
        # request lands in _pending before the drain empties it, or submit
        # sees _dead and raises — never an undrained orphan.
        self._lifecycle = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        # Quiesce barriers (run_quiesced): thunks the scheduler runs at
        # a round boundary with the dispatch pipeline fully drained —
        # the pause point block migration exports/imports through.
        # Enqueued under _lifecycle (same either-or as _pending: a
        # barrier lands before the death drain empties the queue, or
        # the caller sees _dead and raises).
        self._barriers: "queue.Queue[tuple]" = queue.Queue()
        self._round_count = 0
        # Speculative acceptance telemetry (host-side, live rows only).
        self._spec_drafted = 0
        self._spec_accepted = 0
        # Adaptive K (VERDICT r4 ask #5): the draft window resizes from
        # MEASURED rolling acceptance — high acceptance earns deeper
        # windows, low acceptance stops paying for drafts the verify
        # rejects.  K is a static shape, so "per-slot K" is not
        # XLA-expressible without ragged windows; the adaptive unit is
        # the dispatch (all co-tenants share each round's K), driven by
        # the same pooled acceptance the telemetry reports.
        self._spec_recent: collections.deque = collections.deque(maxlen=64)
        self._spec_k_active = self.spec_k
        self._spec_freeze = 0  # proposals to observe before re-adapting
        # Ngram adaptive gate (ISSUE 5 satellite): a prompt-lookup
        # sub-round is ONE (K+1)-wide verify — it costs MORE than a
        # width-1 decode step (wider attention window + per-row window
        # scatter), and how much more is platform-dependent (~1.4x on
        # v5e, ~3x on the CPU toy), so at low acceptance an ngram
        # dispatch LOSES to a plain one (BENCH_r05: cb_ngram_vs_plain_x
        # = 0.70, 0.74 even on repetitive traffic).  Two gates, both
        # from measurement, no assumed cost model:
        #
        # 1. acceptance floor: when every live slot's rolling acceptance
        #    sits below ``ngram_breakeven`` (the break-even at the most
        #    optimistic plausible cost ratio), speculation is a pure
        #    loss on ANY hardware — fall back immediately;
        # 2. measured throughput: periodic TIMED measurement rounds —
        #    the dispatcher drains the pipeline so the device is idle,
        #    dispatches one round of the mode under test, and times
        #    dispatch→consume.  That wall interval is the round's exact
        #    end-to-end cost (pipelined rounds can't be timed: a consume
        #    of an already-finished round returns instantly), and
        #    tokens/dt over the last few timed rounds per mode is the
        #    REAL goodput of spec vs plain on this platform and traffic.
        #    When spec measures slower, fall back.  Measurement
        #    dispatches are real work (their tokens stream normally);
        #    their only cost is the pipeline bubble plus — for the
        #    losing mode — the forgone win on that one round.
        #
        # While gated off, spec measurements ARE the probes: each one
        # that confirms the loss doubles the probe interval (capped at
        # 8x ``ngram_probe_s``), so a regime that keeps losing gets
        # probed asymptotically rarely — gated ngram mode becomes
        # indistinguishable from plain mode — while a stream that turns
        # self-repetitive re-earns speculation within a few probes
        # (rate windows are short on purpose).  Plain fallback rounds
        # keep the per-slot token history warm (see _round_dev), so
        # probe acceptance is real, not cold.
        self.ngram_breakeven = 0.125
        self.ngram_min_obs = 64          # proposals per slot before gating
        self.ngram_measure_s = 5.0       # seconds between timed rounds
        self.ngram_probe_s = 10.0        # gated: base seconds per probe
        # Deadlines at 0.0 → both modes measured on the first dispatches
        # (bootstrap), then every ngram_measure_s per mode.  Wall-time
        # cadence, not dispatch-count: covering rounds make dispatch
        # counts meaningless across traffic shapes.
        self._ngram_next_meas = {"plain": 0.0, "spec": 0.0}
        # Bootstrap: a mode's deadline only advances once it has been
        # timed 3x (the first is compile warmup, skipped at the record
        # site), so the first ~6 dispatches produce two REAL samples of
        # each mode back-to-back — a short workload gets gate evidence
        # in its first moments instead of after 2x ngram_measure_s.
        self._ngram_timed_sched = {"plain": 0, "spec": 0}
        self._ngram_timed_rec = {"plain": 0, "spec": 0}
        self._ngram_probe_scale = 1      # backoff multiplier while gated
        self._ngram_fallback_rounds = 0
        # Set by _spec_gate, committed by _dispatch_round once the round
        # is past its last abandon point (see the drain block there).
        self._gate_fallback = False
        self._slot_spec: dict[int, collections.deque] = {}
        self._mode_rate: dict[str, collections.deque] = {
            "spec": collections.deque(maxlen=4),
            "plain": collections.deque(maxlen=4),
        }
        if self.spec_mode == "neural":
            self._draft_ratio = _param_bytes(self.draft_params) / max(
                1, _param_bytes(params)
            )
        else:
            # ngram drafting has no draft forward; the only K cost is
            # the wider verify window — a small per-K epsilon.
            self._draft_ratio = 0.02
        # (round, slot) per emitted token; bounded — it's interleaving
        # observability, not an audit log.
        self._interleave_log: collections.deque = collections.deque(
            maxlen=4096
        )
        # Fleet-utilization telemetry (ISSUE 4): cumulative emissions and
        # a rolling (time, total) window feed the decode-throughput
        # gauge; occupancy/fill gauges are recomputed in
        # _update_util_gauges at admission/round/retire boundaries —
        # scheduler-thread only, a handful of host ops per round.
        self._emit_total = 0
        self._tput_samples: collections.deque = collections.deque(maxlen=64)
        # Readiness latch (serve/server.py /readyz): flips True at the
        # first emitted token — prefill AND decode programs compiled and
        # produced output.  Monotonic single-writer bool (scheduler
        # thread sets, HTTP threads read); no lock needed.
        self._warmed = False
        self._admit_jit = jax.jit(self._admit_dev, donate_argnums=(1,))
        # use_top_p is static: two compiled round variants, and the
        # common no-nucleus traffic never pays the full-vocab sort.
        self._round_jit = jax.jit(
            self._round_dev, donate_argnums=(1,), static_argnums=(4, 5, 6)
        )
        # Solo variants: one live request + empty queue → longer rounds
        # amortize dispatch overhead (see _round_dev docstring).  The
        # bucket ladder lets the tail round be SIZED to the remaining
        # budget instead of always paying the largest variant (a 48-token
        # request runs one 64-step round, not 32+32 with half wasted).
        self.solo_buckets = [
            self.steps_per_round * m for m in (1, 2, 3, 4, 6, 8)
        ]
        self._admit_round_jit = jax.jit(
            self._admit_round_dev, donate_argnums=(1,),
            static_argnums=(12, 13, 14),
        )
        self._round_spec_jit = jax.jit(
            self._round_spec_dev, donate_argnums=(2,),
            static_argnums=(4, 5, 6, 7),
        )
        self._round_spec_ngram_jit = jax.jit(
            self._round_spec_ngram_dev, donate_argnums=(1,),
            static_argnums=(3, 4, 5, 6),
        )
        # Paged variants ride the same functions; the page-table operand
        # (arg 8 / 7) is traced, so paged and dense spec share traces
        # per (use_top_p, n_rounds, t_hi, K) tuple.
        self._admit_prefix_jit = jax.jit(
            self._admit_prefix_dev, donate_argnums=(1,)
        )
        # Paged admission: right-padded suffix extend straight into the
        # slot's page-table row (shared prefix blocks read through the
        # table, fresh K/V scattered into the private tail blocks) —
        # one compile per pow2 suffix bucket.
        self._admit_paged_jit = jax.jit(
            self._admit_paged_dev, donate_argnums=(1,)
        )
        self._admit_exact_jit = jax.jit(
            self._admit_exact_dev, donate_argnums=(0,)
        )
        # One wrapper → jit's own executable cache; width comes bucketed
        # from precache_prefix (a fresh jax.jit per call would retrace
        # every time, and unbucketed widths would compile per length).
        self._precache_jit = jax.jit(
            lambda params, cache, padded: self.engine.extend_multi(
                params, cache, padded,
                jnp.asarray([0]), jnp.asarray([0]), jnp.asarray([0]),
            )
        )
        # Prefix cache: prompt-prefix bytes → prefilled device cache row.
        # Entries are read-only after insert; LRU-bounded (each entry owns
        # a full [L,1,H,max_seq,Dh] K/V row — HBM, not host RAM).
        # ``prefix_cache=False`` disables BOTH prefix planes (this dense
        # entry cache and paged block sharing) — the replay A/B harness's
        # candidate config (ISSUE 19's seeded-regression demo) and an
        # escape hatch when cache reuse itself is the suspect.
        self.prefix_cache = bool(prefix_cache)
        self._prefix: "collections.OrderedDict[bytes, dict]" = (
            collections.OrderedDict()
        )
        self._prefix_cap = 4
        self._prefix_lock = threading.Lock()
        # The scheduler loop compiles round variants from its own thread
        # while the embedding process may compile elsewhere; this
        # jaxlib's compiler races under concurrent compiles (utils/
        # compat.py) — serialize them before the thread exists.
        serialize_xla_compiles()
        self._thread = threading.Thread(
            target=self._loop, name="continuous-batcher", daemon=True
        )

