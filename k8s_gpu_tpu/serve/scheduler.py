"""Admission, queueing, and round policy for the continuous batcher.

Split out of the original ``serve/batcher.py`` monolith (ISSUE 20):
this module owns the *scheduling plane* — the pending queue and
admission gates (``submit``/``submit_precomputed``), the scheduler
thread's round loop, round sizing and speculative gating policy, quiesce
barriers, retirement/emission bookkeeping, and the request/handle types
every layer shares.  Device dispatch lives in ``serve/executor.py``;
BlockPool interaction and page planning live in ``serve/allocator.py``;
``ContinuousBatcher`` (serve/batcher.py) composes the three back into
the public API.  The split is deliberately state-preserving: all mutable
state stays on the composed instance, so the pre-split stream contract
(tests/test_batcher_split.py pins byte-identity) is unchanged.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.compat import large_thread_stack
from ..utils.faults import global_faults
from ..utils.tracing import global_tracer
from .engine import _empty_cache
from .journal import PROBE_TENANT, RequestRecord, golden_hash

log = logging.getLogger("k8s_gpu_tpu.serve")


class Overloaded(RuntimeError):
    """Admission refused: the pending queue is at ``max_pending``.  The
    load-shedding signal — servers map it to 429 + Retry-After so clients
    back off, instead of letting the queue (and every queued request's
    latency) grow without bound."""



def _suffix_bucket(n: int) -> int:
    """Compile bucket for a prefix-cached prompt's suffix: smallest power
    of two >= n (floor 8).  Right-padded, so no decode-room coupling."""
    b = 8
    while b < n:
        b *= 2
    return b


def prompt_bucket(n_tokens: int, max_seq: int) -> int | None:
    """Smallest compile bucket >= n_tokens that still leaves decode room.

    Power-of-two buckets up to max_seq/2 keep the compile count
    O(log max_seq); two fixed long-prompt buckets (3/4·max_seq and
    max_seq-8) extend serving capacity to max_seq-8 tokens.  Returns None
    when the prompt can't fit with at least 8 tokens of decode room."""
    candidates = []
    b = 8
    while b <= max_seq // 2:
        candidates.append(b)
        b *= 2
    candidates.append((3 * max_seq // 4) // 8 * 8)
    candidates.append(max_seq - 8)
    for c in sorted(set(candidates)):
        if c >= n_tokens and c < max_seq:
            return c
    return None


@dataclass
class _Request:
    ids: np.ndarray          # prompt token ids, unpadded
    max_new: int
    temperature: float
    top_p: float
    seed: int
    out: queue.Queue = field(default_factory=queue.Queue)
    slot: int = -1
    aidx: int = 0            # adapter bank index (0 = base model)
    cidx: int = 0            # constraint bank index (0 = unconstrained)
    # (row_cache, last_logits, pos, rope, start): K/V computed by a
    # prefill worker (serve/disagg.py); admission splices, no forward.
    precomputed: tuple | None = None
    # Called once when the row is spliced into the pool (the precomputed
    # K/V's HBM lifetime ends there) — disagg backpressure hook.
    on_admit: object = None
    emitted: int = 0
    # Steps dispatched for this row but not yet processed: the scheduler
    # stops dispatching once emitted + inflight_steps covers max_new for
    # every live row, so no round is ever all-garbage (each wasted round
    # costs a full device program through the dispatch tunnel).
    inflight_steps: int = 0
    # Host mirror of the row's device cache position AFTER the in-flight
    # rounds land — the t_hi attention-read bucket is computed from it.
    pos_hint: int = 0
    # True when the stream ended because the batcher crashed/stopped, not
    # because of EOS/budget — servers map this to a 5xx, not a 200.
    aborted: bool = False
    # Absolute host-monotonic deadline (None = no deadline), propagated
    # from the caller (the LM server's x-request-deadline-ms header).
    # Expired work is DROPPED — at admission before any device program,
    # and between rounds mid-stream — never computed to completion.
    deadline: float | None = None
    # True when the stream ended because ``deadline`` passed — servers
    # map this to 504, distinct from the crash-abort 503.
    deadline_expired: bool = False
    # Latency telemetry (host wall-clock, seconds): submit time, admit
    # dispatch time, first/last emission time.  Feed the C32 serving
    # histograms at retirement (queue wait, TTFT, inter-token gap).
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first: float = 0.0
    t_last: float = 0.0
    # Paged-KV mode: the physical blocks allocated to this request
    # (held from admission to retirement; [] in dense mode).  The first
    # prefix_tokens/page_size of them are SHARED prefix blocks acquired
    # from the content cache; prefix_tokens None routes the admission
    # through the dense-row splice path instead of the suffix extend.
    blocks: list = field(default_factory=list)
    prefix_tokens: int | None = None
    # Tracing context captured at submit (the HTTP request's span when
    # the request came through the LM server).  None for untraced
    # submits — every span site below is gated on it, so direct batcher
    # use (bench, tests) pays one thread-local read at submit and
    # NOTHING per round.  Spans are created at round/segment
    # granularity only, never per token.
    trace_ctx: object = None
    # SLO accounting dimension (caller-supplied request metadata;
    # "default" for untagged traffic).  Labels the latency histograms,
    # shed counter, and the goodput/total token counters at retirement.
    tenant: str = "default"
    # Admission path (_seated's path argument) — journal evidence of
    # HOW the request was admitted; "" for requests shed pre-admission.
    path: str = ""
    # Prompt length captured at SUBMIT: ids.size, or the precomputed
    # row's n_tokens — ``precomputed`` itself is dropped at seating (its
    # HBM lifetime ends there), so the journal can't read it back.
    prompt_tokens: int = 0
    # Per-request speculative-decode evidence for the journal: drafted
    # proposals and verify-kept acceptances attributable to THIS row.
    spec_drafted: int = 0
    spec_accepted: int = 0
    # Fleet-routing evidence (serve/router.py dispatch, or the LM
    # server's x-route-replica/x-route-reason headers): which replica a
    # front-end chose and why — journaled so `obs requests` explains
    # placement.  "" for direct submits.
    route_replica: str = ""
    route_reason: str = ""
    # Live-migration evidence (serve/migrate.py).  ``migrated`` marks a
    # stream CUT here because its replica exported its KV state away —
    # the server's truncation summary tells the gateway relay this is a
    # resumable handover, not a crash.  ``migrated_from`` names the
    # replica a RESUMED request left (the x-migrated-from header):
    # journaled, and counted by serve_resumed_requests_total.
    migrated: bool = False
    migrated_from: str = ""
    # Every token id delivered to the caller, in emission order —
    # accumulated by the _emit funnel so the journal can stamp a
    # golden content-hash at retirement (serve/replay.py verifies
    # replayed streams against it).
    emitted_ids: list = field(default_factory=list)


class RequestHandle:
    """Caller's view of an in-flight request: iterate tokens as they
    stream; ``result()`` blocks for the full list.  Tokens are cached, so
    re-iterating (or calling result() after iterating) replays them
    instead of deadlocking on the consumed queue.  Single consuming
    thread at a time."""

    def __init__(self, req: _Request):
        self._req = req
        self._tokens: list[int] = []
        self._lps: list[float] = []
        self._done = False

    def __iter__(self):
        yield from self._tokens  # replay what was already consumed
        while not self._done:
            item = self._req.out.get()
            if item is None:
                self._done = True
                return
            tok, lp = item
            self._tokens.append(tok)
            self._lps.append(lp)
            yield tok

    def result(self) -> list[int]:
        return list(self)

    @property
    def aborted(self) -> bool:
        """True when the stream was cut by batcher shutdown/crash — the
        token list is then a truncation, not a completed generation."""
        return self._req.aborted

    @property
    def deadline_expired(self) -> bool:
        """True when the stream ended because the request's deadline
        passed (shed at admission, or cut between rounds)."""
        return self._req.deadline_expired

    @property
    def migrated(self) -> bool:
        """True when the stream was cut because the replica migrated
        its KV state away (serve/migrate.py) — the truncation is a
        resumable handover, not a failure."""
        return self._req.migrated

    @property
    def logprobs(self) -> list:
        """Per-token log-probabilities, parallel to result().  Complete
        only after the stream finishes (same contract as result());
        requires the batcher's ``logprobs=True`` (zeros otherwise)."""
        return list(self._lps)

    @property
    def last_logprob(self) -> float:
        """Logprob of the most recently consumed token (streaming)."""
        return self._lps[-1] if self._lps else 0.0



class SchedulerMixin:
    """Admission/queueing/round policy half of ``ContinuousBatcher``.

    Mixed into the composed batcher; every attribute it touches is
    created by ``ContinuousBatcher.__init__`` (the single owner of the
    shared state) and its device dispatches resolve to
    ``ExecutorMixin`` methods on the same instance."""

    # -- public surface ----------------------------------------------------
    def start(self) -> "ContinuousBatcher":
        # Enlarged stack for the scheduler thread: it compiles round
        # variants, and XLA codegen recursion can blow a default worker
        # stack (utils/compat.py:large_thread_stack has the account).
        with large_thread_stack():
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)

    def submit(
        self,
        ids,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_p: float = 0.0,
        seed: int = 0,
        adapter: str | None = None,
        constraint: str | None = None,
        deadline: float | None = None,
        tenant: str | None = None,
        route: tuple | None = None,
        migrated_from: str = "",
    ) -> RequestHandle:
        """Queue a request; returns a handle streaming generated ids.
        Raises ValueError when the prompt cannot fit, KeyError for an
        unknown ``adapter``/``constraint`` name, ``Overloaded`` when
        ``max_pending`` is configured and the queue is full.
        ``deadline`` is an absolute ``time.monotonic()`` instant: work
        still queued (or still decoding) past it is dropped, not
        computed.  ``tenant`` labels the request's SLO accounting
        (latency histograms, shed counter, goodput/total tokens) and
        its journal record; None/"" means ``"default"``.  Cardinality
        is bounded by the registry's per-name series cap — a flood of
        distinct tenant strings collapses into the overflow series,
        never unbounded growth.  ``route``: ``(replica, reason)`` from
        a fleet front-end (serve/router.py) — journaled so the request
        record explains its placement.  ``migrated_from`` names the
        replica this request resumed away from after a KV migration
        (serve/migrate.py) — journaled, and counted by
        ``serve_resumed_requests_total``."""
        # error/timeout only: this site has no clock to realize a
        # "slow" decision, and a silently-skipped delay must not be
        # counted as an injection.
        global_faults.fire(
            "serve.submit", error_type=RuntimeError,
            only=("error", "timeout"),
        )
        aidx = self.bank.index(adapter)
        cidx = self._constraint_index(constraint)
        ids = np.asarray(ids, np.int32).ravel()
        bucket = prompt_bucket(int(ids.size), self.engine.max_seq)
        if bucket is None:
            raise ValueError(
                f"prompt too long ({ids.size} tokens, "
                f"max {self.engine.max_seq - 8})"
            )
        room = self.engine.max_seq - bucket
        if self.role == "prefill":
            # Prefill-only worker: the budget is the one token the
            # admission program samples while prefilling — the request
            # retires at admission and a decode round is never reached
            # (the executor's _guard_decode enforces it).  The sampled
            # token is discarded by the handover; the decode side
            # recomputes it from the imported chain, byte-identically.
            max_new_tokens = 1
        req = _Request(
            ids=ids,
            max_new=max(1, min(int(max_new_tokens), room)),
            temperature=float(temperature),
            top_p=float(top_p),
            seed=int(seed),
            aidx=aidx,
            cidx=cidx,
            deadline=deadline,
            t_submit=time.monotonic(),
            trace_ctx=global_tracer.current(),
            tenant=str(tenant) if tenant else "default",
            prompt_tokens=int(ids.size),
            route_replica=str(route[0]) if route else "",
            route_reason=str(route[1]) if route else "",
            migrated_from=str(migrated_from or ""),
        )
        if req.migrated_from:
            self.metrics.inc("serve_resumed_requests_total")
        with self._lifecycle:
            if self._dead:
                raise RuntimeError(
                    "batcher scheduler is stopped; restart the server"
                )
            try:
                self._pending.put_nowait(req)
            except queue.Full:
                self.metrics.inc(
                    "serve_shed_total", reason="queue_full",
                    tenant=req.tenant,
                )
                self._journal(req, "queue_full")
                raise Overloaded(
                    f"pending queue full ({self.max_pending} requests); "
                    "retry later"
                ) from None
        self._wake.set()
        return RequestHandle(req)

    def submit_precomputed(
        self, row_cache, last_logits, n_tokens: int, pad: int,
        max_new_tokens: int = 32, temperature: float = 0.0,
        top_p: float = 0.0, seed: int = 0,
        adapter: str | None = None, on_admit=None,
        constraint: str | None = None, tenant: str | None = None,
        route: tuple | None = None,
    ) -> RequestHandle:
        """Admit a request whose prefill ran elsewhere (serve/disagg.py):
        ``row_cache`` is a [L, 1, H, max_seq, Dh] K/V tree computed at a
        [1, n_tokens] bucket with ``pad`` leading pad slots;
        ``last_logits`` [1, V] are the logits at the final prompt
        position.  The decode side only splices and samples."""
        # error/timeout only: this site has no clock to realize a
        # "slow" decision, and a silently-skipped delay must not be
        # counted as an injection.
        global_faults.fire(
            "serve.submit", error_type=RuntimeError,
            only=("error", "timeout"),
        )
        aidx = self.bank.index(adapter)
        cidx = self._constraint_index(constraint)
        room = self.engine.max_seq - n_tokens
        if room < 1:
            raise ValueError("precomputed prompt fills max_seq")
        # Validate shapes HERE, in the caller's thread: a mis-shaped tree
        # would otherwise explode inside the scheduler loop and take the
        # whole batcher (and every tenant's stream) down with it.
        cfg = self.engine.cfg
        tmpl = jax.eval_shape(
            lambda: _empty_cache(cfg, 1, self.engine.max_seq,
                                 self.engine.kv_quant)
        )
        got_keys = set(row_cache) if isinstance(row_cache, dict) else None
        if got_keys != set(tmpl):
            raise ValueError(
                f"row_cache keys {got_keys} != {set(tmpl)} (was it "
                "prefilled by an engine with a different kv_quant "
                "setting?)"
            )
        for key, leaf in row_cache.items():
            if tuple(leaf.shape) != tuple(tmpl[key].shape):
                raise ValueError(
                    f"row_cache[{key!r}] shape {tuple(leaf.shape)} != "
                    f"{tuple(tmpl[key].shape)} (was it prefilled by an "
                    "engine with a different max_seq?)"
                )
        if tuple(last_logits.shape) != (1, cfg.vocab_size):
            raise ValueError(
                f"last_logits shape {tuple(last_logits.shape)} != "
                f"(1, {cfg.vocab_size})"
            )
        req = _Request(
            ids=np.zeros(0, np.int32),
            max_new=max(1, min(int(max_new_tokens), room)),
            temperature=float(temperature),
            top_p=float(top_p),
            seed=int(seed),
            aidx=aidx,
            cidx=cidx,
            precomputed=(
                row_cache, last_logits, n_tokens, n_tokens - pad, pad,
            ),
            on_admit=on_admit,
            t_submit=time.monotonic(),
            trace_ctx=global_tracer.current(),
            tenant=str(tenant) if tenant else "default",
            prompt_tokens=int(n_tokens),
            route_replica=str(route[0]) if route else "",
            route_reason=str(route[1]) if route else "",
        )
        with self._lifecycle:
            if self._dead:
                raise RuntimeError(
                    "batcher scheduler is stopped; restart the server"
                )
            try:
                self._pending.put_nowait(req)
            except queue.Full:
                self.metrics.inc(
                    "serve_shed_total", reason="queue_full",
                    tenant=req.tenant,
                )
                self._journal(req, "queue_full")
                raise Overloaded(
                    f"pending queue full ({self.max_pending} requests); "
                    "retry later"
                ) from None
        self._wake.set()
        return RequestHandle(req)

    def precache_prefix(self, ids) -> None:
        """Prefill *ids* once and keep the K/V row for reuse: any later
        submit whose prompt starts with *ids* only computes its suffix
        (one extend over the suffix bucket), and a prompt that IS a
        cached prefix admits with no model forward at all.  The classic
        use is a shared system prompt / few-shot preamble.

        Exact-shape prefill: one compile per distinct prefix length —
        prefixes are few and long-lived, so that trade is right (bucketed
        prefixes would burn cache slots on pad garbage).  LRU-bounded at
        4 entries; each entry owns a full K/V row in HBM.

        Paged mode needs no dense entry: prefix caching there is
        block-granular and AUTOMATIC (every admission registers its full
        prompt pages — serve/kv_blocks.py), so this call just warms the
        block cache by running the prefix through a throwaway 1-token
        generation; the registered blocks outlive it at refcount 0 until
        evicted.  Only full ``page_size``-aligned chunks are shareable —
        a prefix shorter than one page warms nothing."""
        if self.paged:
            if self.engine.cfg.moe:
                raise ValueError(
                    "prefix caching is unavailable for MoE models: "
                    "capacity-capped expert dispatch makes chunked "
                    "prefill diverge from the one-shot path"
                )
            ids = np.asarray(ids, np.int32).ravel()
            if ids.size == 0 or ids.size > self.engine.max_seq - 8:
                raise ValueError(f"prefix length {ids.size} unusable")
            if not self._thread.is_alive():
                raise RuntimeError(
                    "paged precache_prefix rides a throwaway generation "
                    "— start() the batcher first"
                )
            self.submit(ids, max_new_tokens=1).result()
            return
        if self.engine.cfg.moe:
            # Capacity-capped Switch dispatch couples every token in the
            # dispatch group: a chunked (prefix + suffix) prefill computes
            # caps over different group sizes than the one-shot prefill
            # and silently drops different tokens — chunking cannot match
            # the oracle, so refuse rather than serve diverging streams.
            raise ValueError(
                "prefix caching is unavailable for MoE models: "
                "capacity-capped expert dispatch makes chunked prefill "
                "diverge from the one-shot path"
            )
        ids = np.asarray(ids, np.int32).ravel()
        if ids.size == 0 or ids.size > self.engine.max_seq - 8:
            raise ValueError(f"prefix length {ids.size} unusable")
        # Bucketed width via extend_multi (RIGHT-padded, logits gathered
        # at the last real position): one compile per power-of-2 bucket.
        # Exact-shape prefill would hand the unauthenticated /precache
        # endpoint an unbounded per-length XLA compile cache.  Pad K/V
        # garbage lands at positions >= n — the suffix/decode writes
        # overwrite it in order and position masks never attend it.
        n = int(ids.size)
        w = min(_suffix_bucket(n), self.engine.max_seq)
        padded = jnp.zeros((1, w), jnp.int32).at[0, :n].set(jnp.asarray(ids))
        cache, all_logits = self._precache_jit(
            self.params,
            _empty_cache(self.engine.cfg, 1, self.engine.max_seq,
                         self.engine.kv_quant),
            padded,
        )
        logits = all_logits[:, n - 1]
        with self._prefix_lock:
            self._prefix[ids.tobytes()] = {
                "cache": cache, "logits": logits, "n": int(ids.size),
            }
            self._prefix.move_to_end(ids.tobytes())
            while len(self._prefix) > self._prefix_cap:
                self._prefix.popitem(last=False)

    # -- block migration (serve/migrate.py) --------------------------------
    def run_quiesced(self, fn, timeout_s: float = 60.0):
        """Run ``fn()`` ON the scheduler thread at the next round
        boundary with the dispatch pipeline fully drained — every
        device write landed, no program in flight.  The pause point
        block migration exports/imports through: ``fn`` may read block
        contents, splice new ones, and mutate the pool without racing
        a decode round.  Blocks the calling thread for the result;
        ``fn``'s exception re-raises here (the scheduler survives it).
        Raises RuntimeError when the scheduler is stopped and
        TimeoutError when no boundary is reached in ``timeout_s`` (the
        thunk may still run later; its side effects stand)."""
        box = {
            "done": threading.Event(), "result": None, "error": None,
        }
        with self._lifecycle:
            if self._dead:
                raise RuntimeError(
                    "batcher scheduler is stopped; restart the server"
                )
            self._barriers.put((fn, box))
        self._wake.set()
        if not box["done"].wait(timeout_s):
            raise TimeoutError(
                f"scheduler did not reach a round boundary in "
                f"{timeout_s:.1f}s"
            )
        if box["error"] is not None:
            raise box["error"]
        return box["result"]

    def _run_barriers(self) -> None:
        """Scheduler thread, pipeline drained: run every queued
        quiesced thunk.  A thunk's exception is delivered to ITS
        waiter, never raised here — a malformed import must not kill
        the scheduler serving everyone else."""
        while True:
            try:
                fn, box = self._barriers.get_nowait()
            except queue.Empty:
                return
            try:
                box["result"] = fn()
            except Exception as e:
                box["error"] = e
            box["done"].set()


    def _match_prefix(self, ids: np.ndarray):
        """Longest cached prefix of *ids* (LRU-touched), or None."""
        if not self.prefix_cache:
            return None
        best_key = None
        best = None
        with self._prefix_lock:
            for key, entry in self._prefix.items():
                n = entry["n"]
                if (
                    n <= ids.size
                    and (best is None or n > best["n"])
                    and ids[:n].tobytes() == key
                ):
                    best, best_key = entry, key
            if best_key is not None:
                self._prefix.move_to_end(best_key)
        return best

    def _constraint_index(self, name: str | None) -> int:
        if name is None:
            return 0
        if self.cbank is None:
            raise KeyError(
                f"unknown constraint {name!r}; no ConstraintBank configured"
            )
        return self.cbank.index(name)

    @property
    def steps_taken(self) -> int:
        return self._round_count

    @property
    def pending_requests(self) -> int:
        """Queued-but-unadmitted request count — the autoscale signal
        (operators/inferenceservice.py) and the same quantity the
        'serve_pending_requests' gauge reports."""
        return self._pending.qsize()

    @property
    def inflight_requests(self) -> int:
        """Live request count: queued-but-unadmitted plus admitted rows
        still decoding.  The drain signal — a front-end retiring this
        replica waits for zero (serve/frontend.py; /readyz carries it
        so the wait needs no metrics scrape).  Benign racy read of the
        slot list, like the gauge export's."""
        active = sum(1 for r in self._active if r is not None)
        return self._pending.qsize() + active

    @property
    def scheduler_alive(self) -> bool:
        """Liveness of the decode scheduler: started, not crashed, not
        stopped — one of the three readiness legs /readyz gates on
        (serve/server.py, docs/platform/serving.md 'The health
        contract')."""
        with self._lifecycle:
            dead = self._dead
        return not dead and self._thread.is_alive()

    @property
    def past_first_compile(self) -> bool:
        """True once the engine has emitted a token — prefill and decode
        programs compiled and producing output.  A fresh replica warms on
        its first request; the canary's first probe does it for an idle
        one (serve/canary.py)."""
        return self._warmed

    @property
    def warm_chain_hashes(self) -> list[str]:
        """Sorted hex content hashes of every registered KV block —
        the ``GET /debug/chains`` body the gateway fleet's owner-map
        reconstruction scrapes (serve/frontend.py).  Non-paged mode
        has no chain-addressed state and returns [].  Benign racy read
        of the pool's registry, like the gauge export's: the scheduler
        may register a block mid-iteration, so retry the snapshot a
        few times and degrade to [] rather than block the scrape
        behind a quiesce barrier (reconstruction tolerates a stale
        scrape; it re-converges on the next pass)."""
        pool = getattr(self, "_pool", None)
        if pool is None:
            return []
        for _ in range(3):
            try:
                return [h.hex() for h in pool.chain_hashes()]
            except RuntimeError:
                continue
        return []

    @property
    def spec_stats(self) -> dict:
        """Measured speculative acceptance over live rows: drafted /
        accepted counts and the rate (0.0 when spec is off or nothing
        ran).  This is the number the bench reports — a projection is
        not evidence."""
        d, a = self._spec_drafted, self._spec_accepted
        return {
            "drafted": d, "accepted": a,
            "acceptance": (a / d) if d else 0.0,
            # Ngram adaptive gate: plain rounds dispatched instead of
            # speculative ones because speculation measured as a loss
            # (_spec_gate).  > 0 means the gate engaged.  The tps pair
            # is the gate's own evidence: measured goodput of spec vs
            # plain dispatches (0.0 until enough samples).
            "fallback_rounds": self._ngram_fallback_rounds,
            "gate_spec_tps": self._mode_tps("spec"),
            "gate_plain_tps": self._mode_tps("plain"),
        }

    @property
    def interleave_log(self) -> list[tuple[int, int]]:
        """(round, slot) per emitted token — lets tests prove two requests
        shared the same decode rounds."""
        return list(self._interleave_log)

    # -- scheduler ---------------------------------------------------------
    def _free_slot(self) -> int:
        for i, r in enumerate(self._active):
            if r is None:
                return i
        return -1

    def _hist_row(self, ids, pos0: int):
        """ngram-mode admission: the row's token history with the prompt
        at its cache positions [pos0-n, pos0).  None when spec_mode is
        not ngram (the seat then skips hist entirely)."""
        if self.spec_mode != "ngram":
            return None
        h = np.full((self.engine.max_seq,), -1, np.int32)
        h[pos0 - ids.size: pos0] = ids
        return jnp.asarray(h)

    _ENTRY_UNRESOLVED = object()

    def _dispatch_admit(self, req: _Request, slot: int,
                        entry=_ENTRY_UNRESOLVED) -> tuple:
        """``entry``: the prefix-cache match for ``req.ids`` when the
        caller already looked it up (the _loop fused gate does); left
        unset, it is resolved here."""
        # Queue wait ends the moment the scheduler commits this request
        # to a slot: stamp BEFORE the admit dispatch, so prefill compute
        # lands in the prefill segment (ttft - queue_wait) rather than
        # inflating queue_wait.
        req.t_admit = time.monotonic()
        ctab = self.cbank.banked if self.cbank else None
        if req.precomputed is not None:
            row, logits, pos, rope, start = req.precomputed
            # Disagg hands over host-int geometry; anything else falls
            # back to the conservative bound (t_hi = max_seq for this
            # row's lifetime — correct, just unoptimized).
            known = isinstance(pos, (int, np.integer))
            req.pos_hint = int(pos) if known else self.engine.max_seq
            page_row = None
            if self.paged:
                # Splice the handed-over dense row into the allocated
                # blocks (full-width copy: one compile for any prompt
                # length; past-allocation pages map to trash).
                page_row = self._set_page_row(slot, req.blocks)
            self._dev, first, lp = self._admit_exact_jit(
                self._dev, row, logits, jnp.int32(pos), jnp.int32(rope),
                jnp.int32(start), jnp.int32(slot),
                jnp.float32(req.temperature), jax.random.PRNGKey(req.seed),
                jnp.int32(req.aidx), ctab, jnp.int32(req.cidx),
                jnp.float32(req.top_p), jnp.int32(0),
                hist_row=(
                    self._hist_row(req.ids, int(pos)) if known else None
                ),
                page_row=page_row,
            )
            # Drop the row reference (it lives on in the pool cache) and
            # signal the prefill pool that its HBM is reclaimable.
            req.precomputed = None
            if req.on_admit is not None:
                req.on_admit()
            return self._seated(req, slot, first, lp, "precomputed")
        if self.paged and req.prefix_tokens is not None:
            # Block-granular paged admission (_paged_plan already matched
            # the shared prefix and allocated the tail): right-padded
            # suffix extend through the slot's page-table row.
            page_row = self._set_page_row(slot, req.blocks)
            s_tok = req.prefix_tokens
            n = int(req.ids.size)
            n_real = n - s_tok
            w = min(_suffix_bucket(n_real), self.engine.max_seq)
            suffix = jnp.zeros((1, w), jnp.int32).at[0, :n_real].set(
                jnp.asarray(req.ids[s_tok:])
            )
            req.pos_hint = n
            self._dev, first, lp = self._admit_paged_jit(
                self.params, self._dev, suffix, jnp.int32(n_real),
                jnp.int32(slot), jnp.float32(req.temperature),
                jax.random.PRNGKey(req.seed), jnp.int32(s_tok),
                ctab, jnp.int32(req.cidx), jnp.float32(req.top_p),
                page_row,
                hist_row=self._hist_row(req.ids, n),
            )
            return self._seated(
                req, slot, first, lp,
                "paged_shared" if s_tok else "paged_cold",
            )
        # Prefix-cache entries hold BASE-model K/V; an adapter row must
        # cold-prefill (its prefix K/V differ) — correctness over reuse.
        if entry is self._ENTRY_UNRESOLVED:
            entry = (
                self._match_prefix(req.ids)
                if req.aidx == 0 and not self.paged else None
            )
        if entry is not None and entry["n"] == req.ids.size:
            # The prompt IS a cached prefix: splice + sample, zero forward.
            req.pos_hint = int(entry["n"])
            self._dev, first, lp = self._admit_exact_jit(
                self._dev, entry["cache"], entry["logits"],
                jnp.int32(entry["n"]), jnp.int32(entry["n"]), jnp.int32(0),
                jnp.int32(slot),
                jnp.float32(req.temperature), jax.random.PRNGKey(req.seed),
                jnp.int32(0), ctab, jnp.int32(req.cidx),
                jnp.float32(req.top_p), jnp.int32(int(req.ids[-1])),
                hist_row=self._hist_row(req.ids, int(entry["n"])),
            )
        elif entry is not None and (
            entry["n"] + _suffix_bucket(req.ids.size - entry["n"])
            <= self.engine.max_seq
        ):
            p = entry["n"]
            n_real = int(req.ids.size) - p
            w = _suffix_bucket(n_real)
            req.pos_hint = p + n_real
            suffix = jnp.zeros((1, w), jnp.int32).at[0, :n_real].set(
                jnp.asarray(req.ids[p:])
            )
            self._dev, first, lp = self._admit_prefix_jit(
                self.params, self._dev, entry["cache"], suffix,
                jnp.int32(n_real), jnp.int32(slot),
                jnp.float32(req.temperature),
                jax.random.PRNGKey(req.seed), jnp.int32(p),
                ctab, jnp.int32(req.cidx), jnp.float32(req.top_p),
                hist_row=self._hist_row(req.ids, p + n_real),
            )
        else:
            bucket = prompt_bucket(int(req.ids.size), self.engine.max_seq)
            pad = bucket - int(req.ids.size)
            req.pos_hint = bucket
            padded = jnp.zeros((1, bucket), jnp.int32).at[0, pad:].set(
                jnp.asarray(req.ids)
            )
            page_row = None
            if self.paged:
                # Register the allocation (made by the scheduler loop)
                # in the host page table, then hand the row to the admit
                # program for the prefill scatter.
                page_row = self._set_page_row(slot, req.blocks)
            self._dev, first, lp = self._admit_jit(
                self.params, self._dev, padded, jnp.int32(slot),
                jnp.float32(req.temperature),
                jax.random.PRNGKey(req.seed), jnp.int32(pad),
                self.bank.banked, jnp.int32(req.aidx),
                ctab, jnp.int32(req.cidx), jnp.float32(req.top_p),
                self.draft_params,
                hist_row=self._hist_row(req.ids, bucket),
                page_row=page_row,
            )
        path = (
            "prefix_exact" if entry is not None and entry["n"] == req.ids.size
            else "prefix_suffix" if entry is not None
            else "cold"
        )
        return self._seated(req, slot, first, lp, path)

    def _dispatch_admit_round(self, req: _Request, slot: int) -> tuple:
        """Fused cold-start: one dispatch covering admission AND the
        first tail-sized decode round.  Caller guarantees: plain mode
        (no spec), cold path (no precomputed row, no prefix hit), the
        batcher idle.  The stream equals the unfused path's bit-for-bit
        (same _admit_dev + _round_dev bodies, same PRNG consumption)."""
        req.t_admit = time.monotonic()
        ctab = self.cbank.banked if self.cbank else None
        bucket = prompt_bucket(int(req.ids.size), self.engine.max_seq)
        pad = bucket - int(req.ids.size)
        # ONE normal round, never more: committing the whole budget at
        # admit time would exclude a request arriving a few ms later
        # from ever sharing rounds (the interleaving contract
        # test_lm_server pins).  Short responses still complete in the
        # single fused dispatch; longer ones continue through the normal
        # dispatch loop, where solo-vs-shared is re-decided per round.
        n_steps = self.steps_per_round
        req.pos_hint = bucket
        t = self._t_hi([(slot, req)], 1 + n_steps)
        padded = jnp.zeros((1, bucket), jnp.int32).at[0, pad:].set(
            jnp.asarray(req.ids)
        )
        use_top_p = 0.0 < req.top_p < 1.0
        self._dev, first, lp, toks, lps = self._admit_round_jit(
            self.params, self._dev, padded, jnp.int32(slot),
            jnp.float32(req.temperature), jax.random.PRNGKey(req.seed),
            jnp.int32(pad), self.bank.banked, jnp.int32(req.aidx),
            ctab, jnp.int32(req.cidx), jnp.float32(req.top_p),
            use_top_p, n_steps, t,
        )
        self._seated(req, slot, first, lp, "cold_fused")
        if self.paged and self.engine.attn_impl == "paged_kernel":
            # The fused program body ends in a _round_dev decode round,
            # which reads through the kernel like any other round.
            self.metrics.inc("serve_paged_kernel_rounds_total")
        req.inflight_steps += n_steps
        req.pos_hint += n_steps
        self._round_count += 1
        return ("admit_round", self._round_count, req, first, lp, toks, lps,
                time.monotonic())

    def _seated(self, req: _Request, slot: int, first, lp,
                path: str) -> tuple:
        """Common tail of every admission: bookkeeping + C32 counters
        (admissions by path, live-slot gauge, pending-queue gauge)."""
        req.slot = slot
        req.path = path
        self._active[slot] = req
        if req.t_admit <= 0.0:
            req.t_admit = time.monotonic()
        self.metrics.observe(
            "serve_queue_wait_seconds", req.t_admit - req.t_submit
        )
        if req.trace_ctx is not None:
            # Admission wait as a span: submit → admit dispatch, under
            # the originating HTTP request's context.
            global_tracer.add_span(
                "serve.queue_wait", parent=req.trace_ctx,
                start=req.t_submit, end=req.t_admit,
                slot=slot, path=path,
            )
        # The admit's first token is already in flight: the budget gate
        # must see it, or a freshly admitted max_new=1 request triggers a
        # round that is 100% garbage (and every tail round sizes one
        # bucket too large).  _process's admit branch releases it.
        req.inflight_steps = 1
        self.metrics.inc("serve_admissions_total", path=path)
        # Prefix-cache accounting (dense entry cache AND paged block
        # cache): one hit/miss per admission that CONSULTED it —
        # precomputed (disagg) rows, adapter rows (cached K/V are
        # base-model), and MoE-paged prompts route around the lookup,
        # and counting them as misses would deflate the observed hit
        # ratio an operator sizes the cache from.
        consulted = req.aidx == 0 and (
            self._paged_share if self.paged else self.prefix_cache
        )
        if path in ("prefix_exact", "prefix_suffix", "paged_shared"):
            self.metrics.inc("serve_prefix_cache_hits_total")
        elif consulted and path in ("cold", "cold_fused", "paged_cold"):
            self.metrics.inc("serve_prefix_cache_misses_total")
        self.metrics.set_gauge(
            "serve_pending_requests", float(self._pending.qsize())
        )
        self._update_util_gauges()
        return ("admit", req, first, lp)

    def _update_util_gauges(self) -> None:
        """Serve-plane utilization gauges — the inputs pooled-accelerator
        scheduling decisions (and the KVCacheSaturation alert) read:

        - ``serve_slots_active`` / ``serve_slot_fill_ratio``: decode batch
          occupancy out of the static ``slots`` width;
        - ``serve_kv_occupancy_ratio``: paged mode reports allocated
          physical blocks over the usable pool (the trash block is
          overhead, not capacity); dense mode reports live rows' cache
          positions over slots×max_seq — reserved-but-unwritten tail
          counts as free, which is the actionable number (it is what
          admission can still use);
        - ``serve_decode_tokens_per_second``: emitted tokens over a
          rolling host-wall-clock window (dispatch cadence included — the
          streaming rate callers actually see)."""
        live = [r for r in self._active if r is not None]
        self.metrics.set_gauge("serve_slots_active", float(len(live)))
        self.metrics.set_gauge(
            "serve_slot_fill_ratio",
            len(live) / self.slots if self.slots else 0.0,
        )
        if self.paged:
            # PHYSICAL accounting: a block shared by N slots counts once
            # (per-request block lists would double-count shared
            # prefixes and false-fire KVCacheSaturation), and refcount-0
            # cached blocks count as FREE — they are reclaimable by the
            # next allocation, so they are capacity, not pressure.
            usable = self._pool.usable
            used = self._pool.pinned_count
            self.metrics.set_gauge("serve_kv_blocks_used", float(used))
            self.metrics.set_gauge(
                "serve_kv_blocks_shared", float(self._pool.shared_count)
            )
            self.metrics.set_gauge(
                "serve_kv_blocks_cached", float(self._pool.cached_count)
            )
            occ = used / usable if usable else 0.0
        else:
            cap = float(self.slots * self.engine.max_seq)
            occ = (
                sum(min(r.pos_hint, self.engine.max_seq) for r in live) / cap
                if cap else 0.0
            )
        self.metrics.set_gauge("serve_kv_occupancy_ratio", occ)
        now = time.monotonic()
        self._tput_samples.append((now, self._emit_total))
        t0, n0 = self._tput_samples[0]
        if now - t0 > 0.0:
            self.metrics.set_gauge(
                "serve_decode_tokens_per_second",
                (self._emit_total - n0) / (now - t0),
            )
        # Phase attribution rides the same cadence: the rolling window's
        # share-of-wall split lands as serve_phase_share{phase} gauges
        # (plus phase="residual" for the unattributed remainder).
        self.profiler.export_shares()

    def _adaptive_k(self) -> int:
        """Draft-window size from measured rolling acceptance.

        Throughput model per sub-round: emitted ≈ 1 + E[accepted] where
        E = a(1-a^K)/(1-a) for per-proposal acceptance a, at cost
        ≈ 1 + K·r target-steps (r = draft/target byte ratio; a small
        verify-width epsilon for ngram).  Pick K ∈ {2, 4, 8} maximizing
        emitted/cost, with two dampers: adapt only on ≥256 observed
        proposals (cold batchers keep the configured K), and switch only
        for a >5% modeled win, then freeze for 512 proposals — each new
        K compiles a fresh round variant, which is minutes of tunnel
        time if thrashed."""
        drafted = sum(d for d, _ in self._spec_recent)
        if drafted < 256 or self._spec_freeze > 0:
            return self._spec_k_active
        accepted = sum(a for _, a in self._spec_recent)
        a = min(0.98, max(0.02, accepted / drafted))
        r = self._draft_ratio

        def tput(k: int) -> float:
            expected = a * (1.0 - a ** k) / (1.0 - a)
            return (1.0 + expected) / (1.0 + k * r)

        best = max((2, 4, 8), key=tput)
        if (best != self._spec_k_active
                and tput(best) > 1.05 * tput(self._spec_k_active)):
            log.info(
                "adaptive spec_k: %d -> %d (rolling acceptance %.3f)",
                self._spec_k_active, best, a,
            )
            self._spec_k_active = best
            self._spec_freeze = 512
            self._spec_recent.clear()
        return self._spec_k_active

    def _mode_tps(self, mode: str) -> float:
        """Best per-row rate in the mode's sample window.  Best, not
        mean: a timed round that crossed a t_hi bucket recompiled, and
        averaging in compile time would let one such sample gate a mode
        off for a whole probe-backoff cycle."""
        win = self._mode_rate[mode]
        return max((t / dt for t, dt in win if dt > 0.0), default=0.0)

    def _spec_gate(self, live) -> tuple[bool, str | None]:
        """Dispatch-level adaptive gate for PROMPT-LOOKUP drafting:
        (use_spec, timed_mode).  ``use_spec`` picks this dispatch's
        round kind; ``timed_mode`` (None | "spec" | "plain") asks the
        dispatcher to run it as a TIMED measurement round — pipeline
        drained first, dispatch→consume wall time recorded as that
        mode's cost evidence (see the __init__ comment block for the
        design).  The contract: ngram mode is never materially slower
        than plain, because speculation must EARN its dispatches
        against measured evidence.

        Neural drafts always pass (their window already adapts via
        _adaptive_k).  For ngram, the decision is:

        1. acceptance floor — when EVERY live slot's rolling acceptance
           sits below ``ngram_breakeven``, speculation loses on any
           hardware: plain.  Slots with fewer than ``ngram_min_obs``
           observed proposals are optimistic (a fresh tenant gets
           measured before it gets gated), and the per-slot windows
           make this per-tenant — one high-acceptance co-tenant keeps
           speculative rounds on for its dispatches;
        2. measured throughput — with timed evidence of both kinds,
           plain when spec rounds measure slower end to end (this is
           what catches a platform whose (K+1)-wide verify costs far
           more than a plain step even at moderate acceptance);
        3. measurement scheduling — a timed round of each mode every
           ``ngram_measure_s`` seconds (first ones immediately) keeps
           both windows fresh while speculating.  While gated, the spec
           measurement is the probe and backs off exponentially
           (``ngram_probe_s`` base, x8 cap)."""
        self._gate_fallback = False
        if self.spec_mode != "ngram":
            return True, None
        below_floor = True
        for i, _ in live:
            win = self._slot_spec.get(i)
            d = sum(x for x, _ in win) if win else 0
            if d < self.ngram_min_obs:
                below_floor = False
                break
            if sum(a for _, a in win) / d >= self.ngram_breakeven:
                below_floor = False
                break
        gated = below_floor or (
            len(self._mode_rate["spec"]) >= 2
            and len(self._mode_rate["plain"]) >= 2
            and self._mode_tps("spec") < self._mode_tps("plain")
        )
        now = time.monotonic()
        timed = None
        # Spec checked first: ngram mode's default behavior is to
        # speculate, so the bootstrap's first timed round must be a
        # spec one (a short workload may only ever dispatch a few).
        if now >= self._ngram_next_meas["spec"]:
            timed = "spec"
            self._ngram_timed_sched["spec"] += 1
            if self._ngram_timed_sched["spec"] < 3:
                # Bootstrap: deadline stays due — re-time back-to-back
                # until two real samples exist (see __init__).
                pass
            elif gated:
                # This probe either re-earns speculation (its sample
                # flips the comparison within a short window) or backs
                # off so a persistent loser stops paying for probes.
                self._ngram_probe_scale = min(self._ngram_probe_scale * 2,
                                              8)
                self._ngram_next_meas["spec"] = (
                    now + self.ngram_probe_s * self._ngram_probe_scale
                )
            else:
                self._ngram_probe_scale = 1
                self._ngram_next_meas["spec"] = now + self.ngram_measure_s
        elif now >= self._ngram_next_meas["plain"]:
            timed = "plain"
            self._ngram_timed_sched["plain"] += 1
            if self._ngram_timed_sched["plain"] >= 3:
                self._ngram_next_meas["plain"] = now + self.ngram_measure_s
        if not gated:
            self._ngram_probe_scale = 1
        use_spec = timed == "spec" or (not gated and timed != "plain")
        # Fallback accounting is COMMITTED by _dispatch_round once the
        # round actually dispatches — a timed round abandoned after the
        # drain (rem <= 0) must not count as gate evidence.
        self._gate_fallback = gated and not use_spec
        return use_spec, timed

    def _t_hi(self, live, advance: int) -> int:
        """Static attention-read bound for the next round: the cache is
        only READ up to t_hi (pow2-bucketed from the live rows' positions
        after every in-flight step lands), so a round at position ~50
        streams 256 cache slots per step instead of max_seq.  Writes
        still target the full-size cache — only reads shrink.  Retired
        slots' garbage rows may sit past t_hi; their fully-masked
        attention output is never emitted."""
        need = max((r.pos_hint for _, r in live), default=0) + advance
        t = min(256, self.engine.max_seq)
        while t < need and t < self.engine.max_seq:
            t *= 2
        return min(t, self.engine.max_seq)

    def _dispatch_round(self, inflight=None) -> tuple | None:
        # Snapshot (slot, request) identity: by the time this round is
        # processed the slot may have been retired AND re-admitted to a new
        # request, whose stream must not receive this round's tokens.
        live = [(i, r) for i, r in enumerate(self._active) if r is not None]
        # Budget gate: a round only runs if SOME live row still needs
        # tokens beyond what's already in flight — otherwise the device
        # would burn a whole round (hundreds of ms of garbage compute on
        # the flagship pool) that no stream can consume.
        rems = [r.max_new - r.emitted - r.inflight_steps for _, r in live]
        rem = max(rems, default=0)
        if rem <= 0:
            return None
        # Past the budget gate a decode round WILL dispatch — the point
        # a prefill-only executor must refuse (its 1-token budgets are
        # covered at admission, so reaching here is a role violation).
        self._guard_decode()
        timed_mode = None
        use_spec = self.spec_mode is not None
        if use_spec:
            use_spec, timed_mode = self._spec_gate(live)
        if timed_mode is not None and inflight:
            # Timed measurement round (ngram gate): drain so the device
            # is idle at dispatch — the dispatch→consume interval is
            # then this round's exact end-to-end cost.
            while inflight:
                self._drain_one(inflight)
            live = [(i, r) for i, r in enumerate(self._active)
                    if r is not None]
            rems = [r.max_new - r.emitted - r.inflight_steps
                    for _, r in live]
            rem = max(rems, default=0)
            if rem <= 0:
                # The timed round never dispatched (the drain landed
                # every live row's budget) — roll back its scheduling
                # side effects so the probe/backoff state reflects only
                # evidence that was actually gathered.
                self._ngram_next_meas[timed_mode] = 0.0
                self._ngram_timed_sched[timed_mode] -= 1
                if timed_mode == "spec":
                    self._ngram_probe_scale = max(
                        1, self._ngram_probe_scale // 2
                    )
                return None
        if self._gate_fallback:
            # Point of no return: the plain round below WILL dispatch.
            self._ngram_fallback_rounds += 1
            self.metrics.inc("serve_spec_fallback_rounds_total")
        # Dispatch timestamp BEFORE the jit call: on backends where
        # dispatch is synchronous (CPU) a post-call stamp would make a
        # timed round's dispatch→consume interval read ~0.
        t0 = time.monotonic()
        use_top_p = any(
            r is not None and 0.0 < r.top_p < 1.0 for r in self._active
        )
        solo = len(live) == 1 and self._pending.empty()
        # Shared-round amortization (the multi-request generalization of
        # round-4's solo fix): each dispatch through the tunnel costs
        # ~60-100 ms regardless of its step count, so 8-step shared
        # rounds at batch 8 are ~90% overhead — the round-4 artifact's
        # 2x batched-throughput gap.  When no admission is waiting, size
        # the round to the smallest LIVE remaining budget (bucketed):
        # every co-tenant consumes the whole round, the first row to
        # finish wastes at most the bucket overshoot, and a pending
        # request never waits behind an oversized round (pending
        # non-empty keeps rounds short).  Rows whose budget is already
        # covered in flight are garbage rows either way and don't size.
        shared_rem = min((x for x in rems if x > 0), default=rem)
        # Block-deferred requests (paged overflow) are waiting admissions
        # just like _pending ones: a long "stable" round would sit between
        # them and the slot/blocks a retirement frees, inflating their
        # TTFT — keep rounds short while any are deferred.
        stable = (
            self._pending.empty()
            and not solo
            and not (self.paged and self._overflow)
        )
        if use_spec:
            # Adaptive K from measured rolling acceptance, then size the
            # sub-round count for compute parity at THAT K.
            K = self._adaptive_k()
            if self.spec_mode == "ngram":
                base_rounds = self.steps_per_round
            else:
                base_rounds = max(1, int(round(
                    self.steps_per_round / (1.0 + K * self._draft_ratio)
                )))
            # Solo/stable amortization, tail-sized: cover the remaining
            # budget in one dispatch when a small multiple of the base
            # sub-round count can (each sub-round emits <= K + 1).
            # Timed rounds stay at the base config: budget-sized
            # multiples mint fresh static shapes mid-run, and a timed
            # round that compiles records compile time as "cost".
            n_rounds = base_rounds
            if timed_mode != "spec" and (solo or stable):
                per = base_rounds * (K + 1)
                cover = rem if solo else shared_rem
                mult = next((m for m in (1, 2, 4) if m * per >= cover), 4)
                n_rounds = mult * base_rounds
            advance = n_rounds * (K + 1)
            t_hi = self._t_hi(live, advance)
            pages_op = jnp.asarray(self._pages) if self.paged else None
            # Speculative dispatch is its own phase (the draft+verify
            # program enqueue — self-time subtracts from the enclosing
            # decode_dispatch, which keeps the gate/sizing overhead).
            with self.profiler.phase("spec_draft"):
                if self.spec_mode == "ngram":
                    self._dev, (toks, ns, lps) = self._round_spec_ngram_jit(
                        self.params, self._dev, self.bank.banked, use_top_p,
                        n_rounds, t_hi, K, pages_op,
                    )
                else:
                    self._dev, (toks, ns, lps) = self._round_spec_jit(
                        self.params, self.draft_params, self._dev,
                        self.bank.banked, use_top_p, n_rounds, t_hi, K,
                        pages_op,
                    )
            if self.paged and self.engine.attn_impl == "paged_kernel":
                self.metrics.inc("serve_paged_kernel_rounds_total")
            # Budget-gate charge: EXPECTED tokens from rolling acceptance,
            # not the all-accepted worst case — a worst-case charge at
            # acceptance a<1 makes the gate think the budget is covered
            # and stall the device between dispatches (measured: spec at
            # acceptance 0.77 barely beat plain purely on this stall).
            # pos_hint stays worst-case: it sizes the t_hi attention-read
            # bound, where an underestimate would truncate reads.
            drafted = sum(d for d, _ in self._spec_recent)
            a_hat = (
                sum(a for _, a in self._spec_recent) / drafted
                if drafted >= 64 else 0.5
            )
            expected = max(n_rounds, int(n_rounds * (1.0 + a_hat * K)))
            for _, r in live:
                r.inflight_steps += expected
                r.pos_hint += advance
            timed_dt = None
            if timed_mode == "spec":
                # Block HERE (device was idle at t0, so this interval is
                # the round's exact cost on any backend — async TPU or
                # sync-dispatch CPU); tokens are counted at consume.
                jax.block_until_ready(toks)
                timed_dt = time.monotonic() - t0
            self._round_count += 1
            return (
                "spec", self._round_count, live, toks, ns, lps, expected,
                t0, timed_dt,
            )
        n_steps = self.steps_per_round
        # Timed rounds keep the base step count (same reason as the
        # spec branch: a budget-sized bucket is a fresh compile whose
        # time would be recorded as round cost).
        if timed_mode == "plain":
            pass
        elif solo:
            # Smallest solo bucket covering the remaining budget — the
            # tail round stops wasting steps past the request's end.
            n_steps = next(
                (b for b in self.solo_buckets if b >= rem),
                self.solo_buckets[-1],
            )
        elif stable:
            n_steps = next(
                (b for b in self.solo_buckets if b >= shared_rem),
                self.solo_buckets[-1],
            )
        t_hi = self._t_hi(live, n_steps)
        # Paged mode: the page tables ride as a per-dispatch operand
        # snapshot (1 KB h2d) — the host owns the mapping, so a retired
        # slot's row reads all-trash from the very next dispatch.
        self._dev, (toks, lps) = self._round_jit(
            self.params, self._dev, self.bank.banked,
            self.cbank.banked if self.cbank else None,
            use_top_p, n_steps, t_hi,
            jnp.asarray(self._pages) if self.paged else None,
        )
        if self.paged and self.engine.attn_impl == "paged_kernel":
            # A/B attribution for the fused-kernel rollout: operators can
            # split fleet decode throughput by which read path served it.
            self.metrics.inc("serve_paged_kernel_rounds_total")
        for _, r in live:
            r.inflight_steps += n_steps
            r.pos_hint += n_steps
        timed_dt = None
        if timed_mode == "plain":
            jax.block_until_ready(toks)
            timed_dt = time.monotonic() - t0
        self._round_count += 1
        return ("round", self._round_count, live, toks, lps,
                t0, timed_dt)

    def _emit(self, req: _Request, tok: int, round_id: int,
              lp: float = 0.0) -> None:
        req.emitted += 1
        self._emit_total += 1
        self._warmed = True
        req.t_last = time.monotonic()
        if req.emitted == 1:
            req.t_first = req.t_last
        self._interleave_log.append((round_id, req.slot))
        req.emitted_ids.append(int(tok))
        # One queue item carries both — the handle collects logprobs on
        # ITS side of the thread boundary (no per-token list snapshots).
        req.out.put((int(tok), float(lp)))

    def _retire(self, slot: int) -> None:
        with self.profiler.phase("retire"):
            self._retire_inner(slot)

    def _retire_inner(self, slot: int) -> None:
        req = self._active[slot]
        if req is not None:
            # Self-pollution guard (serve/canary.py): canary probes ride
            # the reserved tenant and are excluded from every user-facing
            # SLO series — the latency histograms (their outside-in view
            # is probe_ttft_seconds, and synthetic traffic must not move
            # the serve_ttft_p95 rule) and the goodput-vs-total tenant
            # counters (a probe is not tenant work).  Completion/token
            # throughput counters still count them: the scheduler really
            # did that work, and bench's cb_canary_overhead_x reads it.
            probe = req.tenant == PROBE_TENANT
            if not req.deadline_expired:
                # An expired row is a shed, not a completion — it must
                # not pollute the completion/latency series.
                self.metrics.inc("serve_completions_total")
                self.metrics.observe(
                    "serve_generated_tokens", float(req.emitted)
                )
                # C32 latency budget surface: time-to-first-token and mean
                # inter-token gap per request (emission-side wall-clock —
                # tokens reach the host in round batches, so the gap is the
                # per-request STREAMING rate, dispatch cadence included).
                # Each lands twice: unlabeled (the all-tenant aggregate
                # the bench and the default p95 rule read) and
                # tenant-labeled (the per-tenant SLO view).
                if req.emitted >= 1 and req.t_first > 0.0 and not probe:
                    ttft = req.t_first - req.t_submit
                    self.metrics.observe("serve_ttft_seconds", ttft)
                    self.metrics.observe(
                        "serve_ttft_seconds", ttft, tenant=req.tenant
                    )
                if req.emitted >= 2 and req.t_first > 0.0 and not probe:
                    gap = (req.t_last - req.t_first) / (req.emitted - 1)
                    self.metrics.observe("serve_inter_token_seconds", gap)
                    self.metrics.observe(
                        "serve_inter_token_seconds", gap,
                        tenant=req.tenant,
                    )
            # Per-tenant goodput accounting: every generated token
            # counts in the total; only tokens of requests that
            # FINISHED inside their latency budget count as goodput.
            # A zero inc still mints the tenant's series, so a tenant
            # whose every request sheds is visible at rate 0 instead of
            # absent.
            if not probe:
                good = (
                    req.emitted
                    if not (req.deadline_expired or req.aborted) else 0
                )
                self.metrics.inc(
                    "serve_tenant_tokens_total", float(req.emitted),
                    tenant=req.tenant,
                )
                self.metrics.inc(
                    "serve_tenant_goodput_tokens_total", float(good),
                    tenant=req.tenant,
                )
            self._journal(req, self._finish_reason(req))
            # Completion sentinel LAST — journal-before-close, like
            # every shed/abort path: when a caller's stream ends, the
            # journal record already exists, so a workload capture
            # taken right after ``result()`` returns can never miss
            # the request it just consumed (serve/replay.py's
            # recorder depends on this happens-before).
            req.out.put(None)
        if self.paged and req is not None and req.blocks:
            # Point the slot at the trash block and release the blocks'
            # references — a shared prefix block stays pinned while any
            # other slot still references it; a registered block whose
            # last reference drops parks in the content cache's LRU
            # (reusable by the next matching prompt) instead of the free
            # list.  Rounds already in flight carry their dispatch-time
            # table snapshot and finish (device FIFO) before any
            # admission that could reuse these blocks — immediate reuse
            # is safe; and a retired slot's garbage writes only target
            # positions past its prompt, which never map to shared or
            # registered blocks.
            self._pages[slot, :] = 0
            for blk in req.blocks:
                self._pool.release(blk)
            req.blocks = []
        self._slot_spec.pop(slot, None)
        self._active[slot] = None
        self._update_util_gauges()

    @staticmethod
    def _finish_reason(req: _Request) -> str:
        """Journal vocabulary for a retired row (serve/journal.py):
        deadline beats aborted beats budget; anything retired early
        with budget remaining stopped on EOS."""
        if req.deadline_expired:
            return "deadline"
        if req.aborted:
            return "aborted"
        if req.emitted >= req.max_new:
            return "budget"
        return "eos"

    def _journal(self, req: _Request, reason: str) -> None:
        """One lifecycle record per terminal outcome — completion,
        shed, or abort — into the bounded journal ring.  Scheduler
        thread (and the submit thread for door sheds); pure host
        bookkeeping, no device work."""
        self.journal.append(RequestRecord(
            tenant=req.tenant,
            trace_id=(
                req.trace_ctx.trace_id if req.trace_ctx is not None
                else ""
            ),
            reason=reason,
            path=req.path,
            # Replay-completeness contract (serve/replay.py): every
            # terminal record carries the full reproduction tuple.
            # prompt_ids is [] only for precomputed-prefill rows — the
            # prompt never existed at this layer.
            prompt_ids=[int(t) for t in req.ids.tolist()],
            max_new=req.max_new,
            temperature=req.temperature,
            top_p=req.top_p,
            seed=req.seed,
            deadline_s=(
                req.deadline - req.t_submit
                if req.deadline is not None else 0.0
            ),
            golden_hash=golden_hash(req.emitted_ids),
            replica=req.route_replica,
            route_reason=req.route_reason,
            slot=req.slot,
            prompt_tokens=req.prompt_tokens,
            tokens=req.emitted,
            queue_wait_s=(
                max(0.0, req.t_admit - req.t_submit)
                if req.t_admit > 0.0 else 0.0
            ),
            ttft_s=(
                max(0.0, req.t_first - req.t_submit)
                if req.t_first > 0.0 else 0.0
            ),
            tpot_s=(
                (req.t_last - req.t_first) / (req.emitted - 1)
                if req.emitted >= 2 and req.t_first > 0.0 else 0.0
            ),
            prefix_blocks=(
                (req.prefix_tokens or 0) // self.page_size
                if self.paged else 0
            ),
            spec_drafted=req.spec_drafted,
            spec_accepted=req.spec_accepted,
            deadline_expired=req.deadline_expired,
            t_submit=req.t_submit,
            t_done=time.monotonic(),
            # Probe admission tagging: the `obs requests --no-probes`
            # filter and the /debug/requests probes=0 query key on this.
            # Migration evidence rides the same extra dict: a stream cut
            # by an export is stamped migrated, a request resumed from
            # another replica's blocks names where it came from.
            extra={
                **({"probe": True} if req.tenant == PROBE_TENANT else {}),
                **({"migrated": True} if req.migrated else {}),
                **(
                    {"migrated_from": req.migrated_from}
                    if req.migrated_from else {}
                ),
            },
        ))

    def _shed_expired(self, req: _Request) -> None:
        """Drop an expired request AT ADMISSION: no prefill or decode
        round ever runs for it — the "dropped, not computed" half of the
        deadline contract."""
        req.deadline_expired = True
        req.aborted = True
        self.metrics.inc(
            "serve_shed_total", reason="deadline", tenant=req.tenant
        )
        self._journal(req, "deadline")
        req.out.put(None)

    def _expire_live(self, slot: int, req: _Request) -> bool:
        """Mid-stream deadline check at round granularity: an expired row
        retires before its fetched tokens are emitted, freeing the slot
        instead of decoding to budget for a caller that stopped waiting.
        Rounds already in flight were dispatched before the expiry was
        observable; their output for this row is dropped here."""
        if req.deadline is None or time.monotonic() <= req.deadline:
            return False
        req.deadline_expired = True
        req.aborted = True
        self.metrics.inc(
            "serve_shed_total", reason="deadline", tenant=req.tenant
        )
        self._retire(slot)
        return True

    def _process_admits(self, items: list) -> None:
        """Consume a RUN of consecutive admit items with ONE device_get
        over all their first tokens.  A burst of n admissions otherwise
        pays n sequential host<->device round trips (~35-100 ms each on
        the tunneled TPU) — measured as the dominant cost of an 8-request
        arrival burst in the r5 bench's first capture."""
        firsts = jax.device_get([(it[2], it[3]) for it in items])
        for (_, req, _, _), (first_dev, lp_dev) in zip(items, firsts):
            req.inflight_steps = max(0, req.inflight_steps - 1)
            if req.trace_ctx is not None:
                # Prefill segment: admit dispatch → first token on host.
                global_tracer.add_span(
                    "serve.prefill", parent=req.trace_ctx,
                    start=req.t_admit, end=time.monotonic(),
                    slot=req.slot,
                )
            if self._active[req.slot] is not req:
                continue  # already retired
            if self._expire_live(req.slot, req):
                continue
            first = int(first_dev)
            hit_eos = self.eos_id >= 0 and first == self.eos_id
            if not hit_eos:
                self._emit(req, first, self._round_count, float(lp_dev))
            if hit_eos or req.emitted >= req.max_new:
                self._retire(req.slot)

    def _drain_one(self, inflight: collections.deque) -> None:
        """Pop and process the next in-flight item; consecutive admits
        are coalesced into one fetch (_process_admits).  Consumption is
        phase-attributed here, at the item boundary: the first-token
        fetch of an admit completes admission, a spec round's fetch +
        accept walk is the verify cost, everything else is plain decode
        consumption (retire nests inside and subtracts its self-time)."""
        item = inflight.popleft()
        if item[0] == "admit" and inflight and inflight[0][0] == "admit":
            batch = [item]
            while inflight and inflight[0][0] == "admit":
                batch.append(inflight.popleft())
            with self.profiler.phase("admission"):
                self._process_admits(batch)
        else:
            name = {
                "admit": "admission",
                "admit_round": "admission",
                "spec": "spec_verify",
            }.get(item[0], "decode_consume")
            with self.profiler.phase(name):
                self._process(item)
        self._update_util_gauges()

    def _process(self, item: tuple) -> None:
        """Consume one in-flight item — the only place the scheduler blocks
        on the device.  Every branch fetches ALL of its device arrays in
        ONE ``jax.device_get`` — sequential ``np.asarray`` fetches each
        pay a full host<->device round trip (~35 ms on the tunneled TPU;
        two of them were most of the solo-latency gap vs the one-shot
        engine)."""
        if item[0] == "admit":
            self._process_admits([item])
            return
        if item[0] == "admit_round":
            (_, round_id, req, first_dev, lp_dev, toks_dev, lps_dev,
             t_disp) = item
            if self.collect_logprobs:
                first_dev, lp_dev, toks, lps = jax.device_get(
                    (first_dev, lp_dev, toks_dev, lps_dev)
                )
            else:
                first_dev, lp_dev, toks = jax.device_get(
                    (first_dev, lp_dev, toks_dev)
                )
                lps = np.zeros_like(toks, np.float32)
            n_steps = toks.shape[0]
            req.inflight_steps = max(
                0, req.inflight_steps - 1 - n_steps
            )
            if req.trace_ctx is not None:
                # Fused cold-start: admit dispatch → results on host
                # covers prefill AND the first round in one program.
                global_tracer.add_span(
                    "serve.prefill", parent=req.trace_ctx,
                    start=req.t_admit, end=time.monotonic(),
                    slot=req.slot, fused=True,
                )
            if self._active[req.slot] is not req:
                return
            if self._expire_live(req.slot, req):
                return
            first = int(first_dev)
            if self.eos_id >= 0 and first == self.eos_id:
                self._retire(req.slot)
                return
            self._emit(req, first, round_id, float(lp_dev))
            if req.emitted >= req.max_new:
                self._retire(req.slot)
                return
            done = False
            n0 = req.emitted
            for t in range(n_steps):
                tok = int(toks[t, req.slot])
                if self.eos_id >= 0 and tok == self.eos_id:
                    done = True
                    break
                self._emit(req, tok, round_id, float(lps[t, req.slot]))
                if req.emitted >= req.max_new:
                    done = True
                    break
            if req.trace_ctx is not None and req.emitted > n0:
                global_tracer.add_span(
                    "serve.round", parent=req.trace_ctx,
                    start=t_disp, end=time.monotonic(),
                    round=round_id, tokens=req.emitted - n0,
                )
            if done:
                self._retire(req.slot)
            return
        if item[0] == "spec":
            (_, round_id, live, toks_dev, ns_dev, lps_dev, charged,
             t_disp, timed_dt) = item
            # [R, B, K+1] / [R, B] — ONE blocking fetch for the batch.
            if self.collect_logprobs:
                toks, ns, lps = jax.device_get((toks_dev, ns_dev, lps_dev))
            else:
                toks, ns = jax.device_get((toks_dev, ns_dev))
                lps = np.zeros(toks.shape, np.float32)
            # Dispatch charged the worst-case advance (every draft
            # accepted); now that ns is known, release the in-flight
            # charge and walk pos_hint back to the device's REAL
            # position so t_hi doesn't ratchet upward.
            k_used = toks.shape[2] - 1  # the dispatch's (possibly
            # adapted) K — derive from the fetched shape, never from
            # self.spec_k, which may have changed since dispatch.
            worst = toks.shape[0] * (k_used + 1)
            for i, req in live:
                # Release exactly what dispatch charged (the expected-
                # value budget charge); pos_hint walks back from its
                # worst-case advance to the device's real position.
                req.inflight_steps = max(0, req.inflight_steps - charged)
                req.pos_hint -= worst - int(ns[:, i].sum())
            # The rolling window for _adaptive_k accumulates below, in
            # the SAME guarded per-row loop as the telemetry counters —
            # garbage sub-rounds of retired/EOS'd rows must not count
            # (post-EOS streams settle into cycles ngram accepts at high
            # rate, which would steer K on traffic that doesn't exist).
            d0, a0 = self._spec_drafted, self._spec_accepted
            e0 = {i: r.emitted for i, r in live}
            for i, req in live:
                if self._active[i] is not req:
                    continue
                if self._expire_live(i, req):
                    continue
                done = False
                n0 = req.emitted
                row_d = row_a = 0
                for r in range(toks.shape[0]):
                    n = int(ns[r, i])
                    self._spec_drafted += k_used
                    self._spec_accepted += n - 1
                    row_d += k_used
                    row_a += n - 1
                    for t in range(n):
                        tok = int(toks[r, i, t])
                        if self.eos_id >= 0 and tok == self.eos_id:
                            done = True
                            break
                        self._emit(req, tok, round_id, float(lps[r, i, t]))
                        if req.emitted >= req.max_new:
                            done = True
                            break
                    if done:
                        break
                if row_d:
                    # Per-slot rolling window — the ngram gate's
                    # per-tenant acceptance evidence (_spec_gate) —
                    # plus the request's own journal evidence.
                    self._slot_spec.setdefault(
                        i, collections.deque(maxlen=8)
                    ).append((row_d, row_a))
                    req.spec_drafted += row_d
                    req.spec_accepted += row_a
                if req.trace_ctx is not None and req.emitted > n0:
                    global_tracer.add_span(
                        "serve.round", parent=req.trace_ctx,
                        start=t_disp, end=time.monotonic(),
                        round=round_id, tokens=req.emitted - n0,
                        speculative=True,
                    )
                if done:
                    self._retire(i)
            drafted_now = self._spec_drafted - d0
            self._spec_recent.append(
                (drafted_now, self._spec_accepted - a0)
            )
            self._spec_freeze = max(0, self._spec_freeze - drafted_now)
            if timed_dt is not None:
                # PER-ROW rate: a round computes the full batch width
                # whatever the live count, so tokens-per-emitting-row
                # per second is the quantity comparable across modes
                # (raw tokens/s would make a round timed at 1 live row
                # look slower than one timed at 4).  A mode's FIRST
                # timed round is compile warmup — its dt would poison
                # the window by orders of magnitude.
                self._ngram_timed_rec["spec"] += 1
                deltas = [r.emitted - e0[i] for i, r in live]
                rows = sum(1 for d in deltas if d > 0)
                if rows and self._ngram_timed_rec["spec"] > 1:
                    self._mode_rate["spec"].append(
                        (sum(deltas) / rows, timed_dt)
                    )
            return
        _, round_id, live, toks_dev, lps_dev, t_disp, timed_dt = item
        if self.collect_logprobs:  # [T, B] — one blocking fetch
            toks, lps = jax.device_get((toks_dev, lps_dev))
        else:
            toks = np.asarray(toks_dev)
            lps = np.zeros_like(toks, np.float32)
        n_steps = toks.shape[0]
        for _, req in live:
            req.inflight_steps = max(0, req.inflight_steps - n_steps)
        e0 = {i: r.emitted for i, r in live}
        for i, req in live:
            if self._active[i] is not req:
                continue  # retired (or slot re-admitted) mid-flight
            if self._expire_live(i, req):
                continue
            done = False
            n0 = req.emitted
            for t in range(n_steps):
                tok = int(toks[t, i])
                if self.eos_id >= 0 and tok == self.eos_id:
                    done = True
                    break
                self._emit(req, tok, round_id, float(lps[t, i]))
                if req.emitted >= req.max_new:
                    done = True
                    break
            if req.trace_ctx is not None and req.emitted > n0:
                # ONE span per (round, request), dispatch → host — the
                # decode-segment granularity tracing promises (never
                # per-token).
                global_tracer.add_span(
                    "serve.round", parent=req.trace_ctx,
                    start=t_disp, end=time.monotonic(),
                    round=round_id, tokens=req.emitted - n0,
                )
            if done:
                self._retire(i)
        if timed_dt is not None:
            # Per emitting row, same normalization and first-sample
            # (compile warmup) skip as the spec branch.
            self._ngram_timed_rec["plain"] += 1
            deltas = [r.emitted - e0[i] for i, r in live]
            rows = sum(1 for d in deltas if d > 0)
            if rows and self._ngram_timed_rec["plain"] > 1:
                self._mode_rate["plain"].append(
                    (sum(deltas) / rows, timed_dt)
                )

    def _loop(self) -> None:
        inflight: collections.deque = collections.deque()
        try:
            while not self._stop.is_set():
                # Quiesce point (run_quiesced): barriers run at a round
                # boundary with the dispatch pipeline fully drained, so
                # a barrier thunk sees every device write landed and no
                # program in flight — the pause migration export/import
                # splices through.  Checked FIRST each iteration: live
                # rows pause between rounds, idle loops wake via _wake.
                if not self._barriers.empty():
                    while inflight:
                        self._drain_one(inflight)
                    self._run_barriers()
                any_active = any(r is not None for r in self._active)
                if (not any_active and self._pending.empty()
                        and not inflight
                        and not (self.paged and self._overflow)):
                    # Keep sampling while idle so the decode-throughput
                    # gauge decays to 0 as the window ages out, instead
                    # of freezing at the last burst's rate forever.
                    self._update_util_gauges()
                    self._wake.wait(timeout=0.1)
                    self._wake.clear()
                    continue
                # Admission: fill free slots from the pending queue.  When
                # all slots are busy, catching up on in-flight work below
                # is what eventually frees one.
                while True:
                    slot = self._free_slot()
                    if slot < 0:
                        break
                    # Block-pressure deferrals (paged mode) retry ahead
                    # of new arrivals — FIFO fairness across the stall.
                    if self.paged and self._overflow:
                        req = self._overflow.popleft()
                    else:
                        try:
                            req = self._pending.get_nowait()
                        except queue.Empty:
                            break
                    # Admission phase (profiler): pop-to-dispatch, with
                    # the paged block plan and the admit program dispatch
                    # as nested sub-phases (their self-time subtracts, so
                    # shares stay disjoint).  push/pop instead of `with`
                    # keeps the continue/break control flow readable.
                    self.profiler.push("admission")
                    try:
                        # Deadline gate BEFORE any allocation or device
                        # program: work that expired while queued is shed,
                        # never prefilled.
                        if (
                            req.deadline is not None
                            and time.monotonic() > req.deadline
                        ):
                            self._shed_expired(req)
                            continue
                        if self.paged:
                            with self.profiler.phase("paged_plan"):
                                planned = self._paged_plan(req)
                            if not planned:
                                if not any(
                                    r is not None for r in self._active
                                ):
                                    # Nothing is holding blocks (refcount-0
                                    # cached blocks are evictable), so the
                                    # request simply cannot fit — fail it,
                                    # don't spin.
                                    req.aborted = True
                                    if req.on_admit is not None:
                                        req.on_admit()
                                    self._journal(req, "no_capacity")
                                    req.out.put(None)
                                    continue
                                # Back at the FRONT: this req was popleft'd
                                # for the retry, and append would rotate the
                                # deferred queue — later arrivals would leap
                                # ahead of it on every pressure stall
                                # (ADVICE: FIFO across block-pressure
                                # deferrals).  Deferral holds NO block
                                # references (the plan released any shared
                                # acquisitions on failure); the retry
                                # re-matches against the then-current cache.
                                self._overflow.appendleft(req)
                                break
                        try:
                            # Idle cold solo start → fuse admission with the
                            # first tail-sized round in one dispatch (plain
                            # mode; prefix/disagg admissions keep their own
                            # cheaper programs).  The prefix lookup runs once
                            # here and feeds both the gate and the unfused
                            # admit path.
                            entry = (
                                self._match_prefix(req.ids)
                                if req.aidx == 0 and req.precomputed is None
                                and not self.paged
                                else None
                            )
                            fused = (
                                self.spec_mode is None
                                and not self.paged  # paged admit is unfused
                                and not inflight
                                and req.precomputed is None
                                and req.max_new > 1
                                and self._pending.empty()
                                and not any(
                                    r is not None for r in self._active
                                )
                                and entry is None
                            )
                            with self.profiler.phase("prefill_dispatch"):
                                if fused:
                                    inflight.append(
                                        self._dispatch_admit_round(req, slot)
                                    )
                                else:
                                    inflight.append(
                                        self._dispatch_admit(req, slot, entry)
                                    )
                        except BaseException:
                            # The popped request is in neither _pending nor
                            # _active yet — the crash drain below would miss
                            # it and its caller would block forever.
                            req.aborted = True
                            if req.on_admit is not None:
                                req.on_admit()
                            self._journal(req, "aborted")
                            req.out.put(None)
                            raise
                    finally:
                        self.profiler.pop()
                # Keep the device busy: dispatch the next round before
                # fetching results of previous ones.  A None dispatch
                # means every live row's budget is already covered by
                # in-flight rounds — process one instead so the loop
                # always makes progress toward retiring those rows.
                # A pending quiesce barrier pauses NEW dispatch: each
                # round already in flight still lands (the barrier drain
                # above consumes them), but pipelining further rounds
                # would race the barrier's purpose — a migration abort
                # cannot cut a stream whose whole budget was dispatched
                # ahead of the boundary.
                if (any(r is not None for r in self._active)
                        and self._barriers.empty()):
                    # decode_dispatch self-time = gate/sizing + the plain
                    # round's program enqueue; the spec program enqueue
                    # (spec_draft) and any timed-round drain consumption
                    # nest inside and subtract.
                    with self.profiler.phase("decode_dispatch"):
                        item = self._dispatch_round(inflight)
                    if item is not None:
                        inflight.append(item)
                    elif inflight:
                        self._drain_one(inflight)
                # Catch up to the pipeline depth (or fully, when idle).
                while inflight and (
                    len(inflight) > self.pipeline_depth
                    or not any(r is not None for r in self._active)
                ):
                    self._drain_one(inflight)
        except Exception:
            log.exception("batcher scheduler died; draining requests")
        finally:
            # Drain on ANY exit — crashed/stopped schedulers must not
            # leave callers blocked on .result() forever, and drained
            # requests are marked aborted so servers report 5xx, not a
            # silently truncated 200.
            with self._lifecycle:
                self._dead = True
                # Fail queued barriers under the SAME lock acquisition
                # that sets _dead: run_quiesced either enqueued before
                # this drain (failed here) or sees _dead and raises —
                # never a waiter parked on a dead scheduler.
                while True:
                    try:
                        _, box = self._barriers.get_nowait()
                    except queue.Empty:
                        break
                    box["error"] = RuntimeError(
                        "batcher scheduler stopped"
                    )
                    box["done"].set()
                for r in self._active:
                    if r is not None:
                        r.aborted = True
                        self._journal(r, "aborted")
                        r.out.put(None)
                if self.paged:
                    while self._overflow:
                        r = self._overflow.popleft()
                        r.aborted = True
                        self._journal(r, "aborted")
                        r.out.put(None)
                while True:
                    try:
                        r = self._pending.get_nowait()
                    except queue.Empty:
                        break
                    r.aborted = True
                    # A drained precomputed request will never be seated:
                    # fire its admit hook so the prefill pool's inflight
                    # semaphore doesn't leak a permit.
                    if r.on_admit is not None:
                        r.on_admit()
                    self._journal(r, "aborted")
                    r.out.put(None)
