"""Wire-level KV block migration: warm state that survives the replica.

Until now a replica's paged-KV pool was process-local — a drain or a
kill destroyed every warm chain and aborted every mid-stream request on
it.  This module is the transfer plane that decouples the logical cache
content from the process that happens to hold it (the FlexNPU /
VirtualFlow decoupling, PAPERS.md): page-aligned physical blocks are
serialized into a deterministic, **chain-hash-addressed** wire format
and spliced into another replica's pool through the same
acquire/register path a local admission uses, so a migrated chain is
indistinguishable from one the destination prefilled itself.

Wire format (``pack`` / ``unpack``)
-----------------------------------

A payload is plain JSON (the admin plane's lingua franca — replicas
already speak it) with base64 block bodies::

    {
      "version": 1,
      "page_size": 8,
      "replica": "lm-a",
      "geometry": {                      # per cache leaf, the shape of
        "k":   {"dtype": "int8",        # ONE block's contents —
                "shape": [L, KH, P, Dh]},  # arr[:, blk] per leaf
        "k_s": {"dtype": "float32", "shape": [L, KH, P]},
        ...
      },
      "blocks": [                        # sorted by hash: deterministic
        {"hash": "<32 hex>", "data": {"k": "<b64>", ...}},
        ...
      ],
      "requests": [                      # live streams at export time —
        {"trace_id": ..., "tenant": ...,  # the gateway's resume
         "prompt_tokens": n, "emitted": n},  # manifest
      ],
      "aborted": 0,
    }

The addressing is PR 5's chained block hash (``kv_blocks.chunk_hashes``:
h_i covers the whole prefix, so a hash names both the tokens AND the
attention context that produced the block's K/V bytes).  Only
*registered* blocks travel — full pages whose content is final and
read-only.  A partial tail block is never shipped: per the CoW rule it
is recomputed private on the destination (the resume path re-extends
from the last full page), exactly as a local prefix-cache hit would.

Determinism: the payload carries **no timestamps and no identifiers
minted from ambient randomness** — block order is sorted by hash, leaf
order is sorted by name, and the JSON is dumped with sorted keys by the
HTTP layer.  Two exports of the same pool state are byte-identical,
which is what makes the chaos drill replayable.

``BlockMigrator`` is the gateway-side coordinator: victim
``POST /admin/export`` → destination ``POST /admin/import``, capped
retries per stage with ``migrate_failures_total{stage=...}`` minted on
every failed attempt.  A migration that exhausts its retries is
reported as ``None`` and the caller falls back to today's behavior
(re-prefill from scratch on the next owner) — degraded, never wrong.
Fault sites ``migrate.export`` / ``migrate.import`` fire in the
``LmServer`` admin handlers and ``migrate.resume`` in the gateway's
stream-failover path (utils/faults.py).
"""

from __future__ import annotations

import base64
import http.client
import json
import threading
import urllib.error
import urllib.request

import numpy as np

from ..utils.clock import Clock, RealClock
from ..utils.metrics import MetricsRegistry, global_metrics

WIRE_VERSION = 1


def pack(snapshot: dict) -> dict:
    """Serialize a batcher export snapshot (``migrate_export``'s return
    value: numpy block bodies keyed by hash bytes) into the JSON-safe
    wire payload.  Deterministic: blocks sorted by hash, leaves sorted
    by name, no ambient time."""
    blocks = []
    for h, leaves in sorted(snapshot.get("blocks", []), key=lambda kv: kv[0]):
        data = {
            name: base64.b64encode(
                np.ascontiguousarray(leaves[name]).tobytes()
            ).decode("ascii")
            for name in sorted(leaves)
        }
        blocks.append({"hash": h.hex(), "data": data})
    geometry = {
        name: {"dtype": str(g["dtype"]), "shape": [int(s) for s in g["shape"]]}
        for name, g in sorted(snapshot.get("geometry", {}).items())
    }
    return {
        "version": WIRE_VERSION,
        "page_size": int(snapshot.get("page_size", 0)),
        "replica": str(snapshot.get("replica", "")),
        "geometry": geometry,
        "blocks": blocks,
        "requests": list(snapshot.get("requests", [])),
        "aborted": int(snapshot.get("aborted", 0)),
    }


def unpack(payload: dict) -> dict:
    """Parse and validate a wire payload back into numpy block bodies.
    Raises ``ValueError`` on a version/geometry/encoding problem — the
    import side refuses malformed state instead of splicing garbage
    into a live pool."""
    if int(payload.get("version", -1)) != WIRE_VERSION:
        raise ValueError(
            f"migrate wire version {payload.get('version')!r} "
            f"!= {WIRE_VERSION}"
        )
    geometry = payload.get("geometry") or {}
    if not isinstance(geometry, dict) or not geometry:
        raise ValueError("migrate payload missing geometry")
    shapes: dict[str, tuple] = {}
    dtypes: dict[str, np.dtype] = {}
    for name in sorted(geometry):
        g = geometry[name]
        try:
            dtypes[name] = np.dtype(g["dtype"])
            shapes[name] = tuple(int(s) for s in g["shape"])
        except (KeyError, TypeError) as e:
            raise ValueError(f"bad geometry for leaf {name!r}: {e}") from e
    blocks: list[tuple[bytes, dict[str, np.ndarray]]] = []
    for ent in payload.get("blocks", []):
        try:
            h = bytes.fromhex(ent["hash"])
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"bad block hash: {e}") from e
        data = ent.get("data") or {}
        if sorted(data) != sorted(shapes):
            raise ValueError(
                f"block {ent.get('hash')}: leaves {sorted(data)} "
                f"!= geometry {sorted(shapes)}"
            )
        leaves: dict[str, np.ndarray] = {}
        for name in sorted(data):
            raw = base64.b64decode(data[name])
            want = int(np.prod(shapes[name])) * dtypes[name].itemsize
            if len(raw) != want:
                raise ValueError(
                    f"block {ent.get('hash')} leaf {name}: "
                    f"{len(raw)} bytes != expected {want}"
                )
            leaves[name] = np.frombuffer(raw, dtypes[name]).reshape(
                shapes[name]
            )
        blocks.append((h, leaves))
    return {
        "page_size": int(payload.get("page_size", 0)),
        "geometry": {
            name: {"dtype": dtypes[name], "shape": shapes[name]}
            for name in sorted(shapes)
        },
        "blocks": blocks,
        "requests": list(payload.get("requests", [])),
    }


def payload_bytes(payload: dict) -> bytes:
    """The canonical encoding of a wire payload: sorted keys, compact
    separators.  Byte-identical across runs for identical pool state —
    the two-run determinism surface the tests pin."""
    return json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()


class BlockMigrator:
    """Gateway-side migration coordinator: one ``migrate()`` call moves
    a victim's registered blocks to a destination replica over the
    admin plane, with capped per-stage retries.  Returns a result dict
    (hashes moved, byte/block counts, live-request manifest) on
    success, ``None`` when a stage exhausts its retries — the caller
    treats that as "no migration happened" and relies on re-prefill.

    Injected ``clock`` is the only time source (FakeClock-replayable);
    metrics land in the caller's registry so the gateway's federation
    view carries the migration counters."""

    # Lock contract (graftcheck lockcheck): the last-result cache is
    # shared between the drain worker thread that runs migrations and
    # admin/debug readers.
    _GUARDED_BY = {
        "_lock": ("_last",),
    }

    def __init__(
        self,
        *,
        clock: Clock | None = None,
        metrics: MetricsRegistry | None = None,
        timeout_s: float = 30.0,
        max_attempts: int = 2,
    ):
        self.clock = clock or RealClock()
        self.metrics = metrics or global_metrics
        self.timeout_s = float(timeout_s)
        self.max_attempts = max(1, int(max_attempts))
        self._lock = threading.Lock()
        self._last: dict | None = None

    # -- HTTP ---------------------------------------------------------------
    def _post(self, url: str, body: dict) -> tuple[int, dict]:
        data = json.dumps(body, sort_keys=True).encode()
        req = urllib.request.Request(
            url, data=data, headers={"content-type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except ValueError:
                payload = {}
            return e.code, payload
        except (OSError, ValueError, http.client.HTTPException) as e:
            raise RuntimeError(f"migrate transport: {e}") from e

    def _attempt(self, stage: str, url: str, body: dict) -> dict | None:
        """One stage (export or import) with capped retries.  Every
        failed attempt mints ``migrate_failures_total{stage=}``; None
        after the cap — the caller degrades to re-prefill."""
        for _ in range(self.max_attempts):
            try:
                code, payload = self._post(url, body)
            except RuntimeError:
                code, payload = 0, {}
            if code == 200:
                return payload
            self.metrics.inc("migrate_failures_total", stage=stage)
        return None

    # -- the coordinator ----------------------------------------------------
    def migrate(
        self,
        victim_url: str,
        dest_url: str,
        *,
        victim: str = "",
    ) -> dict | None:
        """Move the victim's registered blocks to the destination:
        ``POST victim/admin/export`` → ``POST dest/admin/import``.
        Live streams on the victim keep running — the caller re-homes
        the moved chains on its router FIRST and only then calls
        ``abort_live()``, so a cut stream's re-dispatch finds the new
        owner already warm.  Returns ``{"hashes", "blocks", "bytes",
        "imported", "requests", "seconds"}`` or ``None``."""
        t0 = self.clock.now()
        exported = self._attempt(
            "export", victim_url + "/admin/export",
            {"abort_live": False, "include_blocks": True},
        )
        if exported is None:
            return None
        size = len(payload_bytes(exported))
        imported = self._attempt(
            "import", dest_url + "/admin/import", exported
        )
        if imported is None:
            return None
        n_blocks = len(exported.get("blocks", []))
        result = {
            "victim": victim,
            "hashes": [ent["hash"] for ent in exported.get("blocks", [])],
            "blocks": n_blocks,
            "bytes": size,
            "imported": int(imported.get("imported", 0)),
            "requests": list(exported.get("requests", [])),
            "seconds": self.clock.now() - t0,
        }
        self.metrics.inc("migrate_blocks_total", float(n_blocks))
        self.metrics.inc("migrate_bytes_total", float(size))
        self.metrics.observe("migrate_seconds", result["seconds"])
        with self._lock:
            self._last = dict(result)
        return result

    def abort_live(self, victim_url: str) -> int:
        """Cut the victim's live streams stamped *migrated* (an
        abort-only export: no block bodies).  Called AFTER the import
        landed and the caller's router re-homed the chains — the relay
        failover re-dispatches the moment a stream is cut, and that
        re-route must find the destination warm.  Returns the streams
        cut (0 when the call itself failed: the wait-for-inflight
        fallback still drains them)."""
        ab = self._attempt(
            "export", victim_url + "/admin/export",
            {"abort_live": True, "include_blocks": False},
        )
        return int((ab or {}).get("aborted", 0))

    def last(self) -> dict | None:
        """The most recent successful migration result (a copy)."""
        with self._lock:
            return dict(self._last) if self._last is not None else None
