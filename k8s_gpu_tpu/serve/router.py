"""Prefix-affinity fleet router + telemetry-driven replica autoscaling.

One ``ContinuousBatcher`` is fast (paged KV, block-granular prefix
caching, speculative rounds) but serves one device.  Fleet scale means N
replicas behind a front-end — and a naive round-robin front-end destroys
the prefix-cache win: a shared system prompt's KV blocks end up cold on
every replica instead of warm on one.  This module is the front-end
policy plane (ROADMAP item 1):

- **FleetRouter** — routes each request by the *page-aligned chain
  hash* of its prompt (``kv_blocks.chunk_hashes`` — the exact key the
  paged pool's content cache indexes by, so "the router's chain" and
  "the replica's warm blocks" are the same bytes).  Traffic sharing a
  prefix chain lands on the chain's owner replica; brand-new chains are
  placed by rendezvous hashing on the chain ROOT (the first full page —
  every future sharer of the prefix hashes to the same root, so the
  mapping re-converges even if the router's warm table was evicted or
  the router restarted); prompts with no full shareable page fall
  through to least-loaded placement.  Candidates are scored on cache
  affinity × live load read through a ``FleetCollector`` with bounded
  staleness, a two-threshold hysteresis band marks replicas *hot* (a
  hot replica sheds NEW prefixes to other replicas but keeps serving
  the chains already warm on it, so load spills without thrashing the
  cache), and every tie breaks on the replica name — routing is a pure
  function of (request sequence, replica set, load snapshot), which is
  what the two-run determinism test pins.
- **FleetAutoscaler** — a deterministic scale FSM driven by the
  federated alert signals (``router_rule_pack``): queue backlog and
  TTFT-p95 burn scale UP (sized by pending / target-per-replica,
  clamped to ``max_step``), sustained low slot fill scales DOWN one
  step, and a cooldown after every action prevents flapping.  Scale-down
  is prefix-aware: ``FleetRouter.scale_down_victim`` picks the replica
  owning the fewest warm chains, and ``drain`` announces it so its hash
  range re-homes (new traffic immediately routes elsewhere; the warm
  table entries re-assign on next touch) before the replica retires.

The router is transport-agnostic: replicas register a ``submit``
callable (an in-process ``ContinuousBatcher.submit``, or an HTTP client
posting ``/generate`` with ``x-route-replica``/``x-route-reason``
headers for the journal stamp).  ``dispatch`` retries on replica
failure — a dead replica is marked down, its traffic re-routes, and no
request is lost (the chaos test injects ``serve.submit`` faults through
``utils/faults.py`` to pin exactly this).
"""

from __future__ import annotations

import collections
import hashlib
import logging
import math
import threading
from dataclasses import dataclass, field

import numpy as np

from ..utils.alerts import AlertingRule, RecordingRule
from ..utils.clock import Clock, RealClock
from ..utils.metrics import MetricsRegistry, global_metrics
from .kv_blocks import shareable_chain

log = logging.getLogger("k8s_gpu_tpu.router")

# Decision vocabulary (the serve_router_decisions_total{reason=} label
# and the journal's route_reason):
#   affinity  routed by chain hash — to the warm owner, or by rendezvous
#             for a brand-new chain (the canonical cache home either way)
#   load      no shareable full page: least-loaded placement
#   fallback  the chain's warm owner was unusable (hot / draining /
#             down / canary-unhealthy): re-scored onto the best
#             remaining replica
ROUTE_REASONS = ("affinity", "load", "fallback")


@dataclass
class RouteDecision:
    """One routing decision, with its audit trail."""

    replica: str
    reason: str
    chain_depth: int = 0   # full shareable pages in the prompt
    warm_depth: int = 0    # deepest chain prefix already warm on replica
    scores: dict = field(default_factory=dict)  # replica -> score


class FleetRouter:
    """Prefix-affinity router over a named replica set (module
    docstring for the model).  Thread-safe; every route/registration
    call serializes on one lock — the policy is host-side bookkeeping,
    never device work."""

    # Lock contract, statically verified by k8s_gpu_tpu/analysis
    # (lockcheck) and enforced under real concurrency by
    # utils.faults.guard_declared in the race stress test: the replica
    # sets and the warm-chain table are shared between every routing /
    # registration / dispatch thread; staleness bookkeeping has its own
    # lock so a slow scrape can't stall routing.
    _GUARDED_BY = {
        "_lock": (
            "_replicas", "_draining", "_down", "_unhealthy", "_hot",
            "_chains", "_chain_counts", "_drain_hooks",
        ),
        "_refresh_lock": ("_last_refresh",),
    }

    def __init__(
        self,
        *,
        page_size: int = 64,
        collector=None,
        metrics: MetricsRegistry | None = None,
        clock: Clock | None = None,
        staleness_s: float = 10.0,
        hot_enter: float = 0.85,
        hot_exit: float = 0.70,
        affinity_weight: float = 1.0,
        load_weight: float = 1.0,
        pending_norm: float = 16.0,
        max_tracked_chains: int = 4096,
    ):
        """``page_size`` must match the replicas' paged-KV page size —
        the chain hashes only line up with the block cache when the
        chunking does.  ``collector`` (a ``utils.federation
        .FleetCollector``) supplies live per-replica load; without one
        every replica reads load 0 and routing is pure affinity +
        name-order tie-breaks.  ``staleness_s`` bounds how old the load
        snapshot may be before a route triggers a fresh scrape.
        ``hot_enter``/``hot_exit`` are the hysteresis band: a replica
        whose load crosses ``hot_enter`` sheds new prefixes until it
        drops below ``hot_exit``.  ``max_tracked_chains`` bounds the
        warm-chain table (LRU eviction — an evicted chain re-homes by
        rendezvous, which lands it back on the same replica)."""
        self.page = max(1, int(page_size))
        self.collector = collector
        self.metrics = metrics if metrics is not None else global_metrics
        self.clock = clock or RealClock()
        self.staleness_s = float(staleness_s)
        self.hot_enter = float(hot_enter)
        self.hot_exit = float(hot_exit)
        self.affinity_weight = float(affinity_weight)
        self.load_weight = float(load_weight)
        self.pending_norm = max(1.0, float(pending_norm))
        self.max_tracked_chains = max(16, int(max_tracked_chains))
        self._lock = threading.Lock()
        self._replicas: dict[str, object] = {}   # name -> submit | None
        self._draining: set[str] = set()
        self._down: set[str] = set()
        # Canary quarantine (serve/canary.py): replicas the prober
        # walked to unhealthy.  Same eligibility effect as a drain — no
        # NEW traffic, in-flight and warm chains untouched — but a
        # separate set so recovery re-admits without touching
        # drain/down bookkeeping.
        self._unhealthy: set[str] = set()
        self._hot: set[str] = set()
        # name -> callable invoked on drain(name) — the LmServer.drain
        # hook that flips the replica's /readyz to 503.
        self._drain_hooks: dict[str, object] = {}
        # chain hash -> owning replica, LRU order (oldest first).
        self._chains: "collections.OrderedDict[bytes, str]" = (
            collections.OrderedDict()
        )
        self._chain_counts: dict[str, int] = {}
        # Staleness bookkeeping has its OWN lock: the scrape must run
        # OUTSIDE self._lock (a hung HTTP target would otherwise stall
        # every concurrent route for its whole timeout).
        self._refresh_lock = threading.Lock()
        self._last_refresh = float("-inf")

    # -- replica set -------------------------------------------------------
    def add_replica(self, name: str, submit=None, on_drain=None) -> None:
        """Register a replica; ``submit(ids, *, route=..., **kw)`` is
        what ``dispatch`` calls (route-only use may pass None).
        ``on_drain`` is invoked (no args) when ``drain(name)`` announces
        a scale-down — wire ``LmServer.drain`` here so the replica's
        /readyz flips to 503 the moment the router stops routing to it."""
        with self._lock:
            self._replicas[str(name)] = submit
            self._down.discard(str(name))
            self._unhealthy.discard(str(name))
            if on_drain is not None:
                self._drain_hooks[str(name)] = on_drain
            self._chain_counts.setdefault(str(name), 0)
            self._export_gauges()

    def remove_replica(self, name: str) -> None:
        """Deregister and forget the replica's warm chains (they
        re-home by rendezvous on next touch)."""
        with self._lock:
            self._replicas.pop(name, None)
            self._draining.discard(name)
            self._down.discard(name)
            self._unhealthy.discard(name)
            self._drain_hooks.pop(name, None)
            self._hot.discard(name)
            for h in [h for h, r in self._chains.items() if r == name]:
                del self._chains[h]
            self._chain_counts.pop(name, None)
            self.metrics.remove_gauge(
                "serve_router_chains_owned", replica=name
            )
            self._export_gauges()

    def drain(self, name: str) -> int:
        """Announce a scale-down: the replica stops receiving new
        requests and its hash range re-homes (warm entries reassign as
        they are touched).  Returns the warm-chain count it owned —
        the work that will re-home.  The replica's ``on_drain`` hook
        runs after the lock drops (it flips /readyz on the replica —
        its own locks, its own HTTP surface)."""
        with self._lock:
            if name not in self._replicas:
                return 0
            self._draining.add(name)
            self.metrics.inc("serve_router_drains_total")
            self._export_gauges()
            owned = self._chain_counts.get(name, 0)
            hook = self._drain_hooks.get(name)
        if hook is not None:
            try:
                hook()
            except Exception:
                log.exception("drain hook failed for %s", name)
        return owned

    def mark_down(self, name: str) -> None:
        """Exclude a replica observed failing (dispatch does this); its
        chains re-home lazily, exactly like a drain it didn't ask for."""
        with self._lock:
            if name in self._replicas:
                self._down.add(name)
                self._export_gauges()

    def mark_up(self, name: str) -> None:
        with self._lock:
            self._down.discard(name)
            self._export_gauges()

    def mark_unhealthy(self, name: str) -> None:
        """Quarantine on a canary verdict (serve/canary.py walked the
        replica to unhealthy): no NEW traffic, exactly a drain's
        eligibility effect — in-flight requests and warm chains are
        untouched, so a recovered replica resumes with its cache
        intact."""
        with self._lock:
            if name in self._replicas:
                self._unhealthy.add(name)
                self.metrics.inc("serve_router_quarantines_total")
                self._export_gauges()

    def mark_healthy(self, name: str) -> None:
        """Re-admit after probe recovery (the FSM's recover_k streak)."""
        with self._lock:
            self._unhealthy.discard(name)
            self._export_gauges()

    def replica_names(self) -> list[str]:
        with self._lock:
            return sorted(self._replicas)

    def chains_owned(self, name: str) -> int:
        with self._lock:
            return self._chain_counts.get(name, 0)

    def scale_down_victim(self) -> str | None:
        """The prefix-aware scale-down choice: the eligible replica
        owning the FEWEST warm chains (least cache state to lose; ties
        break on name).  None with <= 1 eligible replica."""
        with self._lock:
            eligible = self._eligible_locked()
            if len(eligible) <= 1:
                return None
            return min(
                eligible,
                key=lambda r: (self._chain_counts.get(r, 0), r),
            )

    # -- load --------------------------------------------------------------
    def _eligible_locked(self) -> list[str]:
        out = []
        for name in sorted(self._replicas):
            if (
                name in self._draining or name in self._down
                or name in self._unhealthy
            ):
                continue
            if self.collector is not None:
                up = self.collector.registry.gauge(
                    "fleet_replica_up", replica=name
                )
                if up is not None and up < 0.5:
                    continue
            out.append(name)
        return out

    def _maybe_refresh(self) -> None:
        """The bounded-staleness contract: a load snapshot older than
        ``staleness_s`` triggers one scrape before the next route reads
        it.  Runs WITHOUT the router lock (the collector serializes its
        own passes) so a slow scrape target can't stall routing."""
        if self.collector is None:
            return
        now = self.clock.now()
        with self._refresh_lock:
            if now - self._last_refresh < self.staleness_s:
                return
            self._last_refresh = now
        try:
            self.collector.scrape_once()
        except Exception:
            pass  # stale beats absent; liveness gates eligibility

    def _loads_locked(self) -> dict[str, float]:
        """Per-replica load in [0, 1] from the federated gauges (call
        ``_maybe_refresh`` first, outside the lock).  No collector →
        all zeros (affinity-only routing)."""
        if self.collector is None:
            return {name: 0.0 for name in self._replicas}
        reg = self.collector.registry
        loads = {}
        for name in self._replicas:
            fill = reg.gauge("serve_slot_fill_ratio", replica=name) or 0.0
            kv = reg.gauge(
                "serve_kv_occupancy_ratio", replica=name
            ) or 0.0
            pend = reg.gauge(
                "serve_pending_requests", replica=name
            ) or 0.0
            # Queue pressure dominates: pending work queues BEHIND the
            # slots, so it saturates the pending term before fill/kv
            # alone can mark a replica hot.
            loads[name] = min(1.0, (
                0.4 * fill + 0.2 * kv
                + 0.4 * min(1.0, pend / self.pending_norm)
            ))
        # Hysteresis band update rides every load read.
        for name, load in loads.items():
            if load >= self.hot_enter:
                self._hot.add(name)
            elif load <= self.hot_exit:
                self._hot.discard(name)
        return loads

    def _score(self, warm: int, depth: int, load: float) -> float:
        aff = warm / depth if depth else 0.0
        return self.affinity_weight * aff - self.load_weight * load

    @staticmethod
    def _rendezvous(key: bytes, pool: list[str]) -> str:
        """Highest-random-weight owner of ``key`` among ``pool`` —
        stable under membership change (only keys owned by a removed
        replica move)."""
        return max(
            pool,
            key=lambda r: (
                hashlib.blake2b(
                    key + r.encode(), digest_size=8
                ).digest(),
                r,
            ),
        )

    # -- routing -----------------------------------------------------------
    def route(self, ids, exclude: set | None = None) -> RouteDecision:
        """Choose a replica for a prompt (token ids).  ``exclude`` is a
        per-request blacklist (dispatch's retry path).  Raises
        RuntimeError when no replica is eligible."""
        ids = np.asarray(ids, np.int32).ravel()
        # Only FULL pages are shareable, and at least one suffix token
        # must remain for the extend — kv_blocks.shareable_chain is the
        # ONE implementation of that cap, shared with _paged_plan's
        # acquire loop and the HTTP front-end's routing key, so the
        # router's chain and the block cache's chain agree by
        # construction.
        hashes = shareable_chain(ids, self.page)
        depth = len(hashes)
        self._maybe_refresh()
        with self._lock:
            loads = self._loads_locked()
            eligible = [
                r for r in self._eligible_locked()
                if not exclude or r not in exclude
            ]
            if not eligible:
                raise RuntimeError(
                    "FleetRouter: no eligible replica "
                    f"({len(self._replicas)} registered, "
                    f"{len(self._draining)} draining, "
                    f"{len(self._down)} down, "
                    f"{len(self._unhealthy)} unhealthy)"
                )
            # Warm lookup: per replica, the DEEPEST chain prefix of this
            # prompt already owned by it.  ``warm_any`` remembers that
            # some (now unusable) replica was warm — that distinguishes
            # a "fallback" from a brand-new chain.
            warm: dict[str, int] = {}
            warm_any = False
            for i in range(depth - 1, -1, -1):
                o = self._chains.get(hashes[i])
                if o is None:
                    continue
                warm_any = True
                if o in eligible and o not in warm:
                    warm[o] = i + 1
            scores = {
                r: self._score(warm.get(r, 0), depth, loads.get(r, 0.0))
                for r in eligible
            }
            if depth == 0:
                # No shareable page: pure load placement.
                chosen = min(
                    eligible, key=lambda r: (loads.get(r, 0.0), r)
                )
                reason = "load"
            else:
                owner = None
                if warm:
                    owner = sorted(
                        warm.items(), key=lambda kv: (-kv[1], kv[0])
                    )[0][0]
                if owner is not None:
                    # Warm traffic sticks to its owner even when the
                    # owner is hot — the hysteresis sheds NEW prefixes,
                    # never thrashes warm cache state (a genuinely
                    # overloaded owner sheds through Overloaded at
                    # dispatch, which retries elsewhere).
                    chosen, reason = owner, "affinity"
                else:
                    pool = [
                        r for r in eligible if r not in self._hot
                    ] or eligible
                    if not warm_any:
                        # Brand-new chain: rendezvous on the chain root
                        # (h1 covers the first page — every sharer of
                        # the prefix computes the same root) among the
                        # non-hot replicas.
                        chosen = self._rendezvous(hashes[0], pool)
                        reason = "affinity"
                    else:
                        # Warm only somewhere unusable (draining or
                        # down replica): best remaining by score.
                        chosen = sorted(
                            pool, key=lambda r: (-scores[r], r)
                        )[0]
                        reason = "fallback"
            self._record_chains_locked(hashes, chosen)
            self.metrics.inc(
                "serve_router_decisions_total", reason=reason
            )
            return RouteDecision(
                replica=chosen,
                reason=reason,
                chain_depth=depth,
                warm_depth=warm.get(chosen, 0),
                scores=scores,
            )

    def _record_chains_locked(self, hashes, chosen: str) -> None:
        for h in hashes:
            prev = self._chains.pop(h, None)
            if prev is not None:
                self._chain_counts[prev] = (
                    self._chain_counts.get(prev, 1) - 1
                )
            self._chains[h] = chosen
            self._chain_counts[chosen] = (
                self._chain_counts.get(chosen, 0) + 1
            )
        while len(self._chains) > self.max_tracked_chains:
            _, owner = self._chains.popitem(last=False)
            self._chain_counts[owner] = (
                self._chain_counts.get(owner, 1) - 1
            )
        if hashes:
            self._export_gauges()

    def rehome(self, hashes, new_owner: str) -> int:
        """Reassign warm-chain ownership after a wire-level block
        migration (serve/migrate.py): the destination replica now
        physically holds these chain hashes, so affinity routing must
        send their tenants THERE — without this, the gateway would
        keep routing to the drained victim's re-prefill path and the
        migrated bytes would sit unused until LRU eviction.  Unknown
        owners are refused (0): re-homing onto a retired replica would
        route traffic into a wall.  Returns the chains re-homed."""
        with self._lock:
            if new_owner not in self._replicas:
                return 0
            hashes = list(hashes)
            self._record_chains_locked(hashes, new_owner)
            if hashes:
                self.metrics.inc(
                    "serve_router_rehomed_chains_total",
                    float(len(hashes)),
                )
            return len(hashes)

    def owner_map(self) -> dict[str, str]:
        """The warm-chain table as ``{hex hash: owner}``, sorted by
        hash — the gateway fleet's agreement surface
        (``/admin/ownermap``, serve/frontend.py).  Serialized with
        ``json.dumps(..., sort_keys=True)`` this is the byte string
        two gateways compare digests of."""
        with self._lock:
            return {
                h.hex(): owner
                for h, owner in sorted(self._chains.items())
            }

    def install_chains(self, mapping: dict[bytes, str]) -> int:
        """REPLACE the warm-chain table with a reconstructed
        chain→owner map (serve/frontend.py rebuilt it from replica
        ``/debug/chains`` scrapes + rendezvous tie-breaks).  Entries
        naming an unregistered owner are dropped — installing them
        would route traffic into a wall, exactly the ``rehome``
        refusal.  Insertion in sorted-hash order makes the resulting
        LRU order (and therefore ``snapshot()`` and ``owner_map()``)
        a pure function of the mapping — the two-run byte-identity
        the reconstruction contract pins.  Returns entries installed."""
        with self._lock:
            self._chains.clear()
            for name in self._chain_counts:
                self._chain_counts[name] = 0
            n = 0
            for h, owner in sorted(mapping.items()):
                if owner not in self._replicas:
                    continue
                self._chains[h] = owner
                self._chain_counts[owner] = (
                    self._chain_counts.get(owner, 0) + 1
                )
                n += 1
            while len(self._chains) > self.max_tracked_chains:
                _, owner = self._chains.popitem(last=False)
                self._chain_counts[owner] = (
                    self._chain_counts.get(owner, 1) - 1
                )
                n -= 1
            self._export_gauges()
            return n

    def _export_gauges(self) -> None:
        """Refresh the serve_router_* gauges.  Lock held by caller
        (every mutation path calls this before releasing _lock)."""
        for name in self._replicas:
            self.metrics.set_gauge(
                "serve_router_chains_owned",
                float(self._chain_counts.get(name, 0)),
                replica=name,
            )
        self.metrics.set_gauge(
            "serve_router_replicas", float(len(self._replicas))
        )
        self.metrics.set_gauge(
            "serve_router_replicas_draining",
            float(len(self._draining)),
        )
        self.metrics.set_gauge(
            "serve_router_replicas_unhealthy",
            float(len(self._unhealthy)),
        )

    # -- dispatch ----------------------------------------------------------
    def dispatch(self, ids, **submit_kwargs):
        """Route then submit, retrying on replica failure: a replica
        whose submit raises is marked DOWN and the request re-routes
        (``serve_router_rehash_total``) — zero requests are lost to a
        replica death.  An ``Overloaded`` shed retries elsewhere
        WITHOUT marking the replica down (full is a load signal, not a
        death); ``ValueError``/``KeyError`` are REQUEST faults (prompt
        too long, unknown adapter) that would fail identically on
        every replica — they propagate immediately and never poison
        the replica set.  When every candidate was tried, the last
        replica error is re-raised (so a fleet-wide ``Overloaded``
        stays a shed signal, not a routing RuntimeError).  Returns
        ``(handle, RouteDecision)``."""
        from .batcher import Overloaded

        tried: set[str] = set()
        last_err: Exception | None = None
        for _ in range(max(1, len(self.replica_names()))):
            try:
                dec = self.route(ids, exclude=tried)
            except RuntimeError:
                if last_err is not None:
                    raise last_err
                raise
            with self._lock:
                fn = self._replicas.get(dec.replica)
            if fn is None:
                raise RuntimeError(
                    f"replica {dec.replica!r} registered without a "
                    "submit callable"
                )
            try:
                handle = fn(
                    ids, route=(dec.replica, dec.reason),
                    **submit_kwargs,
                )
                return handle, dec
            except Overloaded as e:
                tried.add(dec.replica)
                last_err = e
                self.metrics.inc("serve_router_rehash_total")
            except (ValueError, KeyError):
                raise
            except Exception as e:
                tried.add(dec.replica)
                last_err = e
                self.mark_down(dec.replica)
                self.metrics.inc("serve_router_rehash_total")
        raise last_err if last_err is not None else RuntimeError(
            "FleetRouter: dispatch found no replica"
        )

    # -- read surface ------------------------------------------------------
    def snapshot(self) -> dict:
        """The router's explain view (``obs route`` / the demo): per
        replica, its role flags, warm-chain count, and current load."""
        self._maybe_refresh()
        with self._lock:
            loads = self._loads_locked()
            return {
                "page_size": self.page,
                "tracked_chains": len(self._chains),
                "replicas": [
                    {
                        "replica": name,
                        "chains": self._chain_counts.get(name, 0),
                        "load": round(loads.get(name, 0.0), 4),
                        "hot": name in self._hot,
                        "draining": name in self._draining,
                        "down": name in self._down,
                        "unhealthy": name in self._unhealthy,
                    }
                    for name in sorted(self._replicas)
                ],
            }


# -- autoscaling --------------------------------------------------------------

# Alert names the autoscaler listens for (router_rule_pack emits them).
SCALE_UP_ALERTS = frozenset({"FleetQueueBacklog", "FleetTtftBurn"})
SCALE_DOWN_ALERTS = frozenset({"FleetLowFill"})


def router_rule_pack(
    collector=None,
    *,
    backlog_per_replica: float = 4.0,
    backlog_for_s: float = 10.0,
    ttft_slo_s: float = 2.0,
    ttft_for_s: float = 10.0,
    ttft_window_s: float = 60.0,
    low_fill: float = 0.25,
    low_fill_for_s: float = 30.0,
) -> list:
    """The serving-plane scaling triggers, as ordinary alert rules over
    a federated registry (``utils/alerts.py`` — same FSM, same
    determinism):

    - ``fleet_pending_per_replica`` (recording): fleet pending-request
      sum over live replicas — scale-invariant backlog;
    - ``FleetQueueBacklog``: sustained backlog above the per-replica
      target → scale up;
    - ``fleet_ttft_p95`` (recording) + ``FleetTtftBurn``: fleet TTFT
      p95 above the SLO → scale up (latency burn, the signal queue
      depth alone misses when requests are long).  The p95 is computed
      from the WINDOWED increase of the federated ``_bucket`` series
      (``ctx.rate`` per ``le``, merged across replicas) — a cumulative
      quantile would let one compile-era 30 s TTFT keep the alert
      firing forever, which both blocks every future scale-down and
      pages on history instead of state;
    - ``FleetLowFill``: fleet-average slot fill sustained below
      ``low_fill`` → scale down one step.

    ``collector`` is accepted for wiring symmetry (the federated
    ``_bucket`` series it writes are what the p95 reads); a
    non-federated registry (unit tests, one replica) falls back to the
    registry's own histogram reservoirs."""

    def _p95(ctx):
        series = ctx.series("serve_ttft_seconds_bucket")
        if not series:
            return ctx.percentile("serve_ttft_seconds", 0.95)
        merged = {}
        for le in sorted({dict(lbls).get("le") for lbls in series}):
            if le is None:
                continue
            merged[(("le", le),)] = ctx.rate(
                "serve_ttft_seconds_bucket", ttft_window_s, le=le
            )
        from ..utils.federation import bucket_quantile

        v = bucket_quantile(merged, 0.95)
        return 0.0 if v is None else v

    return [
        RecordingRule(
            "fleet_pending_per_replica",
            lambda ctx: ctx.gauge("serve_pending_requests")
            / max(1.0, ctx.gauge("fleet_replicas_up", 1.0)),
        ),
        RecordingRule("fleet_ttft_p95", _p95),
        AlertingRule(
            "FleetQueueBacklog",
            lambda ctx: ctx.gauge("fleet_pending_per_replica"),
            above=backlog_per_replica, for_s=backlog_for_s,
            annotation=(
                "fleet backlog at {value:.1f} pending per replica — "
                "scale up"
            ),
        ),
        AlertingRule(
            "FleetTtftBurn",
            lambda ctx: ctx.gauge("fleet_ttft_p95"),
            above=ttft_slo_s, for_s=ttft_for_s, severity="page",
            annotation=(
                "fleet TTFT p95 at {value:.2f}s over the SLO — scale up"
            ),
        ),
        AlertingRule(
            "FleetLowFill",
            lambda ctx: ctx.gauge("serve_slot_fill_ratio"),
            below=low_fill, for_s=low_fill_for_s,
            annotation=(
                "fleet slot fill at {value:.0%} — sustained idle "
                "capacity, scale down"
            ),
        ),
    ]


@dataclass
class ScaleDecision:
    target: int
    reason: str      # backlog | ttft_burn | low_fill | hold | cooldown
    direction: int   # +1 up, -1 down, 0 hold


class FleetAutoscaler:
    """Deterministic replica-count FSM over the alert signals.

    ``decide`` is a pure function of (replicas, pending, firing set,
    clock time, last-action time): the same scripted sequence produces
    the same decisions under ``FakeClock`` — the up/down/cooldown test
    replays exactly that.  Scale-up is SIZED (``ceil(pending /
    target_pending_per_replica)``, stepped by at most ``max_step``);
    scale-down is one replica at a time (cache state re-homes per
    drain, and one step per cooldown bounds the churn)."""

    def __init__(
        self,
        *,
        min_replicas: int = 1,
        max_replicas: int = 8,
        clock: Clock | None = None,
        cooldown_s: float = 30.0,
        max_step: int = 2,
        target_pending_per_replica: float = 4.0,
        metrics: MetricsRegistry | None = None,
    ):
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.clock = clock or RealClock()
        self.cooldown_s = float(cooldown_s)
        self.max_step = max(1, int(max_step))
        self.target_pending_per_replica = max(
            1.0, float(target_pending_per_replica)
        )
        self.metrics = metrics if metrics is not None else global_metrics
        self._last_action = float("-inf")

    def decide(
        self,
        *,
        replicas: int,
        pending: float = 0.0,
        firing=(),
        now: float | None = None,
    ) -> ScaleDecision:
        """``firing``: alert names currently firing (the evaluator's
        ``active_alerts`` filtered to state == "firing")."""
        now = self.clock.now() if now is None else now
        firing = set(firing)
        replicas = max(1, int(replicas))
        in_cooldown = now - self._last_action < self.cooldown_s
        up = firing & SCALE_UP_ALERTS
        if up:
            if in_cooldown:
                return self._hold(replicas, "cooldown")
            need = (
                math.ceil(pending / self.target_pending_per_replica)
                if pending > 0 else replicas + 1
            )
            step = min(self.max_step, max(1, need - replicas))
            target = min(self.max_replicas, replicas + step)
            if target > replicas:
                reason = (
                    "backlog" if "FleetQueueBacklog" in up
                    else "ttft_burn"
                )
                return self._act(replicas, target, reason, now)
            return self._hold(replicas, "hold")
        if firing & SCALE_DOWN_ALERTS and pending <= 0:
            if in_cooldown:
                return self._hold(replicas, "cooldown")
            target = max(self.min_replicas, replicas - 1)
            if target < replicas:
                return self._act(replicas, target, "low_fill", now)
        return self._hold(replicas, "hold")

    def _hold(self, replicas: int, reason: str) -> ScaleDecision:
        self.metrics.set_gauge(
            "serve_autoscaler_target_replicas", float(replicas)
        )
        return ScaleDecision(target=replicas, reason=reason, direction=0)

    def _act(
        self, replicas: int, target: int, reason: str, now: float
    ) -> ScaleDecision:
        self._last_action = now
        direction = 1 if target > replicas else -1
        self.metrics.inc(
            "serve_autoscaler_actions_total",
            direction="up" if direction > 0 else "down",
        )
        self.metrics.set_gauge(
            "serve_autoscaler_target_replicas", float(target)
        )
        return ScaleDecision(
            target=target, reason=reason, direction=direction
        )
