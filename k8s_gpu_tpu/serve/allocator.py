"""Paged-KV block allocation and page planning for the batcher.

Split out of the original ``serve/batcher.py`` monolith (ISSUE 20):
this module owns the *block plane* — every host-side interaction with
``kv_blocks.BlockPool`` (page-table rows, chain acquire/register
planning for admissions) plus the wire-level block export/import the
migration plane (serve/migrate.py) and the disaggregated prefill
handover ride on.  ``migrate_export(hashes=...)`` is the per-chain
filter the prefill workers use to ship exactly one prompt's pages.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from .kv_blocks import chunk_hashes, shareable_depth
from .scheduler import _Request, prompt_bucket

log = logging.getLogger("k8s_gpu_tpu.serve")


class AllocatorMixin:
    """BlockPool-interaction half of ``ContinuousBatcher``: page
    planning at admission, page-table maintenance, and quiesced
    block export/import over the migration wire format."""

    # -- paged-KV block allocator (host side) ------------------------------
    def _blocks_needed(self, bucket: int, max_new: int) -> int:
        return -(-(bucket + max_new) // self.page_size)

    def _set_page_row(self, slot: int, blocks: list[int]):
        """Install a slot's block list in the host page table (entries
        past the allocation → trash block 0) and return the row as the
        admit program's device operand."""
        self._pages[slot, :] = 0
        self._pages[slot, :len(blocks)] = blocks
        return jnp.asarray(self._pages[slot])

    @property
    def _free_blocks(self) -> list[int]:
        """Allocatable block ids (free + refcount-0 cached) — the leak
        check surface tests pin after shutdown."""
        return self._pool.allocatable_blocks()

    def _paged_plan(self, req: _Request) -> bool:
        """Block allocation (and prefix matching) for one paged
        admission — scheduler thread only.  On success ``req.blocks``
        holds shared-then-fresh block ids and ``req.prefix_tokens`` is
        the shared token count (None = dense-splice path: precomputed
        rows, MoE, adapters).  False = block pressure, caller defers;
        no references are held on failure."""
        page = self.page_size
        if req.precomputed is not None:
            # Disagg handover: the dense row splices into fresh blocks;
            # no sharing (its geometry may carry left pad, and its K/V
            # come from a different program than the pool's own extend).
            need = self._blocks_needed(int(req.precomputed[2]), req.max_new)
            blocks = self._pool.alloc(need)
            if blocks is None:
                return False
            req.blocks = blocks
            req.prefix_tokens = None
            return True
        n = int(req.ids.size)
        if not (self._paged_share and req.aidx == 0):
            bucket = prompt_bucket(n, self.engine.max_seq)
            blocks = self._pool.alloc(self._blocks_needed(bucket, req.max_new))
            if blocks is None:
                return False
            req.blocks = blocks
            req.prefix_tokens = None
            return True
        # Automatic block-granular prefix sharing: acquire the longest
        # chain of cached full prompt pages (capped by
        # kv_blocks.shareable_depth — at least one suffix token must
        # remain so the extend produces first-token logits; the router
        # and the HTTP front-end key on the same cap), then allocate
        # the private tail.  Acquire BEFORE alloc: the fresh allocation
        # may evict LRU blocks, and a refcount pins the matched prefix
        # against that eviction.
        hashes = chunk_hashes(req.ids, page)
        shared: list[int] = []
        for h in hashes[: shareable_depth(n, page)]:
            blk = self._pool.acquire(h)
            if blk is None:
                break
            shared.append(blk)
        s = len(shared)
        fresh = self._pool.alloc(self._blocks_needed(n, req.max_new) - s)
        if fresh is None:
            for blk in reversed(shared):
                self._pool.release(blk)
            return False
        req.blocks = shared + fresh
        req.prefix_tokens = s * page
        # Register the request's own FULL prompt pages (never the
        # partial tail — decode writes into it; never shared pages —
        # already registered).  Content is written by the admit program
        # dispatched right after this plan; any sharer's read program
        # is dispatched later and device FIFO orders write before read.
        for j in range(s, n // page):
            self._pool.register(req.blocks[j], hashes[j])
        return True


    def migrate_export(
        self, *, abort_live: bool = False, include_blocks: bool = True,
        hashes=None,
    ) -> dict:
        """Snapshot every registered block (hash-addressed, full pages,
        content final) plus the live-stream manifest for the wire —
        ``serve/migrate.py pack()``'s input.  MUST run under
        ``run_quiesced`` (reads device cache + mutates scheduler
        state).  Only registered blocks travel: a partial tail is CoW —
        the destination recomputes it private, exactly as a local
        prefix hit would.  ``abort_live=True`` additionally retires
        every live stream stamped *migrated* (a resumable handover,
        not a crash — the server's truncation summary tells the
        gateway relay to fail the stream over).  ``include_blocks=
        False`` skips block bodies: the coordinator's abort-only
        second call after the import landed.  ``hashes`` (iterable of
        chain-hash bytes) filters the export to exactly those
        registered blocks — the disaggregated prefill handover ships
        one prompt's chain, not the whole pool."""
        if not self.paged:
            raise ValueError("block migration requires paged KV mode")
        cache = self._dev["cache"]
        geometry = {
            name: {
                "dtype": np.dtype(arr.dtype).name,
                # One block's contents: arr[:, blk] per leaf.
                "shape": (int(arr.shape[0]),) + tuple(
                    int(s) for s in arr.shape[2:]
                ),
            }
            for name, arr in sorted(cache.items())
        }
        blocks: list[tuple[bytes, dict]] = []
        if include_blocks:
            items = self._pool.registered()
            if hashes is not None:
                want = set(hashes)
                items = [(h, b) for h, b in items if h in want]
            if items:
                # ONE gather + ONE device_get for the whole export —
                # per-block fetches would pay N host round-trips.
                idx = jnp.asarray(
                    np.asarray([b for _, b in items], np.int32)
                )
                sel = jax.device_get(
                    {name: arr[:, idx] for name, arr in cache.items()}
                )
                for j, (h, _) in enumerate(items):
                    blocks.append((h, {
                        name: np.ascontiguousarray(sel[name][:, j])
                        for name in sorted(sel)
                    }))
        requests = []
        for r in self._active:
            if r is None:
                continue
            requests.append({
                "tenant": r.tenant,
                "trace_id": (
                    r.trace_ctx.trace_id if r.trace_ctx is not None
                    else ""
                ),
                "prompt_tokens": int(r.prompt_tokens),
                "emitted": int(r.emitted),
            })
        aborted = 0
        if abort_live:
            for slot, r in enumerate(self._active):
                if r is None:
                    continue
                r.migrated = True
                r.aborted = True
                self._retire(slot)
                aborted += 1
        return {
            "page_size": self.page_size,
            "geometry": geometry,
            "blocks": blocks,
            "requests": requests,
            "aborted": aborted,
        }

    def migrate_import(self, parsed: dict) -> int:
        """Splice wire blocks (``serve/migrate.py unpack()``'s output)
        into this pool via the SAME alloc/register/release path a local
        admission retires through, so a migrated chain is
        indistinguishable from one prefilled here: alloc a fresh block,
        write the wire bytes, register its chain hash, release to
        refcount 0 — it parks in the LRU exactly like a retired
        prompt's pages, ready for the next matching acquire.  MUST run
        under ``run_quiesced``.  Hashes already registered are skipped
        (content-addressed: same hash, same bytes); a pool too full to
        take more stops early — a partial chain is still a valid
        (shorter) warm prefix.  Returns the blocks spliced."""
        if not self.paged:
            raise ValueError("block migration requires paged KV mode")
        if int(parsed.get("page_size", 0)) != self.page_size:
            raise ValueError(
                f"wire page_size {parsed.get('page_size')} != local "
                f"{self.page_size}"
            )
        cache = self._dev["cache"]
        geometry = parsed.get("geometry") or {}
        if sorted(geometry) != sorted(cache):
            raise ValueError(
                f"wire cache leaves {sorted(geometry)} != local "
                f"{sorted(cache)}"
            )
        for name, arr in sorted(cache.items()):
            want_dtype = np.dtype(arr.dtype)
            want_shape = (int(arr.shape[0]),) + tuple(
                int(s) for s in arr.shape[2:]
            )
            g = geometry[name]
            if (np.dtype(g["dtype"]) != want_dtype
                    or tuple(g["shape"]) != want_shape):
                raise ValueError(
                    f"leaf {name!r}: wire {g['dtype']}{g['shape']} != "
                    f"local {want_dtype.name}{want_shape}"
                )
        fresh: list[tuple[bytes, int, dict]] = []
        for h, leaves in parsed.get("blocks", []):
            if self._pool.contains(h):
                continue
            got = self._pool.alloc(1)
            if got is None:
                break
            fresh.append((h, got[0], leaves))
        if fresh:
            # ONE scatter per leaf for the whole import — per-block
            # .at[].set would copy the full pool N times.
            idx = jnp.asarray(
                np.asarray([b for _, b, _ in fresh], np.int32)
            )
            new_cache = dict(cache)
            for name in sorted(cache):
                stacked = np.stack(
                    [lv[name] for _, _, lv in fresh], axis=1
                )
                new_cache[name] = cache[name].at[:, idx].set(
                    jnp.asarray(stacked, cache[name].dtype)
                )
            self._dev["cache"] = self._constrain_cache_paged(new_cache)
            for h, blk, _ in fresh:
                self._pool.register(blk, h)
                self._pool.release(blk)
        return len(fresh)

