"""Black-box canary probing: synthetic golden requests against replicas.

Every other health signal in the fleet is white-box and passive — a
replica is "down" only after M failed metric scrapes
(utils/federation.py) or a dispatch-time connection error
(serve/router.py).  A replica that answers fast but returns garbage,
hangs mid-stream, or sheds everything at the door looks perfectly
healthy from the inside.  The ``CanaryProber`` measures the three
things white-box metrics cannot:

- **availability** — did the replica answer the probe within its
  deadline (error / deadline / abort are hard failures);
- **correctness** — greedy decode is deterministic, so the probe's
  token stream is content-hashed against a *golden* recorded on first
  healthy contact; any later drift is real breakage (wrong weights, KV
  corruption, constraint regressions), never noise;
- **outside-in latency** — probe TTFT/TPOT as a user would see them,
  exported as ``probe_ttft_seconds``/``probe_tpot_seconds`` and
  optionally classified ``slow`` against a per-probe TTFT bound (the
  latency-SLO bad-event counter, not an FSM failure).

Targets are pluggable with the same duality ``FleetCollector`` targets
have: an in-process callable today (``ContinuousBatcher.submit`` or
anything with its shape), an HTTP base URL tomorrow (``POST
/generate`` on an ``LmServer``) — so ROADMAP item 1's cross-process
front-end inherits the prober unchanged.

Each replica carries a deterministic health FSM::

    healthy --(1 hard failure)--> degraded
    degraded --(>= fail_k failures in last window_n)--> unhealthy
    degraded --(recover_k consecutive ok)--> healthy
    unhealthy --(recover_k consecutive ok)--> healthy

The walk is a pure function of the probe-outcome sequence — two
scripted runs under ``FakeClock`` produce byte-identical
``/debug/probes`` bodies.  Transitions drive ``FleetRouter``
quarantine (``mark_unhealthy``: no NEW traffic, same effect as a
drain; recovery re-admits) and the gauge
``probe_replica_healthy{replica}`` (1.0 / 0.5 / 0.0) that the
``CanaryFailing``/``ReplicaUnhealthy`` rules in the default pack
evaluate.  Probe traffic rides tenant ``PROBE_TENANT`` so the serve
plane can exclude it from user-facing SLO accounting
(serve/batcher.py — the self-pollution guard).
"""

from __future__ import annotations

import hashlib
import json
import threading
import logging

from ..utils.clock import Clock, RealClock
from ..utils.metrics import MetricsRegistry, global_metrics
from .journal import PROBE_TENANT

log = logging.getLogger("k8s_gpu_tpu.canary")

# FSM states, and the gauge value each exports.
HEALTHY, DEGRADED, UNHEALTHY = "healthy", "degraded", "unhealthy"
_STATE_GAUGE = {HEALTHY: 1.0, DEGRADED: 0.5, UNHEALTHY: 0.0}

# probe_failures_total{reason=} vocabulary:
#   error     the target raised (connection refused, queue full, crash)
#   deadline  no complete answer inside the probe deadline
#   aborted   the replica cut the stream (shutdown / scheduler death)
#   corrupt   answered, but the content hash drifted from the golden
#   slow      answered correctly but TTFT blew ttft_slo_s — a latency-
#             SLO bad event, NOT an FSM failure (the replica works, it
#             is just slow; quarantining it would shed capacity exactly
#             when the fleet is saturated)
FAILURE_REASONS = ("error", "deadline", "aborted", "corrupt", "slow")
_HARD_FAILURES = ("error", "deadline", "aborted", "corrupt")

# Bounded per-replica transition history in the snapshot.
_MAX_TRANSITIONS = 16


class _Replica:
    """Per-replica probe state: the FSM, the K-of-N outcome window,
    and the last probe's evidence.  All access under the prober lock."""

    __slots__ = (
        "target", "state", "window", "ok_streak", "probes", "failures",
        "last", "transitions",
    )

    def __init__(self, target, window_n: int):
        self.target = target
        self.state = HEALTHY
        self.window: list[bool] = []   # last window_n outcomes, oldest first
        self.ok_streak = 0
        self.probes = 0
        self.failures: dict[str, int] = {}
        self.last: dict = {}
        self.transitions: list[dict] = []


class CanaryProber:
    """Clock-driven synthetic prober over a named replica set.

    ``targets`` maps replica name → target, where a target is either a
    callable with ``ContinuousBatcher.submit``'s shape (in-process) or
    an HTTP base URL string (``POST {url}/generate``).  ``interval``
    paces probe rounds; ``probe_once()`` runs one round explicitly
    (tests, and the ``attach``-to-evaluator path).  ``router`` is an
    optional ``serve.router.FleetRouter`` — transitions to unhealthy
    quarantine the replica (``mark_unhealthy``), recovery re-admits.

    ``ttft_slo_s > 0`` classifies an otherwise-good probe whose TTFT
    exceeds it as ``slow`` — minted into ``probe_failures_total`` for
    the latency SLO's budget math, but NOT an FSM failure.  ``golden``
    pre-pins the correctness hash; empty records it from the first
    clean probe fleet-wide (probe order is sorted replica names, so
    keep a known-good replica first or pre-pin when bootstrapping
    against a suspect fleet)."""

    # Lock contract (graftcheck lockcheck + utils.faults
    # guard_declared): probe rounds run on the prober thread (or an
    # evaluator collector) while /debug/probes handlers snapshot.
    _GUARDED_BY = {
        "_lock": ("_replicas", "_golden", "_rounds", "_last_round"),
    }

    def __init__(
        self,
        targets: dict | None = None,
        *,
        clock: Clock | None = None,
        metrics: MetricsRegistry | None = None,
        router=None,
        interval: float = 10.0,
        deadline_s: float = 2.0,
        prompt_ids=(3, 5, 7, 11, 13),
        prompt_text: str = "canary golden probe",
        max_new_tokens: int = 8,
        window_n: int = 5,
        fail_k: int = 3,
        recover_k: int = 3,
        ttft_slo_s: float = 0.0,
        golden: str = "",
        on_transition=None,
    ):
        self.clock = clock or RealClock()
        self.metrics = metrics if metrics is not None else global_metrics
        self.router = router
        self.interval = float(interval)
        self.deadline_s = float(deadline_s)
        self.prompt_ids = tuple(int(i) for i in prompt_ids)
        self.prompt_text = str(prompt_text)
        self.max_new_tokens = max(1, int(max_new_tokens))
        self.window_n = max(1, int(window_n))
        self.fail_k = max(1, min(int(fail_k), self.window_n))
        self.recover_k = max(1, int(recover_k))
        self.ttft_slo_s = float(ttft_slo_s)
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._replicas: dict[str, _Replica] = {}
        self._golden = str(golden)
        self._rounds = 0
        self._last_round = float("-inf")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        for name, target in sorted((targets or {}).items()):
            self.add_target(name, target)

    # -- replica set -------------------------------------------------------
    def add_target(self, name: str, target) -> None:
        """Register a replica; callable or URL-string target.  A fresh
        replica starts healthy (gauge 1.0) — innocent until probed."""
        name = str(name)
        with self._lock:
            self._replicas[name] = _Replica(target, self.window_n)
        self.metrics.set_gauge(
            "probe_replica_healthy", 1.0, replica=name
        )

    def remove_target(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)
        self.metrics.remove_gauge("probe_replica_healthy", replica=name)
        if self.router is not None:
            self.router.mark_healthy(name)

    def target_names(self) -> list[str]:
        with self._lock:
            return sorted(self._replicas)

    # -- probing -----------------------------------------------------------
    def probe_once(self) -> dict:
        """One probe round over every replica in sorted-name order
        (deterministic golden bootstrap and two-run identity).  Returns
        {replica: outcome-reason-or-"ok"}."""
        out: dict[str, str] = {}
        for name in self.target_names():
            with self._lock:
                rep = self._replicas.get(name)
                target = rep.target if rep is not None else None
            if target is None:
                continue
            result = self._execute(target)
            out[name] = self._settle(name, result)
        with self._lock:
            self._rounds += 1
            self._last_round = self.clock.now()
        return out

    def _execute(self, target) -> dict:
        """Run one probe against one target, outside the lock (a hung
        replica must not stall the snapshot surface).  Returns
        {"reason": "" | hard-failure, "ttft_s", "tpot_s", "hash",
        "tokens"}."""
        t0 = self.clock.now()
        try:
            if callable(target):
                toks, ttft, expired, aborted = self._probe_callable(
                    target, t0
                )
            else:
                toks, ttft, expired, aborted = self._probe_http(
                    str(target), t0
                )
        except Exception as e:          # noqa: BLE001 — any failure mode
            return {
                "reason": "error", "detail": type(e).__name__,
                "ttft_s": 0.0, "tpot_s": 0.0, "hash": "", "tokens": 0,
            }
        t1 = self.clock.now()
        tpot = (
            (t1 - (t0 + ttft)) / (len(toks) - 1)
            if len(toks) >= 2 and ttft >= 0.0 else 0.0
        )
        res = {
            "reason": "", "detail": "",
            "ttft_s": max(0.0, ttft), "tpot_s": max(0.0, tpot),
            "hash": _content_hash(toks), "tokens": len(toks),
        }
        if expired or t1 - t0 > self.deadline_s:
            res["reason"] = "deadline"
        elif aborted:
            res["reason"] = "aborted"
        elif not toks:
            res["reason"] = "error"
            res["detail"] = "empty"
        return res

    def _probe_callable(self, submit, t0: float):
        """In-process target: ``submit``'s shape is the batcher's —
        greedy decode (temperature 0), tenant-tagged, deadline-bounded.
        Under ``RealClock`` the clock domain IS ``time.monotonic``, so
        the deadline lands in the batcher's native domain."""
        handle = submit(
            list(self.prompt_ids),
            max_new_tokens=self.max_new_tokens,
            temperature=0.0, top_p=0.0, seed=0,
            tenant=PROBE_TENANT,
            deadline=t0 + self.deadline_s,
        )
        toks, ttft = [], -1.0
        for tok in handle:
            if ttft < 0.0:
                ttft = self.clock.now() - t0
            toks.append(int(tok))
        return (
            toks, ttft,
            bool(getattr(handle, "deadline_expired", False)),
            bool(getattr(handle, "aborted", False)),
        )

    def _probe_http(self, url: str, t0: float):
        """Over-the-wire target: the same probe through ``POST
        /generate`` — what ROADMAP item 1's cross-process front-end
        runs.  The deadline rides ``x-request-deadline-ms`` (server-
        side shed) AND the socket timeout (client-side bound)."""
        import urllib.request

        req = urllib.request.Request(
            url.rstrip("/") + "/generate",
            data=json.dumps({
                "prompt": self.prompt_text,
                "max_new_tokens": self.max_new_tokens,
                "temperature": 0.0,
                "tenant": PROBE_TENANT,
            }).encode(),
            headers={
                "Content-Type": "application/json",
                "x-request-deadline-ms": str(
                    int(self.deadline_s * 1000)
                ),
            },
        )
        with urllib.request.urlopen(req, timeout=self.deadline_s) as r:
            body = json.loads(r.read().decode())
        toks = [int(t) for t in body.get("ids", [])]
        ttft = self.clock.now() - t0 if toks else -1.0
        return toks, ttft, False, False

    def _settle(self, name: str, res: dict) -> str:
        """Classify one probe result, mint its metrics, and walk the
        replica's FSM.  Returns the terminal reason ("ok" for a clean
        probe)."""
        reason = res["reason"]
        if not reason and self._check_golden(res["hash"]) is False:
            reason = "corrupt"
        ok = reason == ""
        if ok and self.ttft_slo_s > 0.0 and res["ttft_s"] > self.ttft_slo_s:
            reason = "slow"      # latency bad event; FSM still ok
        self.metrics.inc("probe_requests_total", replica=name)
        if reason:
            self.metrics.inc(
                "probe_failures_total", replica=name, reason=reason
            )
        if res["tokens"] >= 1 and res["ttft_s"] >= 0.0:
            self.metrics.observe(
                "probe_ttft_seconds", res["ttft_s"], replica=name
            )
        if res["tokens"] >= 2:
            self.metrics.observe(
                "probe_tpot_seconds", res["tpot_s"], replica=name
            )
        transition = None
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:                 # removed mid-probe
                return reason or "ok"
            rep.probes += 1
            if reason:
                rep.failures[reason] = rep.failures.get(reason, 0) + 1
            rep.last = {
                "t": self.clock.now(), "ok": ok,
                "reason": reason, "detail": res.get("detail", ""),
                "ttft_s": round(res["ttft_s"], 6),
                "tpot_s": round(res["tpot_s"], 6),
                "tokens": res["tokens"], "hash": res["hash"],
            }
            rep.window.append(ok)
            del rep.window[:-self.window_n]
            rep.ok_streak = rep.ok_streak + 1 if ok else 0
            nxt = self._next_state(rep, ok)
            if nxt != rep.state:
                transition = (rep.state, nxt)
                rep.transitions.append({
                    "t": self.clock.now(),
                    "from": rep.state, "to": nxt,
                })
                del rep.transitions[:-_MAX_TRANSITIONS]
                rep.state = nxt
            state = rep.state
        self.metrics.set_gauge(
            "probe_replica_healthy", _STATE_GAUGE[state], replica=name
        )
        if transition is not None:
            self._notify(name, *transition)
        return reason or "ok"

    def _next_state(self, rep: _Replica, ok: bool) -> str:
        """The deterministic walk — a pure function of (state, window,
        ok_streak).  Lock held by caller."""
        if rep.state == HEALTHY:
            return DEGRADED if not ok else HEALTHY
        if rep.state == DEGRADED:
            if rep.ok_streak >= self.recover_k:
                return HEALTHY
            fails = sum(1 for o in rep.window if not o)
            if fails >= self.fail_k:
                return UNHEALTHY
            return DEGRADED
        # UNHEALTHY: only a full recovery streak re-admits.
        if rep.ok_streak >= self.recover_k:
            return HEALTHY
        return UNHEALTHY

    def _check_golden(self, h: str):
        """True = matches golden, False = drift, None = no golden yet
        (this clean probe records it)."""
        if not h:
            return None
        with self._lock:
            if not self._golden:
                self._golden = h
                return True
            return self._golden == h

    def _notify(self, name: str, frm: str, to: str) -> None:
        """Drive the router + user hook, outside the prober lock (the
        router takes its own)."""
        if self.router is not None:
            try:
                if to == UNHEALTHY:
                    self.router.mark_unhealthy(name)
                elif to == HEALTHY and frm == UNHEALTHY:
                    self.router.mark_healthy(name)
            except Exception:
                log.exception("router health handoff failed for %s", name)
        if self.on_transition is not None:
            try:
                self.on_transition(name, frm, to)
            except Exception:
                log.exception("probe transition hook failed for %s", name)

    # -- introspection (the /debug/probes surface) -------------------------
    def snapshot(self) -> dict:
        """The ``/debug/probes`` JSON body — every value flows from the
        injected clock or probe evidence, so two scripted ``FakeClock``
        runs serialize byte-identically (``json.dumps(...,
        sort_keys=True)`` on the server side)."""
        with self._lock:
            replicas = {
                name: {
                    "state": rep.state,
                    "ok_streak": rep.ok_streak,
                    "window": [int(o) for o in rep.window],
                    "probes": rep.probes,
                    "failures": dict(sorted(rep.failures.items())),
                    "last": dict(rep.last),
                    "transitions": list(rep.transitions),
                }
                for name, rep in sorted(self._replicas.items())
            }
            return {
                "now": self.clock.now(),
                "rounds": self._rounds,
                "interval_s": self.interval,
                "deadline_s": self.deadline_s,
                "ttft_slo_s": self.ttft_slo_s,
                "golden": self._golden,
                "fsm": {
                    "window_n": self.window_n,
                    "fail_k": self.fail_k,
                    "recover_k": self.recover_k,
                },
                "replicas": replicas,
            }

    def attach(self, evaluator) -> None:
        """Register as a rule-evaluator collector (the federation
        idiom): every evaluation tick probes first — interval-gated, so
        a fast alert cadence doesn't turn into probe spam."""
        def collect():
            with self._lock:
                due = (
                    self.clock.now() - self._last_round >= self.interval
                )
            if due:
                self.probe_once()

        evaluator.collectors.append(collect)

    # -- the probe loop ----------------------------------------------------
    def start(self) -> "CanaryProber":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="canary-prober", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _loop(self) -> None:
        cond = threading.Condition()
        while not self._stop.is_set():
            with self._lock:
                due = (
                    self.clock.now() - self._last_round >= self.interval
                )
            if due:
                try:
                    self.probe_once()
                except Exception:
                    log.exception("probe round failed")
            with cond:
                # Short waits: stop() stays responsive under RealClock
                # and FakeClock's cheap poll keeps rounds aligned.
                self.clock.wait(cond, 0.25)


def _content_hash(tokens) -> str:
    """The correctness fingerprint: a stable hash of the greedy token
    stream.  Token IDS, not decoded text — tokenizer round-trips can
    normalize away real drift."""
    if not tokens:
        return ""
    raw = ",".join(str(int(t)) for t in tokens).encode()
    return hashlib.sha256(raw).hexdigest()[:16]
