"""Per-request serving journal: one lifecycle record per retired request.

Metrics aggregate (``serve_ttft_seconds`` cannot say WHICH request blew
the budget) and traces sample the slow path span-by-span but take a
trace id to find.  The journal is the middle layer: a bounded ring of
one compact record per request the batcher finished with — completed,
budget-exhausted, deadline-shed, queue-shed, or aborted — carrying the
whole latency story (queue wait, TTFT, per-token gap), the efficiency
story (prefix-cache blocks hit, speculative acceptance), and the trace
id that cross-links into ``/debug/traces`` for span-level detail.

``ContinuousBatcher`` owns one and appends at every terminal point;
``MetricsServer`` exports it at ``/debug/requests`` and ``obs
requests`` renders it.  Overflow drops the oldest record (it is recent
behavior the journal is for — the same bound philosophy as the
histogram reservoirs and the trace ring); ``dropped`` counts evictions.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import asdict, dataclass, field


# The reserved synthetic tenant canary probes ride (serve/canary.py).
# The leading underscore marks the whole "_"-prefix as reserved for
# synthetic traffic: the batcher skips user-facing SLO accounting for
# it and the tenant burn-rate rule skips reserved tenants wholesale.
PROBE_TENANT = "_canary"


# Terminal reasons a record can carry (the ``reason`` vocabulary):
#   eos            the model emitted the stop token
#   budget         max_new_tokens reached
#   deadline       the latency budget expired (at admission or mid-stream)
#   queue_full     shed at the door — max_pending admission control
#   no_capacity    paged mode could not seat the prompt even on an idle pool
#   aborted        batcher crash/shutdown cut the stream
FINISH_REASONS = (
    "eos", "budget", "deadline", "queue_full", "no_capacity", "aborted",
)

# Gateway-side terminal reasons (serve/frontend.py writes these with
# path="gateway"; replica journals never carry them):
#   admission      the weighted-fair admission controller refused the
#                  ticket — ``extra["admission"]`` narrows it to the
#                  shed cause (quota / burn / queue_full / timeout)
#   overloaded     every candidate replica was saturated
#   rejected       a replica rejected the request (4xx passthrough)
#   error          relay failed after exhausting dispatch attempts
#   ok             delivered (gateway-side mirror of the replica record)
GATEWAY_REASONS = (
    "ok", "admission", "overloaded", "rejected", "error",
    "deadline", "aborted",
)


@dataclass
class RequestRecord:
    """One retired request, flattened for JSON (``to_dict``)."""

    tenant: str = "default"
    trace_id: str = ""
    reason: str = ""
    path: str = ""            # admission path ("" when shed pre-admission)
    # Fleet routing evidence (serve/router.py): which replica the
    # front-end chose and why ("" when the request reached the batcher
    # without going through a router) — `obs requests` explains
    # placement from these.
    replica: str = ""
    route_reason: str = ""    # affinity | load | fallback | ""
    slot: int = -1
    prompt_tokens: int = 0
    tokens: int = 0           # generated tokens actually delivered
    queue_wait_s: float = 0.0
    ttft_s: float = 0.0       # 0.0 when no token was emitted
    tpot_s: float = 0.0       # mean inter-token gap; 0.0 under 2 tokens
    prefix_blocks: int = 0    # shared KV blocks acquired from the cache
    spec_drafted: int = 0     # speculative proposals for this request
    spec_accepted: int = 0    # ...and how many the verify kept
    deadline_expired: bool = False
    t_submit: float = 0.0     # time.monotonic() domain, like spans
    t_done: float = 0.0
    extra: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = asdict(self)
        if not d["extra"]:
            d.pop("extra")
        return d


class RequestJournal:
    """Thread-safe bounded ring of ``RequestRecord``s."""

    # Lock contract (graftcheck lockcheck + utils.faults
    # guard_declared): the scheduler thread appends while /debug/requests
    # handlers snapshot.
    _GUARDED_BY = {"_lock": ("_ring", "dropped")}

    def __init__(self, maxlen: int = 512):
        self._lock = threading.Lock()
        self._ring: "deque[RequestRecord]" = deque(
            maxlen=max(1, int(maxlen))
        )
        self.dropped = 0

    def append(self, rec: RequestRecord) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(rec)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(
        self,
        limit: int = 100,
        tenant: str = "",
        reason: str = "",
        trace_id: str = "",
        probes: bool = True,
    ) -> list[dict]:
        """Newest-first records as dicts, optionally filtered; the
        ``/debug/requests`` body.  ``limit <= 0`` returns none (the
        bare ``[-0:]`` hazard the alerts snapshot also guards).
        ``probes=False`` drops canary records (``extra.probe`` — the
        ``obs requests --no-probes`` filter)."""
        if limit <= 0:
            return []
        with self._lock:
            recs = list(self._ring)
        out = []
        for rec in reversed(recs):
            if tenant and rec.tenant != tenant:
                continue
            if reason and rec.reason != reason:
                continue
            if trace_id and rec.trace_id != trace_id:
                continue
            if not probes and rec.extra.get("probe"):
                continue
            out.append(rec.to_dict())
            if len(out) >= limit:
                break
        return out
