"""Per-request serving journal: one lifecycle record per retired request.

Metrics aggregate (``serve_ttft_seconds`` cannot say WHICH request blew
the budget) and traces sample the slow path span-by-span but take a
trace id to find.  The journal is the middle layer: a bounded ring of
one compact record per request the batcher finished with — completed,
budget-exhausted, deadline-shed, queue-shed, or aborted — carrying the
whole latency story (queue wait, TTFT, per-token gap), the efficiency
story (prefix-cache blocks hit, speculative acceptance), and the trace
id that cross-links into ``/debug/traces`` for span-level detail.

``ContinuousBatcher`` owns one and appends at every terminal point;
``MetricsServer`` exports it at ``/debug/requests`` and ``obs
requests`` renders it.  Overflow drops the oldest record (it is recent
behavior the journal is for — the same bound philosophy as the
histogram reservoirs and the trace ring); ``dropped`` counts evictions.
"""

from __future__ import annotations

import hashlib
import threading
from collections import deque
from dataclasses import asdict, dataclass, field


def golden_hash(token_ids) -> str:
    """sha256[:16] over a delivered token-id stream — the replay
    golden (the CanaryProber content-hash discipline, applied to every
    journaled request).  Empty stream hashes to "" so "no tokens" and
    "tokens" never compare equal."""
    if not token_ids:
        return ""
    raw = ",".join(str(int(t)) for t in token_ids).encode()
    return hashlib.sha256(raw).hexdigest()[:16]


# The reserved synthetic tenant canary probes ride (serve/canary.py).
# The leading underscore marks the whole "_"-prefix as reserved for
# synthetic traffic: the batcher skips user-facing SLO accounting for
# it and the tenant burn-rate rule skips reserved tenants wholesale.
PROBE_TENANT = "_canary"


# Terminal reasons a record can carry (the ``reason`` vocabulary):
#   eos            the model emitted the stop token
#   budget         max_new_tokens reached
#   deadline       the latency budget expired (at admission or mid-stream)
#   queue_full     shed at the door — max_pending admission control
#   no_capacity    paged mode could not seat the prompt even on an idle pool
#   aborted        batcher crash/shutdown cut the stream
FINISH_REASONS = (
    "eos", "budget", "deadline", "queue_full", "no_capacity", "aborted",
)

# Gateway-side terminal reasons (serve/frontend.py writes these with
# path="gateway"; replica journals never carry them):
#   admission      the weighted-fair admission controller refused the
#                  ticket — ``extra["admission"]`` narrows it to the
#                  shed cause (quota / burn / queue_full / timeout)
#   overloaded     every candidate replica was saturated
#   rejected       a replica rejected the request (4xx passthrough)
#   error          relay failed after exhausting dispatch attempts
#   ok             delivered (gateway-side mirror of the replica record)
GATEWAY_REASONS = (
    "ok", "admission", "overloaded", "rejected", "error",
    "deadline", "aborted",
)


@dataclass
class RequestRecord:
    """One retired request, flattened for JSON (``to_dict``)."""

    tenant: str = "default"
    trace_id: str = ""
    reason: str = ""
    path: str = ""            # admission path ("" when shed pre-admission)
    # Replay plane (serve/replay.py): the complete reproduction record.
    # Every terminal path must fill these — a journal record that cannot
    # be re-submitted is a gap in the flight recorder.  ``prompt_ids``
    # is empty only when the prompt genuinely never existed at this
    # layer (precomputed-prefill handoff rows).
    prompt_ids: list = field(default_factory=list)
    max_new: int = 0
    temperature: float = 0.0
    top_p: float = 0.0
    seed: int = 0
    # Arrival time relative to the journal's origin (first-appended
    # record's t_submit) — may be negative for a request that arrived
    # before the journal's first terminal event; the recorder re-bases.
    arrival_offset_s: float = 0.0
    # The request's RELATIVE latency budget at submit (seconds; 0.0 =
    # none) — replay re-arms the same budget against its own clock.
    deadline_s: float = 0.0
    # sha256[:16] over the emitted token-id stream (canary discipline);
    # "" when no token was delivered.
    golden_hash: str = ""
    # Journal-global completion index, stamped by append(): the
    # ``/debug/requests?since=`` cursor's unit.
    seq: int = 0
    # Fleet routing evidence (serve/router.py): which replica the
    # front-end chose and why ("" when the request reached the batcher
    # without going through a router) — `obs requests` explains
    # placement from these.
    replica: str = ""
    route_reason: str = ""    # affinity | load | fallback | ""
    # Disaggregated prefill/decode handover (ISSUE 20): the prefill
    # worker that computed this request's KV pages ("" when the
    # request took the fused path) and the handover wall time —
    # prefill + export + wire + import, the gateway's "gateway.handover"
    # span — so replay diffs attribute disagg cost per request.
    prefill_replica: str = ""
    handover: float = 0.0     # seconds; 0.0 on the fused path
    slot: int = -1
    prompt_tokens: int = 0
    tokens: int = 0           # generated tokens actually delivered
    queue_wait_s: float = 0.0
    ttft_s: float = 0.0       # 0.0 when no token was emitted
    tpot_s: float = 0.0       # mean inter-token gap; 0.0 under 2 tokens
    prefix_blocks: int = 0    # shared KV blocks acquired from the cache
    spec_drafted: int = 0     # speculative proposals for this request
    spec_accepted: int = 0    # ...and how many the verify kept
    deadline_expired: bool = False
    t_submit: float = 0.0     # time.monotonic() domain, like spans
    t_done: float = 0.0
    extra: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = asdict(self)
        if not d["extra"]:
            d.pop("extra")
        return d


class RequestJournal:
    """Thread-safe bounded ring of ``RequestRecord``s."""

    # Lock contract (graftcheck lockcheck + utils.faults
    # guard_declared): the scheduler thread appends while /debug/requests
    # handlers snapshot.
    _GUARDED_BY = {"_lock": ("_ring", "dropped", "_seq", "_origin")}

    def __init__(self, maxlen: int = 512):
        self._lock = threading.Lock()
        self._ring: "deque[RequestRecord]" = deque(
            maxlen=max(1, int(maxlen))
        )
        self.dropped = 0
        # Monotonic completion index: +1 per appended record, never
        # reset by ring eviction — the ``?since=`` cursor a periodic
        # scraper (serve/replay.py's recorder) resumes from.
        self._seq = 0
        # Arrival origin: the first appended record's t_submit.  Every
        # later record's arrival_offset_s is relative to it, so one
        # journal's offsets share a zero without leaking absolute
        # monotonic-clock values into the wire format.
        self._origin: float | None = None

    def append(self, rec: RequestRecord) -> None:
        with self._lock:
            if self._origin is None:
                self._origin = rec.t_submit
            rec.arrival_offset_s = rec.t_submit - self._origin
            self._seq += 1
            rec.seq = self._seq
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(rec)

    @property
    def cursor(self) -> int:
        """The current completion index: pass it back as ``since=`` to
        receive only records appended after this read."""
        with self._lock:
            return self._seq

    @property
    def origin(self) -> float | None:
        """This journal's arrival-offset zero (first record's
        t_submit, monotonic domain) — None before any append.  The
        workload recorder aligns multi-journal captures on it."""
        with self._lock:
            return self._origin

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(
        self,
        limit: int = 100,
        tenant: str = "",
        reason: str = "",
        trace_id: str = "",
        probes: bool = True,
        since: int = 0,
    ) -> list[dict]:
        """Newest-first records as dicts, optionally filtered; the
        ``/debug/requests`` body.  ``limit <= 0`` returns none (the
        bare ``[-0:]`` hazard the alerts snapshot also guards).
        ``probes=False`` drops canary records (``extra.probe`` — the
        ``obs requests --no-probes`` filter).  ``since`` is a
        completion-index cursor (``RequestJournal.cursor``): only
        records appended AFTER that read are returned, so a periodic
        scraper ships deltas instead of re-fetching the whole ring."""
        if limit <= 0:
            return []
        with self._lock:
            recs = list(self._ring)
        out = []
        for rec in reversed(recs):
            if since and rec.seq <= since:
                break  # the ring is seq-ordered; everything older matches
            if tenant and rec.tenant != tenant:
                continue
            if reason and rec.reason != reason:
                continue
            if trace_id and rec.trace_id != trace_id:
                continue
            if not probes and rec.extra.get("probe"):
                continue
            out.append(rec.to_dict())
            if len(out) >= limit:
                break
        return out
