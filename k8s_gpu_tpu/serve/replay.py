"""Workload flight recorder & deterministic replay.

The journal (serve/journal.py) answers "what did the fleet just do";
this module answers the next operational question — *do it again*.
Three pieces:

- ``WorkloadRecorder`` scrapes request journals (in-process objects or
  live ``/debug/requests`` URLs, cursor-delta like the waterfall's
  trace scraper) and assembles a ``.workload`` file: the complete
  reproduction record per request — prompt token ids, full sampling
  params + seed, tenant, latency budget, arrival-time offset schedule,
  and the golden content-hash of what was actually emitted.  The wire
  format is deterministic sorted-JSON (the ``migrate.py`` discipline):
  two captures of the same traffic are byte-identical.

- ``WorkloadReplayer`` re-injects a workload at recorded (or
  time-scaled) arrivals against an in-process ``ContinuousBatcher`` or
  a live fleet URL, under the injected Clock, and verifies every
  greedy completion against its recorded golden hash — the
  CanaryProber correctness discipline applied to *every* recorded
  request, not one synthetic probe.  Emits ``replay_requests_total`` /
  ``replay_mismatch_total`` and a deterministic run report.

- ``diff_reports`` compares two runs (or a run against the recorded
  baseline via ``workload_report``) request-by-request: TTFT/TPOT/E2E
  deltas decomposed into the waterfall segment taxonomy
  (``queue_wait``/``prefill``/``decode``/``gateway_route``/...), with
  a threshold gate (``regression`` + ``regressed_segments``) that
  ``obs replay diff`` turns into a non-zero exit and
  ``replay_rule_pack`` turns into a ``ReplayRegression`` page.

Clock domains: journal offsets are per-journal (each ring's origin is
its first record's ``t_submit``).  The recorder aligns multi-target
captures on each journal's reported ``origin`` — exact when the
targets share a monotonic clock (one host), best-effort across hosts.
"""

from __future__ import annotations

import hashlib
import json
import threading
import urllib.request

import numpy as np

from ..utils.clock import Clock, RealClock
from ..utils.metrics import MetricsRegistry, global_metrics
from ..utils.waterfall import SEGMENTS
from .journal import golden_hash

WORKLOAD_VERSION = 1
REPORT_VERSION = 1

# A request is verifiable when it is greedy (sampling would need the
# exact RNG stream; greedy needs only the model) and actually finished
# with content (eos/budget — a shed emitted nothing to verify).
_VERIFIABLE_REASONS = ("eos", "budget")

# Wire-format float precision: one grid for every duration/offset so
# serialization never depends on float repr noise (the waterfall
# snapshot uses the same round(x, 9)).
def _r9(x: float) -> float:
    return round(float(x), 9)


def request_key(prompt_ids, max_new, temperature, top_p, seed,
                tenant) -> str:
    """Identity hash of the reproduction tuple — the cross-run join
    key ``diff_reports`` matches requests by.  Two submissions of the
    same prompt/params/tenant share a key and are told apart by their
    occurrence index (arrival order)."""
    raw = "|".join((
        ",".join(str(int(t)) for t in prompt_ids),
        str(int(max_new)),
        repr(float(temperature)),
        repr(float(top_p)),
        str(int(seed)),
        str(tenant),
    )).encode()
    return hashlib.sha256(raw).hexdigest()[:16]


def record_segments(rec: dict) -> dict:
    """Decompose one journal record's E2E into the waterfall segment
    taxonomy.  Exhaustive partition: the returned values sum to the
    record's E2E exactly (``unattributed`` is the residual), the same
    contract utils/waterfall.py keeps for span timelines."""
    e2e = max(0.0, float(rec.get("t_done", 0.0)) -
              float(rec.get("t_submit", 0.0)))
    qw = min(max(0.0, float(rec.get("queue_wait_s", 0.0))), e2e)
    ttft = float(rec.get("ttft_s", 0.0))
    if ttft > 0.0:
        prefill = max(0.0, min(ttft, e2e) - qw)
        decode = max(0.0, e2e - max(min(ttft, e2e), qw))
    else:
        prefill = 0.0
        decode = 0.0
    unattributed = max(0.0, e2e - qw - prefill - decode)
    return {
        "queue_wait": _r9(qw),
        "prefill": _r9(prefill),
        "decode": _r9(decode),
        "unattributed": _r9(unattributed),
    }


def _entry_e2e(rec: dict) -> float:
    return max(0.0, float(rec.get("t_done", 0.0)) -
               float(rec.get("t_submit", 0.0)))


# ---------------------------------------------------------------------------
# capture


class WorkloadRecorder:
    """Cursor-delta journal scraper → deterministic ``.workload``.

    ``targets`` maps a source name to either a ``RequestJournal``
    object (in-process capture) or a base URL whose
    ``/debug/requests?since=`` endpoint serves that journal (live
    capture).  ``scrape_once`` ships deltas only — the ``since=``
    cursor contract ``/debug/traces`` pioneered — and dedups on
    ``(target, seq)`` so the cursor-before-records overlap never
    double-counts.  A dead target (mid-burst replica kill) is counted
    in ``scrape_errors`` and skipped; its requests survive in the
    journals of the replicas that resumed them."""

    # Lock contract (graftcheck lockcheck): callers may scrape from a
    # background thread while another thread builds the workload.
    _GUARDED_BY = {
        "_lock": ("_records", "_cursors", "_origins", "scrape_errors"),
    }

    def __init__(self, targets: dict, *, clock: Clock | None = None,
                 probes: bool = False, timeout_s: float = 5.0,
                 cursors: dict | None = None):
        self.targets = dict(targets)
        self.clock = clock or RealClock()
        self.probes = probes
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        # (target, seq) → record dict; insertion order is scrape order,
        # but the workload build re-sorts deterministically.
        self._records: dict = {}
        # ``cursors`` seeds per-target start positions ("capture from
        # here"): records at-or-before a seeded cursor are never
        # scraped — how a capture window excludes warmup traffic.
        self._cursors: dict = dict(cursors or {})
        self._origins: dict = {}
        self.scrape_errors = 0

    # -- scraping ----------------------------------------------------------
    def _scrape_journal(self, name: str, journal) -> list[dict]:
        with self._lock:
            since = self._cursors.get(name, 0)
        # Cursor FIRST (the /debug/traces discipline): a record
        # appended between the cursor read and the snapshot is shipped
        # twice, and the (target, seq) dedup absorbs it; reading the
        # cursor after would turn that race into a silent gap.
        cur = journal.cursor
        recs = journal.snapshot(
            limit=1_000_000, since=since, probes=True,
        )
        origin = journal.origin
        with self._lock:
            self._cursors[name] = cur
            if origin is not None:
                self._origins[name] = origin
        return recs

    def _scrape_url(self, name: str, url: str) -> list[dict]:
        with self._lock:
            since = self._cursors.get(name, 0)
        full = (
            f"{url.rstrip('/')}/debug/requests"
            f"?since={since}&limit=1000000"
        )
        with urllib.request.urlopen(full, timeout=self.timeout_s) as r:
            body = json.loads(r.read().decode())
        with self._lock:
            self._cursors[name] = int(body.get("cursor", since))
            if body.get("origin") is not None:
                self._origins[name] = float(body["origin"])
        return list(body.get("requests", ()))

    def scrape_once(self) -> int:
        """One pass over every target; returns records newly seen."""
        new = 0
        for name in sorted(self.targets):
            target = self.targets[name]
            try:
                if isinstance(target, str):
                    recs = self._scrape_url(name, target)
                else:
                    recs = self._scrape_journal(name, target)
            except (OSError, ValueError):
                with self._lock:
                    self.scrape_errors += 1
                continue
            with self._lock:
                for rec in recs:
                    k = (name, int(rec.get("seq", 0)))
                    if k not in self._records:
                        self._records[k] = rec
                        new += 1
        return new

    # -- assembly ----------------------------------------------------------
    def workload(self) -> dict:
        """Build the canonical workload from everything scraped so
        far.  Deterministic: same records in, same object out — the
        two-captures-byte-identical contract."""
        with self._lock:
            items = [
                (name, seq, rec)
                for (name, seq), rec in self._records.items()
            ]
            origins = dict(self._origins)
        base_origin = min(origins.values()) if origins else 0.0
        # Global arrival offset: per-journal offset re-based onto the
        # earliest journal origin (exact when targets share a
        # monotonic clock; per-target-consistent otherwise).
        staged = []
        for name, seq, rec in items:
            if not self.probes and (rec.get("extra") or {}).get("probe"):
                continue
            ids = rec.get("prompt_ids") or []
            if not ids:
                continue  # not reproducible at this layer
            shift = origins.get(name, base_origin) - base_origin
            staged.append((
                float(rec.get("arrival_offset_s", 0.0)) + shift,
                name, seq, rec,
            ))
        # Dedup one logical request observed on several planes (a
        # gateway "ok" mirror + the replica's own record share a trace
        # id).  Untraced records never dedup — each is its own
        # occurrence.
        groups: dict = {}
        for off, name, seq, rec in staged:
            key = request_key(
                rec["prompt_ids"], rec.get("max_new", 0),
                rec.get("temperature", 0.0), rec.get("top_p", 0.0),
                rec.get("seed", 0), rec.get("tenant", "default"),
            )
            tid = rec.get("trace_id", "")
            gk = (key, tid) if tid else (key, f"@{name}/{seq}")
            groups.setdefault(gk, []).append((off, name, seq, rec, key))
        chosen = []
        for gk in sorted(groups):
            cands = groups[gk]
            # Completed beats shed/abort (the resume path finished the
            # request somewhere); a replica record beats its gateway
            # mirror (it carries the golden hash and real segments);
            # then earliest wins.
            cands.sort(key=lambda c: (
                0 if c[3].get("reason") in _VERIFIABLE_REASONS else 1,
                1 if c[3].get("path") == "gateway" else 0,
                c[0], c[1], c[2],
            ))
            chosen.append(cands[0])
        chosen.sort(key=lambda c: (c[0], c[4], c[1], c[2]))
        min_off = chosen[0][0] if chosen else 0.0
        occurrence: dict = {}
        out = []
        for off, name, seq, rec, key in chosen:
            occ = occurrence.get(key, 0)
            occurrence[key] = occ + 1
            out.append({
                "key": key,
                "occurrence": occ,
                "arrival_offset_s": _r9(off - min_off),
                "prompt_ids": [int(t) for t in rec["prompt_ids"]],
                "max_new": int(rec.get("max_new", 0)),
                "temperature": float(rec.get("temperature", 0.0)),
                "top_p": float(rec.get("top_p", 0.0)),
                "seed": int(rec.get("seed", 0)),
                "tenant": str(rec.get("tenant", "default")),
                "deadline_s": _r9(rec.get("deadline_s", 0.0)),
                "reason": str(rec.get("reason", "")),
                "tokens": int(rec.get("tokens", 0)),
                "verify": bool(
                    float(rec.get("temperature", 0.0)) == 0.0
                    and rec.get("reason") in _VERIFIABLE_REASONS
                    and rec.get("golden_hash")
                ),
                "golden_hash": str(rec.get("golden_hash", "")),
                "trace_id": str(rec.get("trace_id", "")),
                "source": name,
                "ttft_s": _r9(rec.get("ttft_s", 0.0)),
                "tpot_s": _r9(rec.get("tpot_s", 0.0)),
                "e2e_s": _r9(_entry_e2e(rec)),
                "segments": record_segments(rec),
            })
        return {"version": WORKLOAD_VERSION, "requests": out}

    def workload_bytes(self) -> bytes:
        return workload_bytes(self.workload())


def workload_bytes(workload: dict) -> bytes:
    """Canonical ``.workload`` encoding: sorted keys, no whitespace,
    trailing newline — byte-identical for equal captures."""
    return (
        json.dumps(workload, sort_keys=True, separators=(",", ":"))
        + "\n"
    ).encode()


def load_workload(data: bytes) -> dict:
    """Parse + validate a ``.workload`` payload; raises ``ValueError``
    on malformed input *before* anything is replayed."""
    try:
        obj = json.loads(data.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"not a workload file: {e}") from None
    if not isinstance(obj, dict) or obj.get("version") != WORKLOAD_VERSION:
        raise ValueError(
            f"workload version {obj.get('version') if isinstance(obj, dict) else '?'!r} "
            f"unsupported (want {WORKLOAD_VERSION})"
        )
    reqs = obj.get("requests")
    if not isinstance(reqs, list):
        raise ValueError("workload has no requests list")
    for i, r in enumerate(reqs):
        if not isinstance(r, dict):
            raise ValueError(f"request {i} is not an object")
        ids = r.get("prompt_ids")
        if not isinstance(ids, list) or not ids or not all(
            isinstance(t, int) and t >= 0 for t in ids
        ):
            raise ValueError(f"request {i}: bad prompt_ids")
        if not isinstance(r.get("max_new"), int) or r["max_new"] < 0:
            raise ValueError(f"request {i}: bad max_new")
        for f in ("temperature", "top_p", "arrival_offset_s"):
            if not isinstance(r.get(f, 0.0), (int, float)):
                raise ValueError(f"request {i}: bad {f}")
    return obj


def workload_report(workload: dict) -> dict:
    """View a capture as a run report — the *recorded* baseline
    ``obs replay diff`` compares a replay against."""
    entries = []
    for r in workload.get("requests", ()):
        entries.append({
            "key": r["key"],
            "occurrence": int(r.get("occurrence", 0)),
            "tenant": r.get("tenant", "default"),
            "reason": r.get("reason", ""),
            "tokens": int(r.get("tokens", 0)),
            "verify": bool(r.get("verify")),
            "match": None,
            "golden_hash": r.get("golden_hash", ""),
            "replay_hash": "",
            "error": "",
            "ttft_s": _r9(r.get("ttft_s", 0.0)),
            "tpot_s": _r9(r.get("tpot_s", 0.0)),
            "e2e_s": _r9(r.get("e2e_s", 0.0)),
            "segments": dict(r.get("segments") or {}),
        })
    return {
        "version": REPORT_VERSION,
        "source": "recorded",
        "target": "capture",
        "time_scale": 1.0,
        "requests": entries,
        "totals": _totals(entries),
    }


def _totals(entries: list[dict]) -> dict:
    return {
        "requests": len(entries),
        "verified": sum(1 for e in entries if e["verify"]),
        "matched": sum(1 for e in entries if e["match"] is True),
        "mismatches": sum(1 for e in entries if e["match"] is False),
        "errors": sum(1 for e in entries if e.get("error")),
    }


def report_bytes(report: dict) -> bytes:
    return (
        json.dumps(report, sort_keys=True, separators=(",", ":")) + "\n"
    ).encode()


# ---------------------------------------------------------------------------
# replay


class WorkloadReplayer:
    """Re-inject a workload at its recorded arrival schedule.

    ``time_scale`` stretches (>1) or compresses (<1) inter-arrival
    gaps; 0 fires everything immediately (ordering still preserved —
    submissions are issued sequentially in arrival order).  Deadlines
    are NOT re-armed by default: a replay exists to compare compute,
    and re-arming wall-clock budgets on different hardware would shed
    different requests run-to-run (``arm_deadlines=True`` opts in).

    Verification: every ``verify`` request's replayed token stream is
    hashed (``golden_hash``) and compared to the recorded golden —
    mismatches increment ``replay_mismatch_total``; every replayed
    request increments ``replay_requests_total``."""

    def __init__(self, *, clock: Clock | None = None,
                 registry: MetricsRegistry | None = None,
                 time_scale: float = 1.0, arm_deadlines: bool = False,
                 state: "ReplayState | None" = None,
                 timeout_s: float = 60.0):
        self.clock = clock or RealClock()
        self.registry = registry or global_metrics
        self.time_scale = max(0.0, float(time_scale))
        self.arm_deadlines = arm_deadlines
        self.state = state
        self.timeout_s = timeout_s

    # -- pacing ------------------------------------------------------------
    def _pace(self, t_start: float, offset_s: float) -> None:
        due = offset_s * self.time_scale
        delay = due - (self.clock.now() - t_start)
        if delay > 0:
            self.clock.sleep(delay)

    # -- in-process --------------------------------------------------------
    def run(self, workload: dict, *, batcher=None, journal=None,
            url: str = "", journal_url: str = "") -> dict:
        """Replay against an in-process batcher (``batcher=``) or a
        live fleet URL (``url=``).  Returns the run report; publishes
        it to ``state`` when attached."""
        reqs = list(workload.get("requests", ()))
        if batcher is not None:
            report = self._run_batcher(reqs, batcher, journal)
        elif url:
            report = self._run_http(reqs, url, journal_url)
        else:
            raise ValueError("replay target required: batcher= or url=")
        if self.state is not None:
            self.state.publish_report(report)
        return report

    def _run_batcher(self, reqs, batcher, journal) -> dict:
        journal = journal if journal is not None else batcher.journal
        start_cursor = journal.cursor
        t_start = self.clock.now()
        handles: list = [None] * len(reqs)
        errors: list[str] = [""] * len(reqs)
        for i, r in enumerate(reqs):
            self._pace(t_start, float(r.get("arrival_offset_s", 0.0)))
            deadline = None
            if self.arm_deadlines and float(r.get("deadline_s", 0.0)):
                deadline = self.clock.now() + float(r["deadline_s"])
            err = ""
            for attempt in range(6):
                try:
                    handles[i] = batcher.submit(
                        np.asarray(r["prompt_ids"], np.int32),
                        max_new_tokens=max(1, int(r.get("max_new", 1))),
                        temperature=float(r.get("temperature", 0.0)),
                        top_p=float(r.get("top_p", 0.0)),
                        seed=int(r.get("seed", 0)),
                        deadline=deadline,
                        tenant=r.get("tenant", "default"),
                    )
                    err = ""
                    break
                except Exception as e:  # Overloaded / scheduler dead
                    err = f"{type(e).__name__}: {e}"
                    # The recorded fleet admitted this request; a shed
                    # here is replay-harness backpressure, not a
                    # finding — brief clock backoff, bounded retries.
                    self.clock.sleep(0.05)
            errors[i] = err
        streams: list[list[int]] = []
        for h in handles:
            streams.append([int(t) for t in h.result()] if h is not None
                           else [])
        return self._report_from_journal(
            reqs, streams, errors, journal, start_cursor,
            target="batcher", client_e2e=None,
        )

    # -- live fleet --------------------------------------------------------
    def _run_http(self, reqs, url: str, journal_url: str) -> dict:
        base = url.rstrip("/")
        t_start = self.clock.now()
        streams: list[list[int]] = [[] for _ in reqs]
        errors: list[str] = [""] * len(reqs)
        e2e: list[float] = [0.0] * len(reqs)
        threads = []

        def _one(i: int, r: dict) -> None:
            body = {
                "prompt": "",
                "prompt_ids": [int(t) for t in r["prompt_ids"]],
                "max_new_tokens": max(1, int(r.get("max_new", 1))),
                "temperature": float(r.get("temperature", 0.0)),
                "top_p": float(r.get("top_p", 0.0)),
                "seed": int(r.get("seed", 0)),
                "tenant": r.get("tenant", "default"),
            }
            headers = {"Content-Type": "application/json"}
            if self.arm_deadlines and float(r.get("deadline_s", 0.0)):
                headers["x-request-deadline-ms"] = str(
                    float(r["deadline_s"]) * 1000.0
                )
            req = urllib.request.Request(
                base + "/generate",
                data=json.dumps(body).encode(),
                headers=headers, method="POST",
            )
            t0 = self.clock.now()
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as resp:
                    payload = json.loads(resp.read().decode())
                streams[i] = [int(t) for t in payload.get("ids", ())]
            except OSError as e:
                errors[i] = f"OSError: {e}"
            except ValueError as e:
                errors[i] = f"ValueError: {e}"
            e2e[i] = self.clock.now() - t0

        for i, r in enumerate(reqs):
            self._pace(t_start, float(r.get("arrival_offset_s", 0.0)))
            th = threading.Thread(
                target=_one, args=(i, r), daemon=True,
                name=f"replay-{i}",
            )
            th.start()
            threads.append(th)
        for th in threads:
            th.join(self.timeout_s)
        journal = None
        start_cursor = 0
        recs = []
        if journal_url:
            try:
                full = (
                    f"{journal_url.rstrip('/')}/debug/requests"
                    "?since=0&limit=1000000"
                )
                with urllib.request.urlopen(
                    full, timeout=self.timeout_s
                ) as r:
                    recs = list(
                        json.loads(r.read().decode()).get("requests", ())
                    )
            except (OSError, ValueError):
                recs = []
        return self._report_from_records(
            reqs, streams, errors, recs, target=url, client_e2e=e2e,
        )

    # -- report assembly ---------------------------------------------------
    def _report_from_journal(self, reqs, streams, errors, journal,
                             start_cursor, *, target,
                             client_e2e) -> dict:
        recs = list(reversed(journal.snapshot(
            limit=1_000_000, since=start_cursor, probes=True,
        )))
        return self._report_from_records(
            reqs, streams, errors, recs, target=target,
            client_e2e=client_e2e,
        )

    def _report_from_records(self, reqs, streams, errors, recs, *,
                             target, client_e2e) -> dict:
        # Oldest-first per-key FIFO: the i-th replayed occurrence of a
        # key matches the i-th journal record with that key.
        by_key: dict = {}
        for rec in recs:
            ids = rec.get("prompt_ids") or []
            if not ids:
                continue
            k = request_key(
                ids, rec.get("max_new", 0), rec.get("temperature", 0.0),
                rec.get("top_p", 0.0), rec.get("seed", 0),
                rec.get("tenant", "default"),
            )
            by_key.setdefault(k, []).append(rec)
        entries = []
        for i, r in enumerate(reqs):
            rec = None
            pool = by_key.get(r["key"])
            if pool:
                rec = pool.pop(0)
            replay_hash = golden_hash(streams[i]) if streams[i] else (
                (rec or {}).get("golden_hash", "") or ""
            )
            verify = bool(r.get("verify"))
            match: bool | None = None
            if verify:
                match = bool(
                    replay_hash and
                    replay_hash == r.get("golden_hash", "")
                )
            self.registry.inc("replay_requests_total")
            if match is False:
                self.registry.inc("replay_mismatch_total")
            segs = record_segments(rec) if rec is not None else {
                "queue_wait": 0.0, "prefill": 0.0, "decode": 0.0,
                "unattributed": 0.0,
            }
            e2e_s = _entry_e2e(rec) if rec is not None else 0.0
            if client_e2e is not None:
                # Client-observed E2E ⊇ replica E2E: the surplus is the
                # fleet plane (routing + network), attributed to
                # gateway_route so a gateway-layer regression shows up
                # as its own segment, not inflated decode.
                gw = max(0.0, client_e2e[i] - e2e_s)
                segs = dict(segs)
                segs["gateway_route"] = _r9(gw)
                e2e_s = max(e2e_s, client_e2e[i])
            entries.append({
                "key": r["key"],
                "occurrence": int(r.get("occurrence", 0)),
                "tenant": r.get("tenant", "default"),
                "reason": (rec or {}).get("reason", ""),
                "tokens": int((rec or {}).get(
                    "tokens", len(streams[i]))),
                "verify": verify,
                "match": match,
                "golden_hash": r.get("golden_hash", ""),
                "replay_hash": replay_hash,
                "error": errors[i],
                "ttft_s": _r9((rec or {}).get("ttft_s", 0.0)),
                "tpot_s": _r9((rec or {}).get("tpot_s", 0.0)),
                "e2e_s": _r9(e2e_s),
                "segments": segs,
            })
        return {
            "version": REPORT_VERSION,
            "source": "replay",
            "target": str(target),
            "time_scale": self.time_scale,
            "requests": entries,
            "totals": _totals(entries),
        }


# ---------------------------------------------------------------------------
# diff


def _mean(xs: list[float]) -> float:
    return sum(xs) / len(xs) if xs else 0.0


def _ratio(c: float, b: float) -> float:
    if b > 0.0:
        return round(c / b, 6)
    return 1.0 if c <= 0.0 else 1e9


def diff_reports(baseline: dict, candidate: dict, *,
                 rel_threshold: float = 0.10,
                 abs_floor_s: float = 0.005) -> dict:
    """Per-request, per-segment comparison of two run reports.

    A segment regresses when the candidate spends more than
    ``abs_floor_s`` extra seconds in it across matched requests AND
    exceeds the baseline by ``rel_threshold`` relative — the double
    gate keeps microsecond jitter from starring a segment while still
    catching a real phase shift.  ``regression`` is the overall gate
    (any regressed segment, or any candidate mismatch — wrong bytes
    always gate)."""
    b_by = {
        (e["key"], e["occurrence"]): e
        for e in baseline.get("requests", ())
    }
    c_by = {
        (e["key"], e["occurrence"]): e
        for e in candidate.get("requests", ())
    }
    matched_keys = sorted(k for k in b_by if k in c_by)
    rows = []
    seg_names = sorted(set(SEGMENTS))
    seg_b = {s: 0.0 for s in seg_names}
    seg_c = {s: 0.0 for s in seg_names}
    b_ttft, c_ttft, b_tpot, c_tpot, b_e2e, c_e2e = [], [], [], [], [], []
    for k in matched_keys:
        be, ce = b_by[k], c_by[k]
        b_ttft.append(be["ttft_s"]); c_ttft.append(ce["ttft_s"])
        b_tpot.append(be["tpot_s"]); c_tpot.append(ce["tpot_s"])
        b_e2e.append(be["e2e_s"]); c_e2e.append(ce["e2e_s"])
        deltas = {}
        for s in seg_names:
            bv = float((be.get("segments") or {}).get(s, 0.0))
            cv = float((ce.get("segments") or {}).get(s, 0.0))
            seg_b[s] += bv
            seg_c[s] += cv
            if bv or cv:
                deltas[s] = _r9(cv - bv)
        rows.append({
            "key": k[0],
            "occurrence": k[1],
            "tenant": ce.get("tenant", "default"),
            "d_ttft_s": _r9(ce["ttft_s"] - be["ttft_s"]),
            "d_tpot_s": _r9(ce["tpot_s"] - be["tpot_s"]),
            "d_e2e_s": _r9(ce["e2e_s"] - be["e2e_s"]),
            "match": ce.get("match"),
            "segments": deltas,
        })
    segments = {}
    regressed = []
    for s in seg_names:
        bv, cv = seg_b[s], seg_c[s]
        if bv == 0.0 and cv == 0.0:
            continue
        delta = cv - bv
        reg = bool(
            delta > abs_floor_s
            and (bv <= 0.0 or cv > bv * (1.0 + rel_threshold))
        )
        segments[s] = {
            "baseline_s": _r9(bv),
            "candidate_s": _r9(cv),
            "delta_s": _r9(delta),
            "ratio": _ratio(cv, bv),
            "regressed": reg,
        }
        if reg:
            regressed.append(s)
    mismatches = sum(
        1 for e in candidate.get("requests", ())
        if e.get("match") is False
    )
    return {
        "version": REPORT_VERSION,
        "matched": len(matched_keys),
        "only_baseline": sum(1 for k in b_by if k not in c_by),
        "only_candidate": sum(1 for k in c_by if k not in b_by),
        "mismatches": mismatches,
        "ttft": {
            "baseline_s": _r9(_mean(b_ttft)),
            "candidate_s": _r9(_mean(c_ttft)),
            "ratio": _ratio(_mean(c_ttft), _mean(b_ttft)),
        },
        "tpot": {
            "baseline_s": _r9(_mean(b_tpot)),
            "candidate_s": _r9(_mean(c_tpot)),
            "ratio": _ratio(_mean(c_tpot), _mean(b_tpot)),
        },
        "e2e": {
            "baseline_s": _r9(_mean(b_e2e)),
            "candidate_s": _r9(_mean(c_e2e)),
            "ratio": _ratio(_mean(c_e2e), _mean(b_e2e)),
        },
        "segments": segments,
        "regressed_segments": regressed,
        "regression": bool(regressed) or mismatches > 0,
        "requests": rows,
    }


def diff_bytes(diff: dict) -> bytes:
    return (
        json.dumps(diff, sort_keys=True, separators=(",", ":")) + "\n"
    ).encode()


def export_gauges(diff: dict,
                  registry: MetricsRegistry | None = None) -> None:
    """Publish a diff's headline numbers so the alert plane can gate
    on them (``replay_rule_pack``'s ``ReplayRegression``)."""
    reg = registry or global_metrics
    reg.set_gauge("replay_ttft_regression_x",
                  float(diff.get("ttft", {}).get("ratio", 1.0)))
    reg.set_gauge("replay_regressed_segments",
                  float(len(diff.get("regressed_segments", ()))))


# ---------------------------------------------------------------------------
# /debug/replay state


class ReplayState:
    """The ``/debug/replay`` backing store: last run report + last
    diff, snapshotted as one sorted-JSON body (two reads of the same
    state are byte-identical)."""

    _GUARDED_BY = {"_lock": ("_report", "_diff")}

    def __init__(self):
        self._lock = threading.Lock()
        self._report: dict | None = None
        self._diff: dict | None = None

    def publish_report(self, report: dict) -> None:
        with self._lock:
            self._report = report

    def publish_diff(self, diff: dict) -> None:
        with self._lock:
            self._diff = diff

    def snapshot(self) -> dict:
        with self._lock:
            return {"report": self._report, "diff": self._diff}
