"""Speculative decoding math + draft distillation.

The reference delegates all inference to Ollama (智能风控解决方案.md:196,
250-266) and has no speculative path; this module holds the pieces the
platform's ONE speculative surface — the continuous batcher's spec
rounds (batcher._round_spec_dev / _round_spec_ngram_dev) — is built on:
the exact accept/correct math (``reject_row`` / ``rejection_sample``),
the shared sampling warp (``warped_probs``), and draft training
(``distill_draft``).  A standalone one-shot ``SpeculativeDecoder``
existed through round 4; at its cost structure (K extra dispatches per
round against the engine's single-scan generate) its breakeven
acceptance was 1.0 — it could never win — so it was folded into the
batcher path, which amortizes the verify over shared rounds and is the
only spec code path now (VERDICT r4 ask #5).  Design notes that still
govern the batcher implementation:

- **One verify launch per round.**  A small draft model proposes K tokens
  autoregressively (K cheap decode steps), then the target model scores
  the whole window in a single ``extend_multi`` forward (query length
  K+1 against the KV cache).  Decode latency per emitted token drops
  from one target launch to ``1/(a+1)`` launches, where ``a`` is the
  number of accepted drafts.
- **Static shapes, per-row state.**  Every round is one jitted program:
  the window width is the static ``K+1``; acceptance length, sequence
  position, and EOS state are per-row *data* (masks and gathers), never
  shapes — rows with different acceptance histories share the trace.
- **Rollback is free.**  Rejected drafts leave stale K/V in the cache at
  positions beyond the accepted prefix; the position masks in
  ``InferenceEngine`` never attend past a row's current length, and the
  next round's window overwrites those slots (engine.py:extend_multi).
- **Sampling is exact too.**  temperature > 0 runs Leviathan-style
  rejection sampling (``rejection_sample``): accept draft i with prob
  ``min(1, p_i(g_i)/q_i(g_i))``, emit the first rejection from the
  normalized residual ``max(p-q, 0)`` — the output distribution equals
  target-only sampling for ANY draft, with temperature/top-k applied as
  distribution warps to both sides.
- **Greedy exactness.**  With temperature 0 the emitted stream is
  *bit-identical* to ``InferenceEngine.generate`` on the target alone —
  the draft only changes how fast tokens appear, never which tokens.
  (tests/test_speculative.py asserts token-for-token parity.)
  Precision caveat: this holds when matmul results don't depend on
  program shape — true on CPU and on TPU with
  ``jax.default_matmul_precision('highest')``.  At TPU DEFAULT
  precision, f32 einsums take bf16 MXU passes whose rounding differs
  between the width-(k+1) verify and the width-1 decode, so a
  near-tie argmax (top-2 logit gap inside bf16 noise, ~1e-3 relative)
  may resolve differently — the output is then still a valid greedy
  stream of the target model under an equivalent-precision program,
  the standard contract for speculative serving.

Draft-cache bookkeeping: the draft stays one position behind the target
(invariant: draft cache valid through ``P-2``), carrying the pair
``(prev, cur)`` of the last two stream tokens.  Each round re-ingests
``prev`` at ``P-1`` (an idempotent overwrite) before drafting from
``cur`` — this makes the a == K "all accepted" case, where the draft
never saw the bonus token, uniform with every other acceptance length.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .engine import InferenceEngine, SamplingConfig


def warped_probs(logits, sampling: SamplingConfig):
    """The sampling distribution as explicit probabilities — the softmax
    of the SAME warp ``InferenceEngine._sample`` draws from
    (engine.warp_logits), so the accept-ratio/residual math and direct
    sampling can never drift apart."""
    return jax.nn.softmax(
        InferenceEngine.warp_logits(logits, sampling), axis=-1
    )


def reject_row(key, p, q, g):
    """ONE row of speculative rejection sampling (Leviathan et al.) — THE
    single implementation of the accept/residual math; the batched
    ``rejection_sample`` and the continuous batcher's per-row path both
    ride it (divergent copies would let the two spec surfaces drift, the
    same hazard nucleus_mask's docstring names for sampling warps).

    p [K+1, V]: warped target distributions at each verify position;
    q [K, V]: warped draft distributions the drafts were drawn from;
    g [K]: drafted tokens.  Returns (a, x): the number of leading drafts
    accepted and the correction token drawn from the normalized residual
    ``max(p_a - q_a, 0)``.  Extending q with a zero row makes the
    all-accepted bonus case the same formula: the residual against q = 0
    is exactly ``p_{K+1}``.

    Exactness: accept g_i with prob min(1, p_i(g_i)/q_i(g_i)), else emit
    from the normalized residual — the emitted token is distributed
    exactly as p_i regardless of q (tests/test_speculative.py checks the
    empirical distribution)."""
    K = g.shape[0]
    ka, kc = jax.random.split(key)
    p_at_g = jnp.take_along_axis(p[:K], g[:, None], axis=1)[:, 0]
    q_at_g = jnp.take_along_axis(q, g[:, None], axis=1)[:, 0]
    u = jax.random.uniform(ka, (K,))
    accept = u * q_at_g < p_at_g          # u < p/q without the divide
    a = jnp.cumprod(accept.astype(jnp.int32)).sum()
    q_ext = jnp.concatenate([q, jnp.zeros_like(q[:1])], axis=0)
    res = jnp.maximum(p - q_ext, 0.0)
    res_a, p_a = res[a], p[a]
    norm = res_a.sum()
    # Degenerate residual (p == q exactly at a rejected position) can't
    # happen in exact arithmetic but can at float epsilon: fall back to p.
    dist = jnp.where(norm > 1e-9, res_a / jnp.maximum(norm, 1e-30), p_a)
    x = jax.random.categorical(kc, jnp.log(dist + 1e-30))
    return a, x.astype(jnp.int32)


def rejection_sample(key, p, q, g):
    """Batched rejection sampling: split *key* per row and vmap
    ``reject_row`` — per-row keys are a strict generalization of a
    shared one (independent rows either way; the batcher needs per-row
    so a seeded request's draws never depend on its co-tenants).

    p [B, K+1, V], q [B, K, V], g [B, K] → (a [B], x [B])."""
    B = g.shape[0]
    return jax.vmap(reject_row)(jax.random.split(key, B), p, q, g)


def int8_draft(draft_params):
    """Prepare a draft param tree for int8 compute (the batcher's
    ``draft_int8=True``): weights quantized int8 + per-channel scales
    (serve/quant.py), consumed by an ``InferenceEngine(int8_compute=
    True)`` whose matmuls then run int8 × int8 → int32.

    This is SAFE aggressiveness, and the reason it lives in this module:
    the acceptance test above (``reject_row``) is exact for *any* draft
    distribution q — a quantized draft can only shift q away from p and
    lower the acceptance rate, never corrupt the output stream.  The
    same argument does NOT cover the target: its probabilities define
    correctness, so the target keeps its serving dtype."""
    from .quant import quantize_params

    return quantize_params(draft_params)


def distill_draft(target_model, tparams, draft_cfg=None, *, steps: int = 200,
                  batch: int = 8, seq_len: int = 64, lr: float = 3e-3,
                  key=None, data_temperature: float = 1.0,
                  hard_labels: bool = False, prompts=None,
                  train_dtype=None, target_agreement: float = 0.0):
    """Distill a small draft LM from a target — the trained-draft path
    that turns speculative acceptance from a projection into a measured
    number (the random-init draft accepts ~0 of its proposals).

    Training data is the TARGET'S OWN samples (ancestral sequences at
    ``data_temperature`` from random 2-token prompts) — acceptance is
    measured on decode-time streams, so the draft must fit the target's
    output behavior, not some external corpus.  Two losses for the two
    serving modes:

    - ``hard_labels=False`` (default): KL(p_target ‖ p_draft) — fits
      the full distribution, which is what SAMPLED spec's rejection
      ratio min(1, p/q) rewards (acceptance ≈ exp(-KL) per token).
    - ``hard_labels=True`` + ``data_temperature=0.0``: cross-entropy
      against the target's ARGMAX on its own greedy trajectories —
      GREEDY spec accepts iff the argmaxes agree, and a diffuse target
      (early training) can have tiny KL yet near-zero argmax agreement,
      so greedy serving distills against the argmax function itself,
      on-policy.

    ``prompts`` [B, P] int32: distill on THESE prompts' trajectories
    instead of random ones (overrides ``batch`` — the row count is
    prompts.shape[0]) — on-policy distillation on the serving prompt
    distribution, the deployment-realistic setup (production spec
    drafts are distilled on production traffic).  Matters most for
    barely-trained targets, whose argmax function doesn't generalize
    across prefixes for ANY draft.

    ``train_dtype`` (e.g. ``jnp.float32``): run the draft's compute in
    this dtype — greedy acceptance is argmax AGREEMENT, and fitting
    near-tie argmaxes through bf16 forward noise is exactly what stalled
    round-4's acceptance at 0.34 against a 0.886 machinery ceiling.  The
    draft is tiny, so f32 compute costs little at serve time and the
    spec-round sizing already charges it by bytes.

    ``target_agreement`` > 0: early-stop once the draft's argmax matches
    the labels at this rate on the training trajectories (checked every
    25 steps; hard-label mode only) — ``steps`` becomes a budget cap
    instead of a fixed spend.

    ``draft_cfg`` defaults to the target shrunk to 2 layers at half
    width — a ~10× cheaper forward.  Returns (draft_model, dparams,
    final_loss)."""
    import dataclasses

    import optax

    from ..models import TransformerLM

    cfg = target_model.cfg
    if draft_cfg is None:
        draft_cfg = dataclasses.replace(
            cfg, n_layers=2, d_model=max(32, cfg.d_model // 2),
            d_ff=max(64, cfg.d_ff // 2), num_experts=0,
        )
    if train_dtype is not None:
        draft_cfg = dataclasses.replace(draft_cfg, dtype=train_dtype)
    if draft_cfg.vocab_size != cfg.vocab_size:
        raise ValueError("draft_cfg must keep the target's vocab_size")
    draft_model = TransformerLM(draft_cfg)
    if key is None:
        key = jax.random.PRNGKey(0)
    k_init, k_data = jax.random.split(key)
    dparams = draft_model.init(k_init)
    # Sample the training stream from the target once (one engine
    # generate per distillation — the samples are reused every step;
    # fitting a tiny draft needs distribution coverage, not fresh data).
    if prompts is None:
        prompts = jax.random.randint(
            k_data, (batch, 2), 1, cfg.vocab_size, jnp.int32
        )
    prompts = jnp.asarray(prompts, jnp.int32)
    P = prompts.shape[1]
    if P >= seq_len:
        raise ValueError(f"prompts ({P}) must be shorter than seq_len "
                         f"({seq_len})")
    eng = InferenceEngine(target_model, max_seq=max(seq_len + 4, 16))
    gen = eng.generate(
        tparams, prompts, max_new_tokens=seq_len - P,
        sampling=SamplingConfig(temperature=data_temperature),
        key=jax.random.fold_in(k_data, 1),
    )
    seqs = jnp.concatenate([prompts, gen.tokens], axis=1)  # [B, seq_len]

    # Warmup + cosine decay: constant-lr adamw leaves the draft circling
    # the argmax decision boundaries it must land inside (measured on
    # the r4 flagship: constant 3e-3 plateaued at 0.34 acceptance where
    # the decayed schedule keeps improving to the noise ceiling).
    sched = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=lr,
        warmup_steps=max(1, steps // 20), decay_steps=max(2, steps),
        end_value=lr * 0.01,
    )
    opt = optax.adamw(sched)
    ost = opt.init(dparams)
    # Target labels once, outside the loop: the sequences are fixed, the
    # target is the expensive side, and no grad flows through it.  Only
    # the branch in use materializes — the other would hold full [B,S,V]
    # f32 arrays alive in the jitted closure for nothing.
    tlogits, _ = jax.jit(target_model.forward)(tparams, seqs)
    if hard_labels:
        labels = jnp.argmax(tlogits, axis=-1)
    else:
        pt = jax.nn.softmax(tlogits.astype(jnp.float32), axis=-1)
        lp = jax.nn.log_softmax(tlogits.astype(jnp.float32), axis=-1)
    del tlogits

    @jax.jit
    def step(dparams, ost):
        def loss_fn(dp):
            dlogits, _ = draft_model.forward(dp, seqs)
            lq = jax.nn.log_softmax(dlogits.astype(jnp.float32), axis=-1)
            if hard_labels:
                return -jnp.mean(
                    jnp.take_along_axis(lq, labels[..., None], -1)
                )
            return jnp.mean(jnp.sum(pt * (lp - lq), axis=-1))

        kl, grads = jax.value_and_grad(loss_fn)(dparams)
        updates, ost2 = opt.update(grads, ost, dparams)
        return optax.apply_updates(dparams, updates), ost2, kl

    if hard_labels and target_agreement > 0.0:
        @jax.jit
        def agreement(dp):
            dlogits, _ = draft_model.forward(dp, seqs)
            return jnp.mean(jnp.argmax(dlogits, -1) == labels)

    kl = jnp.inf
    for i in range(steps):
        dparams, ost, kl = step(dparams, ost)
        if (hard_labels and target_agreement > 0.0 and i % 25 == 24
                and float(agreement(dparams)) >= target_agreement):
            break
    return draft_model, dparams, float(kl)
