"""Speculative decoding — draft-model lookahead, target-model verify.

The reference delegates all inference to Ollama (智能风控解决方案.md:196,
250-266) and has no speculative path; this is the TPU-native serving
accelerator the platform hosts instead.  Design:

- **One verify launch per round.**  A small draft model proposes K tokens
  autoregressively (K cheap decode steps), then the target model scores
  the whole window in a single ``extend_multi`` forward (query length
  K+1 against the KV cache).  Decode latency per emitted token drops
  from one target launch to ``1/(a+1)`` launches, where ``a`` is the
  number of accepted drafts.
- **Static shapes, per-row state.**  Every round is one jitted program:
  the window width is the static ``K+1``; acceptance length, sequence
  position, and EOS state are per-row *data* (masks and gathers), never
  shapes — rows with different acceptance histories share the trace.
- **Rollback is free.**  Rejected drafts leave stale K/V in the cache at
  positions beyond the accepted prefix; the position masks in
  ``InferenceEngine`` never attend past a row's current length, and the
  next round's window overwrites those slots (engine.py:extend_multi).
- **Sampling is exact too.**  temperature > 0 runs Leviathan-style
  rejection sampling (``rejection_sample``): accept draft i with prob
  ``min(1, p_i(g_i)/q_i(g_i))``, emit the first rejection from the
  normalized residual ``max(p-q, 0)`` — the output distribution equals
  target-only sampling for ANY draft, with temperature/top-k applied as
  distribution warps to both sides.
- **Greedy exactness.**  With temperature 0 the emitted stream is
  *bit-identical* to ``InferenceEngine.generate`` on the target alone —
  the draft only changes how fast tokens appear, never which tokens.
  (tests/test_speculative.py asserts token-for-token parity.)
  Precision caveat: this holds when matmul results don't depend on
  program shape — true on CPU and on TPU with
  ``jax.default_matmul_precision('highest')``.  At TPU DEFAULT
  precision, f32 einsums take bf16 MXU passes whose rounding differs
  between the width-(k+1) verify and the width-1 decode, so a
  near-tie argmax (top-2 logit gap inside bf16 noise, ~1e-3 relative)
  may resolve differently — the output is then still a valid greedy
  stream of the target model under an equivalent-precision program,
  the standard contract for speculative serving.

Draft-cache bookkeeping: the draft stays one position behind the target
(invariant: draft cache valid through ``P-2``), carrying the pair
``(prev, cur)`` of the last two stream tokens.  Each round re-ingests
``prev`` at ``P-1`` (an idempotent overwrite) before drafting from
``cur`` — this makes the a == K "all accepted" case, where the draft
never saw the bonus token, uniform with every other acceptance length.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .engine import InferenceEngine, SamplingConfig


def warped_probs(logits, sampling: SamplingConfig):
    """The sampling distribution as explicit probabilities — the softmax
    of the SAME warp ``InferenceEngine._sample`` draws from
    (engine.warp_logits), so the accept-ratio/residual math and direct
    sampling can never drift apart."""
    return jax.nn.softmax(
        InferenceEngine.warp_logits(logits, sampling), axis=-1
    )


def reject_row(key, p, q, g):
    """ONE row of speculative rejection sampling (Leviathan et al.) — THE
    single implementation of the accept/residual math; the batched
    ``rejection_sample`` and the continuous batcher's per-row path both
    ride it (divergent copies would let the two spec surfaces drift, the
    same hazard nucleus_mask's docstring names for sampling warps).

    p [K+1, V]: warped target distributions at each verify position;
    q [K, V]: warped draft distributions the drafts were drawn from;
    g [K]: drafted tokens.  Returns (a, x): the number of leading drafts
    accepted and the correction token drawn from the normalized residual
    ``max(p_a - q_a, 0)``.  Extending q with a zero row makes the
    all-accepted bonus case the same formula: the residual against q = 0
    is exactly ``p_{K+1}``.

    Exactness: accept g_i with prob min(1, p_i(g_i)/q_i(g_i)), else emit
    from the normalized residual — the emitted token is distributed
    exactly as p_i regardless of q (tests/test_speculative.py checks the
    empirical distribution)."""
    K = g.shape[0]
    ka, kc = jax.random.split(key)
    p_at_g = jnp.take_along_axis(p[:K], g[:, None], axis=1)[:, 0]
    q_at_g = jnp.take_along_axis(q, g[:, None], axis=1)[:, 0]
    u = jax.random.uniform(ka, (K,))
    accept = u * q_at_g < p_at_g          # u < p/q without the divide
    a = jnp.cumprod(accept.astype(jnp.int32)).sum()
    q_ext = jnp.concatenate([q, jnp.zeros_like(q[:1])], axis=0)
    res = jnp.maximum(p - q_ext, 0.0)
    res_a, p_a = res[a], p[a]
    norm = res_a.sum()
    # Degenerate residual (p == q exactly at a rejected position) can't
    # happen in exact arithmetic but can at float epsilon: fall back to p.
    dist = jnp.where(norm > 1e-9, res_a / jnp.maximum(norm, 1e-30), p_a)
    x = jax.random.categorical(kc, jnp.log(dist + 1e-30))
    return a, x.astype(jnp.int32)


def rejection_sample(key, p, q, g):
    """Batched rejection sampling: split *key* per row and vmap
    ``reject_row`` — per-row keys are a strict generalization of a
    shared one (independent rows either way; the batcher needs per-row
    so a seeded request's draws never depend on its co-tenants).

    p [B, K+1, V], q [B, K, V], g [B, K] → (a [B], x [B])."""
    B = g.shape[0]
    return jax.vmap(reject_row)(jax.random.split(key, B), p, q, g)


@dataclass
class SpecOutput:
    tokens: jnp.ndarray    # [B, max_new] generated ids (pad after EOS/budget)
    lengths: jnp.ndarray   # [B] valid token count per row
    rounds: int            # verify rounds run
    accepted: jnp.ndarray  # [B] total drafts accepted (diagnostics)


@dataclass
class SpecStats:
    """Running acceptance telemetry across calls (host-side)."""
    rounds: int = 0
    drafted: int = 0
    accepted: int = 0
    emitted: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0


class SpeculativeDecoder:
    """Greedy speculative decoding over two InferenceEngines.

    ``target`` and ``draft`` must share vocab and tokenizer; the draft is
    typically 4-10x smaller (fewer layers / narrower).  ``k`` is the
    speculation depth — each round costs K draft steps + 1 target verify
    and emits between 1 and K+1 tokens.
    """

    def __init__(self, target: InferenceEngine, draft: InferenceEngine,
                 k: int = 4):
        if target.cfg.vocab_size != draft.cfg.vocab_size:
            raise ValueError("target and draft must share a vocabulary")
        if k < 1:
            raise ValueError("speculation depth k must be >= 1")
        self.target = target
        self.draft = draft
        self.k = k
        self.stats = SpecStats()
        self._loop_jit = jax.jit(
            self._decode_loop, static_argnames=("max_new", "sampling")
        )
        self._prefill_t = jax.jit(self.target.prefill)
        self._prefill_d = jax.jit(self.draft.prefill)

    # -- one speculation round (jitted; all state per-row) -----------------
    def _round(self, tparams, dparams, state, pad_left, *, max_new: int,
               sampling: SamplingConfig):
        K = self.k
        (t_cache, d_cache, prev, cur, pos, done, emitted, out, acc_total,
         drafted, key) = state
        eos_id, pad_id = sampling.eos_id, sampling.pad_id
        sampled = sampling.temperature > 0  # static: picks the trace
        B = cur.shape[0]
        kv_start = jnp.broadcast_to(jnp.asarray(pad_left, jnp.int32), (B,))
        frozen = done | (emitted >= max_new)
        key, k_draft, k_rej = jax.random.split(key, 3)
        draft_keys = jax.random.split(k_draft, K)

        # 1. Draft: re-ingest prev at pos-1, then K lookahead steps
        #    (argmax when greedy; draws from the warped draft distribution
        #    when sampling, keeping the q vectors for the ratio test).
        #    Frozen rows park their writes at their current pos (idempotent
        #    overwrites) so they can never run past max_seq while other
        #    rows finish.
        step = jnp.where(frozen, 0, 1)
        d_cache, _ = self.draft.decode_step_multi(
            dparams, d_cache, prev, pos - step, pos - step - pad_left, kv_start
        )
        tok = cur
        drafts, q_probs = [], []
        for i in range(K):
            off = jnp.where(frozen, 0, i)
            d_cache, dlogits = self.draft.decode_step_multi(
                dparams, d_cache, tok, pos + off, pos + off - pad_left, kv_start
            )
            if sampled:
                qp = warped_probs(dlogits, sampling)
                tok = jax.random.categorical(
                    draft_keys[i], jnp.log(qp + 1e-30), axis=-1
                ).astype(cur.dtype)
                q_probs.append(qp)
            else:
                tok = jnp.argmax(dlogits, axis=-1).astype(cur.dtype)
            drafts.append(tok)
        g = jnp.stack(drafts, axis=1)  # [B, K]

        # 2. Verify: one target forward over [cur, g_0..g_{K-1}] (W = K+1).
        window = jnp.concatenate([cur[:, None], g], axis=1)
        vstart = jnp.where(frozen, pos - K - 1, pos)
        vstart = jnp.maximum(vstart, kv_start)  # frozen rows: safe rewrite
        t_cache, vlogits = self.target.extend_multi(
            tparams, t_cache, window, vstart, vstart - pad_left, kv_start
        )

        # 3. Accept + correction.  Greedy: longest exactly-matching prefix,
        #    correction = target argmax.  Sampled: Leviathan rejection
        #    sampling — the emitted stream is distributed exactly as
        #    target-only sampling under the same SamplingConfig.
        idx = jnp.arange(K + 1, dtype=jnp.int32)[None]            # [1, K+1]
        if sampled:
            p = warped_probs(vlogits, sampling)                   # [B,K+1,V]
            a, x = rejection_sample(k_rej, p, jnp.stack(q_probs, 1), g)
            corr = jnp.broadcast_to(
                x.astype(cur.dtype)[:, None], (B, K + 1)
            )
        else:
            t_pred = jnp.argmax(vlogits, axis=-1).astype(cur.dtype)
            match = (g == t_pred[:, :K]).astype(jnp.int32)        # [B, K]
            a = jnp.cumprod(match, axis=1).sum(axis=1)            # [B] 0..K
            corr = t_pred
        base = jnp.concatenate([g, g[:, -1:]], axis=1)
        e = jnp.where(idx < a[:, None], base, corr)               # [B, K+1]

        is_eos = e == eos_id
        eos_cum = jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
        valid = (
            (idx <= a[:, None])
            & (eos_cum - is_eos.astype(jnp.int32) == 0) & ~is_eos
            & ~frozen[:, None]
            & ((emitted[:, None] + idx) < max_new)
        )
        hit_eos = (is_eos & (idx <= a[:, None]) & ~frozen[:, None]).any(axis=1)

        # 4. Scatter emissions into the output buffer (invalid slots route
        #    to index max_new, which JAX scatter drops as out-of-bounds).
        wpos = jnp.where(valid, emitted[:, None] + idx, max_new)
        rows = jnp.arange(B)[:, None]
        out = out.at[rows, wpos].set(jnp.where(valid, e, pad_id),
                                     mode="drop")

        # 5. Advance: prev/cur slide to the accepted frontier.
        advance = jnp.where(frozen, 0, a + 1)
        new_prev = jnp.where(
            frozen, prev, jnp.take_along_axis(window, a[:, None], 1)[:, 0]
        )
        new_cur = jnp.where(
            frozen, cur, jnp.take_along_axis(corr, a[:, None], 1)[:, 0]
        )
        n_valid = valid.sum(axis=1, dtype=jnp.int32)
        new_state = (
            t_cache, d_cache, new_prev, new_cur, pos + advance,
            done | hit_eos, emitted + n_valid, out,
            acc_total + jnp.where(frozen, 0, a),
            # Frozen rows draft nothing real — count only live rows, so
            # acceptance_rate = accepted/drafted stays meaningful when
            # batch rows finish at different times.
            drafted + jnp.where(frozen, 0, K),
            key,
        )
        return new_state, jnp.where(frozen, 0, a)

    def _decode_loop(self, tparams, dparams, state, pad_left, *,
                     max_new: int, sampling: SamplingConfig):
        """All speculation rounds as ONE on-device ``lax.while_loop``.

        The whole generate is a single dispatch after prefill — on a
        tunneled TPU the host↔device round trip costs tens of ms, so a
        per-round host check (sync + relaunch) would dominate the very
        latency speculation exists to cut.  Termination state (done,
        emitted) lives on device; the host fetches once at the end.
        """

        def live(s):
            done, emitted = s[5], s[6]
            return ~(done | (emitted >= max_new)).all()

        def cond(carry):
            s, rounds = carry
            return live(s) & (rounds < max_new)

        def body(carry):
            s, rounds = carry
            s, _ = self._round(
                tparams, dparams, s, pad_left,
                max_new=max_new, sampling=sampling,
            )
            return s, rounds + 1

        state, rounds = jax.lax.while_loop(
            cond, body, (state, jnp.int32(0))
        )
        return state, rounds

    # -- public API --------------------------------------------------------
    def generate(self, tparams, dparams, prompt, *, max_new_tokens: int = 32,
                 sampling: SamplingConfig = SamplingConfig(),
                 pad_left: int = 0, key=None) -> SpecOutput:
        """prompt [B, S] int32 → SpecOutput.

        temperature 0: greedy, bit-exact vs the plain engine (module
        docstring).  temperature > 0: Leviathan rejection sampling — the
        emitted stream is distributed *exactly* as target-only sampling
        under the same SamplingConfig, for any draft (rejection_sample).

        Requires ``S + max_new_tokens + k + 1 <= max_seq`` of both engines
        (the last verify window may overshoot the budget by up to k).
        """
        B, S = prompt.shape
        K = self.k
        # Both caches must hold the full stream + lookahead: a shorter
        # draft cache would silently drop out-of-bounds K/V writes (JAX
        # scatter semantics) and degrade acceptance to ~0 with no error.
        limit = min(self.target.max_seq, self.draft.max_seq)
        if S + max_new_tokens + K + 1 > limit:
            raise ValueError(
                f"prompt {S} + max_new {max_new_tokens} + lookahead {K + 1} "
                f"exceeds max_seq {limit} "
                f"(target {self.target.max_seq}, draft {self.draft.max_seq})"
            )
        pad = jnp.asarray(pad_left, jnp.int32)
        t_cache, t_logits = self._prefill_t(tparams, prompt, pad)
        d_cache, _ = self._prefill_d(dparams, prompt, pad)

        if key is None:
            key = jax.random.PRNGKey(0)
        key, k0 = jax.random.split(key)
        cur = InferenceEngine._sample(t_logits, k0, sampling).astype(
            prompt.dtype
        )
        done = cur == sampling.eos_id
        out = jnp.full((B, max_new_tokens), sampling.pad_id, prompt.dtype)
        out = out.at[:, 0].set(jnp.where(done, sampling.pad_id, cur))
        emitted = (~done).astype(jnp.int32)
        prev = prompt[:, -1]
        pos = jnp.full((B,), S, jnp.int32)
        acc = jnp.zeros((B,), jnp.int32)
        drafted = jnp.zeros((B,), jnp.int32)

        state = (t_cache, d_cache, prev, cur, pos, done, emitted, out, acc,
                 drafted, key)
        state, rounds_dev = self._loop_jit(
            tparams, dparams, state, pad,
            max_new=max_new_tokens, sampling=sampling,
        )
        rounds = int(jax.device_get(rounds_dev))
        lengths = state[6]
        accepted = state[8]
        self.stats.rounds += rounds
        self.stats.drafted += int(jax.device_get(state[9]).sum())
        self.stats.accepted += int(jax.device_get(accepted).sum())
        self.stats.emitted += int(jax.device_get(lengths).sum())
        return SpecOutput(
            tokens=state[7], lengths=lengths, rounds=rounds,
            accepted=accepted,
        )


def distill_draft(target_model, tparams, draft_cfg=None, *, steps: int = 200,
                  batch: int = 8, seq_len: int = 64, lr: float = 3e-3,
                  key=None, data_temperature: float = 1.0,
                  hard_labels: bool = False, prompts=None):
    """Distill a small draft LM from a target — the trained-draft path
    that turns speculative acceptance from a projection into a measured
    number (the random-init draft accepts ~0 of its proposals).

    Training data is the TARGET'S OWN samples (ancestral sequences at
    ``data_temperature`` from random 2-token prompts) — acceptance is
    measured on decode-time streams, so the draft must fit the target's
    output behavior, not some external corpus.  Two losses for the two
    serving modes:

    - ``hard_labels=False`` (default): KL(p_target ‖ p_draft) — fits
      the full distribution, which is what SAMPLED spec's rejection
      ratio min(1, p/q) rewards (acceptance ≈ exp(-KL) per token).
    - ``hard_labels=True`` + ``data_temperature=0.0``: cross-entropy
      against the target's ARGMAX on its own greedy trajectories —
      GREEDY spec accepts iff the argmaxes agree, and a diffuse target
      (early training) can have tiny KL yet near-zero argmax agreement,
      so greedy serving distills against the argmax function itself,
      on-policy.

    ``prompts`` [B, P] int32: distill on THESE prompts' trajectories
    instead of random ones (overrides ``batch`` — the row count is
    prompts.shape[0]) — on-policy distillation on the serving prompt
    distribution, the deployment-realistic setup (production spec
    drafts are distilled on production traffic).  Matters most for
    barely-trained targets, whose argmax function doesn't generalize
    across prefixes for ANY draft.

    ``draft_cfg`` defaults to the target shrunk to 2 layers at half
    width — a ~10× cheaper forward.  Returns (draft_model, dparams,
    final_loss)."""
    import dataclasses

    import optax

    from ..models import TransformerLM

    cfg = target_model.cfg
    if draft_cfg is None:
        draft_cfg = dataclasses.replace(
            cfg, n_layers=2, d_model=max(32, cfg.d_model // 2),
            d_ff=max(64, cfg.d_ff // 2), num_experts=0,
        )
    if draft_cfg.vocab_size != cfg.vocab_size:
        raise ValueError("draft_cfg must keep the target's vocab_size")
    draft_model = TransformerLM(draft_cfg)
    if key is None:
        key = jax.random.PRNGKey(0)
    k_init, k_data = jax.random.split(key)
    dparams = draft_model.init(k_init)
    # Sample the training stream from the target once (one engine
    # generate per distillation — the samples are reused every step;
    # fitting a tiny draft needs distribution coverage, not fresh data).
    if prompts is None:
        prompts = jax.random.randint(
            k_data, (batch, 2), 1, cfg.vocab_size, jnp.int32
        )
    prompts = jnp.asarray(prompts, jnp.int32)
    P = prompts.shape[1]
    if P >= seq_len:
        raise ValueError(f"prompts ({P}) must be shorter than seq_len "
                         f"({seq_len})")
    eng = InferenceEngine(target_model, max_seq=max(seq_len + 4, 16))
    gen = eng.generate(
        tparams, prompts, max_new_tokens=seq_len - P,
        sampling=SamplingConfig(temperature=data_temperature),
        key=jax.random.fold_in(k_data, 1),
    )
    seqs = jnp.concatenate([prompts, gen.tokens], axis=1)  # [B, seq_len]

    opt = optax.adamw(lr)
    ost = opt.init(dparams)
    # Target labels once, outside the loop: the sequences are fixed, the
    # target is the expensive side, and no grad flows through it.  Only
    # the branch in use materializes — the other would hold full [B,S,V]
    # f32 arrays alive in the jitted closure for nothing.
    tlogits, _ = jax.jit(target_model.forward)(tparams, seqs)
    if hard_labels:
        labels = jnp.argmax(tlogits, axis=-1)
    else:
        pt = jax.nn.softmax(tlogits.astype(jnp.float32), axis=-1)
        lp = jax.nn.log_softmax(tlogits.astype(jnp.float32), axis=-1)
    del tlogits

    @jax.jit
    def step(dparams, ost):
        def loss_fn(dp):
            dlogits, _ = draft_model.forward(dp, seqs)
            lq = jax.nn.log_softmax(dlogits.astype(jnp.float32), axis=-1)
            if hard_labels:
                return -jnp.mean(
                    jnp.take_along_axis(lq, labels[..., None], -1)
                )
            return jnp.mean(jnp.sum(pt * (lp - lq), axis=-1))

        kl, grads = jax.value_and_grad(loss_fn)(dparams)
        updates, ost2 = opt.update(grads, ost, dparams)
        return optax.apply_updates(dparams, updates), ost2, kl

    kl = jnp.inf
    for _ in range(steps):
        dparams, ost, kl = step(dparams, ost)
    return draft_model, dparams, float(kl)
