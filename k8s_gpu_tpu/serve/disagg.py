"""Disaggregated prefill/decode serving.

Prefill and decode have opposite hardware appetites: prefill is one
big compute-bound matmul burst, decode is a bandwidth-bound trickle.
Co-locating them makes long prompts stall every in-flight decode for
the duration of their prefill (head-of-line blocking on the device).
Disaggregation runs prefill in its OWN worker pool (its own engine —
same chip, another core's program slot, or a different mesh entirely)
and hands the finished K/V row to the decode batcher, whose admission
is then a pure splice+sample (``submit_precomputed`` →
``_admit_exact_dev``) — the decode program never runs a prompt-width
forward.

This is the Splitwise/DistServe shape, sized for this framework: the
KV "transfer" is a device array handed between jitted programs (same
process; across meshes XLA reshards it), and the landing mechanism is
the same seat-and-splice the prefix cache already uses.

The pool preserves the batcher's contracts: greedy streams are
oracle-exact (prefill is the same bucketed computation, just run
elsewhere), adapters ride through (the pool prefills with the bank when
a request names one), and shutdown drains cleanly.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.compat import large_thread_stack
from .batcher import (
    ContinuousBatcher, RequestHandle, _suffix_bucket, prompt_bucket,
)


@dataclass
class _PrefillJob:
    ids: np.ndarray
    max_new: int
    temperature: float
    top_p: float
    seed: int
    adapter: str | None
    # filled by the worker: the handle of the decode-side request
    done: "queue.Queue[object]" = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.done is None:
            self.done = queue.Queue()


class DisaggregatedLm:
    """Prefill workers + a decode batcher.

    ``submit`` returns the same RequestHandle the batcher gives; callers
    cannot tell the difference — except that a long prompt no longer
    blocks the decode rounds of everyone else.
    """

    def __init__(self, model, params, *, batcher: ContinuousBatcher,
                 prefill_workers: int = 1, inflight_cap: int | None = None,
                 chunk_tokens: int = 0):
        """``inflight_cap`` bounds prefilled-but-not-yet-seated rows
        (each pins a full [L,1,H,max_seq,Dh] K/V row in HBM while it
        waits for a decode slot).  Default: the batcher's slot count —
        prefill never runs more than one slot-generation ahead.

        ``chunk_tokens`` > 0: CHUNKED prefill — the prompt runs as
        ceil(n/C) bounded extend_multi dispatches on the request's own
        off-pool row instead of one prompt-width program, so the decode
        batcher's rounds interleave between chunks (the device serializes
        dispatches at CHUNK granularity — bounded stalls instead of a
        full-prompt stall).  One compile total: every chunk is width C,
        the last right-padded (pad garbage lands above the live length,
        which masks never attend and decode overwrites in order).  MoE
        models fall back to whole-prompt prefill — capacity-capped
        dispatch couples tokens across the dispatch group, so chunking
        would diverge from the one-shot oracle (same reason the prefix
        cache refuses MoE)."""
        self.batcher = batcher
        self.params = params
        self.chunk_tokens = int(chunk_tokens)
        if self.chunk_tokens < 0 or (
            self.chunk_tokens and self.chunk_tokens % 8 != 0
        ):
            raise ValueError(
                "chunk_tokens must be a non-negative multiple of 8"
            )
        self._inflight = threading.Semaphore(
            inflight_cap if inflight_cap is not None else batcher.slots
        )
        # The pool's own engine: same model/config as the decode side,
        # independent program (on multi-chip deployments this is where a
        # separate prefill mesh plugs in).
        from .engine import InferenceEngine

        # kv_quant follows the decode side: the handed-over row must
        # splice into the pool cache leaf-for-leaf.
        self.engine = InferenceEngine(
            model, max_seq=batcher.engine.max_seq,
            kv_quant=batcher.engine.kv_quant,
        )
        self._prefill_jit = jax.jit(self.engine.prefill)
        self._extend_jit = jax.jit(self.engine.extend_multi)
        self._jobs: "queue.Queue[_PrefillJob | None]" = queue.Queue()
        self._dead = False
        self._lifecycle = threading.Lock()
        self._threads = [
            threading.Thread(target=self._worker, name=f"prefill-{i}",
                             daemon=True)
            for i in range(max(1, prefill_workers))
        ]

    def start(self) -> "DisaggregatedLm":
        # Prefill workers compile bucketed variants on their own threads
        # — enlarged stack, same account as the batcher's scheduler.
        with large_thread_stack():
            for t in self._threads:
                t.start()
        return self

    def stop(self) -> None:
        with self._lifecycle:
            self._dead = True
        for _ in self._threads:
            self._jobs.put(None)
        for t in self._threads:
            t.join(timeout=10)

    def submit(self, ids, max_new_tokens: int = 32, temperature: float = 0.0,
               top_p: float = 0.0, seed: int = 0,
               adapter: str | None = None) -> RequestHandle:
        """Queue a request; prefill happens on the pool, decode on the
        batcher.  Raises like ContinuousBatcher.submit."""
        self.batcher.bank.index(adapter)  # unknown names fail fast
        ids = np.asarray(ids, np.int32).ravel()
        if ids.size == 0:
            raise ValueError("empty prompt")
        if prompt_bucket(int(ids.size), self.engine.max_seq) is None:
            raise ValueError(
                f"prompt too long ({ids.size} tokens, "
                f"max {self.engine.max_seq - 8})"
            )
        job = _PrefillJob(ids, int(max_new_tokens), float(temperature),
                          float(top_p), int(seed), adapter)
        with self._lifecycle:
            if self._dead:
                raise RuntimeError("prefill pool is stopped")
            self._jobs.put(job)
        out = job.done.get()
        if isinstance(out, Exception):
            raise out
        return out

    def _prefill_chunked(self, ids, bank, aidx):
        """ceil(n/C) width-C extend dispatches on a fresh off-pool row.
        Returns (row_cache, last_logits [1, V]) with exact geometry
        (pos = n, no left pad)."""
        from .engine import _empty_cache

        C = self.chunk_tokens
        n = int(ids.size)
        cache = _empty_cache(self.engine.cfg, 1, self.engine.max_seq,
                             self.engine.kv_quant)
        logits = None
        for i in range(0, n, C):
            chunk = ids[i:i + C]
            arr = jnp.zeros((1, C), jnp.int32).at[0, :chunk.size].set(
                jnp.asarray(chunk)
            )
            cache, lg = self._extend_jit(
                self.params, cache, arr,
                jnp.asarray([i]), jnp.asarray([i]), jnp.asarray([0]),
                adapters=bank.banked,
                adapter_idx=jnp.asarray([aidx]) if bank.banked else None,
            )
            logits = lg[:, chunk.size - 1]
        return cache, logits

    def _prefill_exact(self, ids, bank, aidx):
        """One RIGHT-padded bucketed extend on a fresh off-pool row —
        exact geometry (pos = n, pad = 0), so the decode side's paged
        admission splices page-ALIGNED blocks (a left-padded row would
        shift every token's cache position by the bucket pad).  Pad
        garbage lands above the live length: masks never attend it and
        decode overwrites it in order.  One compile per pow2 bucket."""
        from .engine import _empty_cache

        n = int(ids.size)
        w = min(_suffix_bucket(n), self.engine.max_seq)
        cache = _empty_cache(self.engine.cfg, 1, self.engine.max_seq,
                             self.engine.kv_quant)
        padded = jnp.zeros((1, w), jnp.int32).at[0, :n].set(
            jnp.asarray(ids)
        )
        cache, lg = self._extend_jit(
            self.params, cache, padded,
            jnp.asarray([0]), jnp.asarray([0]), jnp.asarray([0]),
            adapters=bank.banked,
            adapter_idx=jnp.asarray([aidx]) if bank.banked else None,
        )
        return cache, lg[:, n - 1]

    # -- worker ------------------------------------------------------------
    def _worker(self) -> None:
        bank = self.batcher.bank
        while True:
            job = self._jobs.get()
            if job is None:
                return
            try:
                # Backpressure BEFORE the prefill: don't burn compute on
                # (and pin HBM for) a row no decode slot can take yet.
                self._inflight.acquire()
                released = False
                try:
                    aidx = bank.index(job.adapter)
                    if self.chunk_tokens and not self.engine.cfg.moe:
                        row, logits = self._prefill_chunked(
                            job.ids, bank, aidx
                        )
                        n_tokens, pad = int(job.ids.size), 0
                    elif self.batcher.paged and not self.engine.cfg.moe:
                        # Paged decode side: emit page-aligned blocks
                        # (exact geometry, no left pad).  MoE keeps the
                        # whole-prompt prefill below — its left-padded
                        # row still splices into blocks correctly, the
                        # pad positions simply occupy (masked) block
                        # space.
                        row, logits = self._prefill_exact(
                            job.ids, bank, aidx
                        )
                        n_tokens, pad = int(job.ids.size), 0
                    else:
                        bucket = prompt_bucket(
                            int(job.ids.size), self.engine.max_seq
                        )
                        pad = bucket - int(job.ids.size)
                        padded = jnp.zeros((1, bucket), jnp.int32).at[
                            0, pad:
                        ].set(jnp.asarray(job.ids))
                        row, logits = self._prefill_jit(
                            self.params, padded, jnp.int32(pad),
                            adapters=bank.banked,
                            adapter_idx=(
                                jnp.asarray([aidx]) if bank.banked else None
                            ),
                        )
                        n_tokens = bucket
                    handle = self.batcher.submit_precomputed(
                        row, logits, n_tokens, pad,
                        max_new_tokens=job.max_new,
                        temperature=job.temperature,
                        top_p=job.top_p,
                        seed=job.seed,
                        adapter=job.adapter,
                        on_admit=self._inflight.release,
                    )
                    released = True  # the on_admit hook owns the release
                    job.done.put(handle)
                finally:
                    if not released:
                        self._inflight.release()
            except Exception as e:  # surface to the submitter, keep serving
                job.done.put(e)
