"""LM serving HTTP surface: the model-serving counterpart of the
Fin-Agent service (reference 智能风控解决方案.md:175-331 serves agents over
FastAPI; here the platform's own LM serves over the same stdlib-HTTP shape
as utils/obs.py).

POST /generate  {"prompt": "text", "max_new_tokens": N[, "stream": true]}
                -> {"text", ...} or newline-delimited JSON token events
POST /tokenize  {"text": "..."}  -> {"ids": [...]}
GET  /healthz, /readyz

Requests are admitted into a shared ContinuousBatcher: concurrent requests
decode *interleaved* in one statically-shaped device program instead of
queueing behind each other (serve/batcher.py), and ``"stream": true``
returns tokens as they are produced.  Pass ``mesh`` for tp-sharded serving.
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..data.tokenizer import BpeTokenizer
from ..utils.faults import global_faults
from ..utils.obs import RequestMetricsMixin
from .batcher import ContinuousBatcher, Overloaded
from .journal import PROBE_TENANT
from .journal import RequestRecord as JournalRecord
from .kv_blocks import chunk_hashes, shareable_depth
from .migrate import pack as migrate_pack
from .migrate import unpack as migrate_unpack

# Advisory client backoff on 429/503: long enough to drain a round or
# two, short enough that a recovered server re-fills quickly.
RETRY_AFTER_S = 1


class LmServer:
    """port=0 binds an ephemeral port (tests); ``.port`` is the bound one."""

    def __init__(
        self,
        model,
        params,
        tokenizer: BpeTokenizer,
        host: str = "127.0.0.1",
        port: int = 0,
        max_new_tokens_cap: int = 256,
        slots: int = 4,
        mesh=None,
        adapters: dict | None = None,
        constraints: dict | None = None,
        eos_id: int = -1,
        draft=None,
        spec_k: int = 4,
        kv_quant: bool = False,
        paged_blocks: int = 0,
        page_size: int = 64,
        max_pending: int = 64,
        metrics=None,
        name: str = "",
        role: str = "both",
    ):
        """``max_pending`` bounds the batcher's unadmitted-request queue:
        at the bound, /generate sheds with 429 + Retry-After instead of
        queueing unboundedly (0 disables admission control).  Requests
        may carry an ``x-request-deadline-ms`` header — a per-request
        latency budget propagated into the batcher; work still queued or
        decoding past it is dropped and answered 504.

        Requests may also carry a tenant tag — ``{"tenant": "..."}`` in
        the body, or the ``x-tenant`` header as a fallback — which
        labels the batcher's per-tenant SLO accounting (TTFT/inter-token
        histograms, shed counter, goodput/total token counters) and the
        request journal; untagged traffic is tenant ``"default"``
        (docs/platform/serving.md, "The tenant label contract").

        ``metrics``: a ``MetricsRegistry`` for the batcher's serve-plane
        telemetry — each replica of a multi-replica deployment gets its
        own so the federation collector can tell them apart.

        ``adapters``: name → (lora_params, LoraConfig); requests pick
        one with {"adapter": "<name>"} — multi-tenant fine-tunes served
        from one decode program (serve/lora_bank.py).

        ``constraints``: name → regex pattern, compiled against this
        tokenizer's vocabulary into a ConstraintBank; requests pick one
        with {"constraint": "<name>"} (serve/constrain.py).  Configure
        ``eos_id`` with constraints so dead-ended rows retire cleanly.

        ``draft``/``kv_quant``/``paged_blocks``/``page_size`` pass
        through to ContinuousBatcher: speculative rounds, the int8 pool
        KV cache, and the paged (block-table) KV pool.

        ``name``: this replica's fleet name, echoed in the /healthz and
        /readyz JSON bodies next to the live in-flight count — the
        scrape-free fast path a draining front-end polls
        (serve/frontend.py) and a sanity check that a gateway is
        talking to the replica it thinks it is.

        ``role`` (ISSUE 20, disaggregated serving): ``"prefill"``
        makes this a dedicated prefill worker — every /generate or
        /prefill budget clamps to the one admission-sampled token and
        the executor refuses decode rounds outright; ``"decode"`` and
        ``"both"`` serve normally (the gateway's classifier, not this
        process, keeps long prompts off decode workers).  The live
        role is flippable via POST /admin/role while idle — the ratio
        controller's reassignment path."""
        cbank = None
        if constraints:
            from .constrain import ConstraintBank

            token_strings = [
                tokenizer.decode([i]) for i in range(tokenizer.vocab_size)
            ]
            cbank = ConstraintBank(constraints, token_strings)
        self.batcher = ContinuousBatcher(
            model, params, slots=slots, mesh=mesh, adapters=adapters,
            constraints=cbank, eos_id=eos_id, logprobs=True,
            draft=draft, spec_k=spec_k, kv_quant=kv_quant,
            paged_blocks=paged_blocks, page_size=page_size,
            max_pending=max_pending, metrics=metrics, role=role,
        )
        # The per-request lifecycle ring — hand to a MetricsServer's
        # ``journal=`` to serve it at /debug/requests.
        self.journal = self.batcher.journal
        # The phase profiler — hand to a MetricsServer's ``profile=`` to
        # serve the attribution snapshot at /debug/profile (obs profile).
        self.profiler = self.batcher.profiler
        self.tokenizer = tokenizer
        self.name = str(name)
        self.started_at = time.time()
        self.cap = max_new_tokens_cap
        # Drain latch (the health contract, docs/platform/serving.md):
        # a draining replica keeps answering in-flight and direct work
        # but reports NotReady so front-ends stop sending new traffic.
        # Monotonic-ish single-flag state; benign bool race.
        self._draining = False
        # Migration latch: True while an /admin/export barrier holds
        # the scheduler — /readyz reports NotReady so a gateway doesn't
        # route new traffic onto a replica whose warm state is mid-copy
        # to another owner.  Same benign bool race as _draining.
        self._migrating = False
        outer = self

        class Handler(RequestMetricsMixin, BaseHTTPRequestHandler):
            metrics_server_label = "lm-server"
            known_routes = ("/generate", "/tokenize", "/precache",
                            "/prefill",
                            "/healthz", "/readyz", "/debug/chains",
                            "/admin/export", "/admin/import",
                            "/admin/role")

            def _get(self):
                if self.path == "/debug/chains":
                    # The gateway fleet's reconstruction scrape
                    # (serve/frontend.py): which chain hashes are
                    # physically warm HERE.  Read-only and barrier-free
                    # — a reconstruction pass hits every replica and
                    # must never stall decode to answer.
                    return self._json(200, outer.chain_state())
                if self.path == "/healthz":
                    # Liveness: the process answers.  Anything deeper
                    # belongs in /readyz — a liveness probe that checks
                    # readiness restarts pods for being busy.  The
                    # replica name + in-flight count ride along so a
                    # front-end's drain wait stays scrape-free even
                    # while the replica reports NotReady.
                    self._json(200, {
                        "ok": True,
                        "uptime_s": time.time() - outer.started_at,
                        "replica": outer.name,
                        "inflight": outer.batcher.inflight_requests,
                    })
                elif self.path == "/readyz":
                    r = outer.readiness()
                    self._json(200 if r["ready"] else 503, r)
                else:
                    self._json(404, {"error": "not found"})

            def _post(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    return self._json(400, {"error": "invalid JSON body"})
                if not isinstance(body, dict):
                    return self._json(400, {"error": "body must be an object"})
                if self.path == "/generate":
                    return self._generate(body)
                if self.path == "/tokenize":
                    text = body.get("text", "")
                    if not isinstance(text, str):
                        return self._json(400, {"error": "text must be a string"})
                    ids = outer.tokenizer.encode(text)
                    return self._json(200, {"ids": ids.tolist(),
                                            "count": int(ids.size)})
                if self.path == "/precache":
                    # Install a shared prompt prefix (system prompt /
                    # few-shot preamble): later /generate prompts starting
                    # with it prefill only their suffix.
                    text = body.get("prompt", "")
                    if not isinstance(text, str) or not text:
                        return self._json(400, {"error": "prompt (string) required"})
                    ids = outer.tokenizer.encode(text)
                    try:
                        outer.batcher.precache_prefix(ids)
                    except ValueError as e:
                        return self._json(400, {"error": str(e)})
                    return self._json(200, {"cached_tokens": int(ids.size)})
                if self.path == "/prefill":
                    return self._prefill(body)
                if self.path == "/admin/export":
                    return self._admin_export(body)
                if self.path == "/admin/import":
                    return self._admin_import(body)
                if self.path == "/admin/role":
                    return self._admin_role(body)
                return self._json(404, {"error": "not found"})

            def _prefill(self, body):
                """Disaggregated prefill (ISSUE 20): admit + prefill
                the prompt into this replica's paged pool, then export
                exactly that prompt's registered page chain over the
                migration wire format (serve/migrate.py).  The 1-token
                admission sample is discarded — the decode worker
                recomputes the suffix (and that token) from the
                imported chain byte-identically, because sampling is
                seeded per request, not per process.  Returns the
                packed payload plus the hex ``chain`` the gateway
                forwards to the decode owner's /admin/import.  No
                ``migrating`` readiness latch: this is a per-chain
                export on a worker the gateway never routes decode
                traffic to, and flapping /readyz per prefill would
                churn the fleet's health view."""
                prompt_ids = body.get("prompt_ids")
                if (not isinstance(prompt_ids, list) or not prompt_ids
                        or not all(
                            isinstance(i, int)
                            and not isinstance(i, bool)
                            for i in prompt_ids
                        )):
                    return self._json(400, {
                        "error": "prompt_ids must be a non-empty "
                                 "list of ints"})
                if not outer.batcher.paged:
                    return self._json(400, {
                        "error": "disaggregated prefill requires "
                                 "paged KV mode"})
                ids = np.asarray(prompt_ids, np.int32)
                page = int(outer.batcher.page_size)
                depth = shareable_depth(int(ids.size), page)
                if depth <= 0:
                    return self._json(400, {
                        "error": "prompt too short for page-aligned "
                                 f"handover (needs > {page} tokens)"})
                try:
                    seed = int(body.get("seed", 0))
                    temperature = float(body.get("temperature", 0.0))
                    top_p = float(body.get("top_p", 0.0))
                except (TypeError, ValueError) as e:
                    return self._json(400, {"error": f"bad parameter: {e}"})
                tenant = body.get("tenant")
                if tenant is not None and not isinstance(tenant, str):
                    return self._json(
                        400, {"error": "tenant must be a string"})
                t0 = time.perf_counter()
                try:
                    handle = outer.batcher.submit(
                        ids, max_new_tokens=1, temperature=temperature,
                        top_p=top_p, seed=seed, tenant=tenant,
                    )
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                except Overloaded as e:
                    return self._json(
                        429, {"error": str(e)},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                except RuntimeError as e:
                    return self._json(
                        503, {"error": str(e)},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                handle.result()
                if handle.aborted:
                    return self._json(
                        503, {"error": "prefill aborted: server "
                                       "shutting down or batcher "
                                       "crashed"},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                chain = chunk_hashes(ids, page)[:depth]
                try:
                    snap = outer.batcher.run_quiesced(
                        lambda: outer.batcher.migrate_export(
                            hashes=chain,
                        )
                    )
                except (RuntimeError, TimeoutError) as e:
                    return self._json(
                        503, {"error": str(e)},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                payload = migrate_pack(snap)
                payload["replica"] = outer.name
                payload["chain"] = [h.hex() for h in chain]
                payload["prefill_s"] = round(
                    time.perf_counter() - t0, 6)
                return self._json(200, payload)

            def _admin_role(self, body):
                """Flip this replica's executor role — the ratio
                controller's reassignment path (serve/ratio.py).
                Refused while requests are in flight: a prefill-only
                executor raises on any decode round, so flipping under
                live streams would crash the scheduler instead of
                degrading."""
                role = body.get("role")
                if role not in ("both", "prefill", "decode"):
                    return self._json(
                        400, {"error": f"unknown role {role!r}"})
                if outer.batcher.inflight_requests > 0:
                    return self._json(
                        409,
                        {"error": "role flip refused: requests in "
                                  "flight"},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                outer.batcher.role = role
                return self._json(200, {
                    "replica": outer.name, "role": role,
                })

            def _admin_export(self, body):
                """Serialize this replica's registered KV blocks into
                the chain-hash-addressed wire payload (serve/migrate.py)
                through a scheduler quiesce barrier.  ``abort_live``
                additionally retires live streams stamped *migrated*
                (the coordinator's second call, AFTER the import
                landed); ``include_blocks=false`` skips block bodies.
                /readyz reports a ``migrating`` leg for the duration so
                no new traffic lands mid-export."""
                abort_live = bool(body.get("abort_live", False))
                include_blocks = bool(body.get("include_blocks", True))
                try:
                    # error/timeout only: no clock here to realize a
                    # "slow" decision as an actual delay.
                    global_faults.fire(
                        "migrate.export", error_type=RuntimeError,
                        only=("error", "timeout"),
                    )
                    outer._migrating = True
                    try:
                        snap = outer.batcher.run_quiesced(
                            lambda: outer.batcher.migrate_export(
                                abort_live=abort_live,
                                include_blocks=include_blocks,
                            )
                        )
                    finally:
                        outer._migrating = False
                except ValueError as e:  # not paged: a request fault
                    return self._json(400, {"error": str(e)})
                except (RuntimeError, TimeoutError) as e:
                    return self._json(
                        503, {"error": str(e)},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                payload = migrate_pack(snap)
                payload["replica"] = outer.name
                return self._json(200, payload)

            def _admin_import(self, body):
                """Splice a wire payload's blocks into this replica's
                pool through a scheduler quiesce barrier.  Geometry or
                encoding mismatches are refused with 400 before any
                pool mutation — never splice garbage into live state."""
                try:
                    # error/timeout only, as at migrate.export.
                    global_faults.fire(
                        "migrate.import", error_type=RuntimeError,
                        only=("error", "timeout"),
                    )
                    parsed = migrate_unpack(body)
                    n = outer.batcher.run_quiesced(
                        lambda: outer.batcher.migrate_import(parsed)
                    )
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                except (RuntimeError, TimeoutError) as e:
                    return self._json(
                        503, {"error": str(e)},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                return self._json(200, {
                    "imported": n,
                    "replica": outer.name,
                })

            def _generate(self, body):
                # ``prompt_ids`` (pre-tokenized) is the resume path: a
                # gateway failing a migrated stream over re-submits the
                # original prompt PLUS the tokens already emitted, and
                # round-tripping those through decode/encode could
                # re-tokenize differently — ids are the contract.
                prompt = body.get("prompt", "")
                prompt_ids = body.get("prompt_ids")
                if prompt_ids is not None:
                    if (not isinstance(prompt_ids, list) or not prompt_ids
                            or not all(
                                isinstance(i, int)
                                and not isinstance(i, bool)
                                for i in prompt_ids
                            )):
                        return self._json(400, {
                            "error": "prompt_ids must be a non-empty "
                                     "list of ints"})
                    vocab = getattr(outer.tokenizer, "vocab_size", 0)
                    if vocab and not all(
                        0 <= i < vocab for i in prompt_ids
                    ):
                        return self._json(400, {
                            "error": "prompt_ids out of vocabulary "
                                     "range"})
                elif not isinstance(prompt, str) or not prompt:
                    return self._json(400, {"error": "prompt (string) required"})
                try:
                    want = int(body.get("max_new_tokens", 32))
                    temperature = float(body.get("temperature", 0.0))
                    top_p = float(body.get("top_p", 0.0))
                    seed = int(body.get("seed", 0))
                except (TypeError, ValueError) as e:
                    return self._json(400, {"error": f"bad parameter: {e}"})
                adapter = body.get("adapter")
                if adapter is not None and not isinstance(adapter, str):
                    return self._json(400, {"error": "adapter must be a string"})
                constraint = body.get("constraint")
                if constraint is not None and not isinstance(constraint, str):
                    return self._json(
                        400, {"error": "constraint must be a string"})
                # Tenant tag: body field first, x-tenant header as the
                # proxy-injected fallback; absent/empty → "default".
                # Length-capped — it becomes a metric label, and the
                # registry's cardinality guard bounds the SERIES count
                # but not one value's byte length.
                tenant = body.get("tenant")
                if tenant is None:
                    tenant = self.headers.get("x-tenant") or ""
                if not isinstance(tenant, str):
                    return self._json(
                        400, {"error": "tenant must be a string"})
                tenant = tenant.strip()[:64] or "default"
                # Fleet front-end stamp: a router forwarding to this
                # replica announces its decision in headers so the
                # journal record explains placement (serve/router.py;
                # length-capped like the tenant label).
                route = None
                route_replica = self.headers.get("x-route-replica")
                if route_replica:
                    route = (
                        route_replica.strip()[:64],
                        (self.headers.get("x-route-reason") or ""
                         ).strip()[:16] or "forwarded",
                    )
                stream = bool(body.get("stream", False))
                want_lp = bool(body.get("logprobs", False))
                # Per-request latency budget: x-request-deadline-ms is a
                # RELATIVE budget (clients cannot share our monotonic
                # clock); it becomes an absolute deadline the batcher
                # enforces at admission and between rounds.
                deadline = None
                budget_ms = self.headers.get("x-request-deadline-ms")
                if budget_ms is not None:
                    try:
                        budget_ms = float(budget_ms)
                    except (TypeError, ValueError):
                        budget_ms = None
                    if budget_ms is None or not math.isfinite(budget_ms):
                        return self._json(400, {
                            "error": "x-request-deadline-ms must be a "
                                     "finite number"
                        })
                    if budget_ms <= 0:
                        # A shed like any other deadline drop — the 504
                        # rate must move the same observables the
                        # batcher's admission/round gates do (counter
                        # AND journal), in the BATCHER's registry so a
                        # per-replica deployment attributes it right.
                        outer.batcher.metrics.inc(
                            "serve_shed_total", reason="deadline",
                            tenant=tenant,
                        )
                        ctx = getattr(self, "trace_ctx", None)
                        # Replay completeness: even a door shed must be
                        # a reproducible record — tokenize here (the
                        # normal path does it a few lines down anyway).
                        shed_ids = (
                            np.asarray(prompt_ids, np.int32)
                            if prompt_ids is not None
                            else outer.tokenizer.encode(prompt)
                        )
                        outer.journal.append(JournalRecord(
                            tenant=tenant,
                            trace_id=ctx.trace_id if ctx else "",
                            reason="deadline",
                            prompt_ids=[int(t) for t in shed_ids],
                            max_new=max(1, min(want, outer.cap)),
                            temperature=temperature,
                            top_p=top_p,
                            seed=seed,
                            deadline_s=budget_ms / 1000.0,
                            prompt_tokens=int(len(shed_ids)),
                            replica=route[0] if route else "",
                            route_reason=route[1] if route else "",
                            deadline_expired=True,
                            t_submit=time.monotonic(),
                            t_done=time.monotonic(),
                            extra=(
                                {"probe": True}
                                if tenant == PROBE_TENANT else {}
                            ),
                        ))
                        return self._json(
                            504, {"error": "deadline exceeded"})
                    deadline = time.monotonic() + budget_ms / 1000.0
                # Resume stamp (serve/migrate.py): the gateway names the
                # replica this request migrated away from — journaled,
                # counted by serve_resumed_requests_total.
                migrated_from = (
                    self.headers.get("x-migrated-from") or ""
                ).strip()[:64]
                ids = (
                    np.asarray(prompt_ids, np.int32)
                    if prompt_ids is not None
                    else outer.tokenizer.encode(prompt)
                )
                t0 = time.perf_counter()
                try:
                    handle = outer.batcher.submit(
                        ids,
                        max_new_tokens=max(1, min(want, outer.cap)),
                        temperature=temperature,
                        top_p=top_p,
                        seed=seed,
                        adapter=adapter,
                        constraint=constraint,
                        deadline=deadline,
                        tenant=tenant,
                        route=route,
                        migrated_from=migrated_from,
                    )
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                except KeyError as e:  # unknown adapter name
                    return self._json(400, {"error": e.args[0]})
                except Overloaded as e:  # queue full: shed with backoff
                    return self._json(
                        429, {"error": str(e)},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                except RuntimeError as e:  # scheduler dead: clean 503
                    return self._json(
                        503, {"error": str(e)},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                if stream:
                    return self._stream(handle, ids, t0, want_lp)
                gen_ids = handle.result()
                if handle.deadline_expired:
                    return self._json(504, {
                        "error": "deadline exceeded",
                        "ids": gen_ids,
                    })
                if handle.aborted:
                    return self._json(503, {
                        "error": "generation aborted: server shutting down "
                                 "or batcher crashed",
                        "ids": gen_ids,
                    }, headers={"Retry-After": str(RETRY_AFTER_S)})
                dt = time.perf_counter() - t0
                out = {
                    "text": outer.tokenizer.decode(gen_ids),
                    "ids": gen_ids,
                    "prompt_tokens": int(ids.size),
                    "generated_tokens": len(gen_ids),
                    "tokens_per_s": round(len(gen_ids) / dt, 2) if dt > 0 else 0.0,
                }
                if want_lp:
                    out["logprobs"] = handle.logprobs
                ctx = getattr(self, "trace_ctx", None)
                if ctx is not None:
                    # Hand the caller the key to /debug/traces: this
                    # request's admission wait and batcher rounds are
                    # assembled under this id.
                    out["trace_id"] = ctx.trace_id
                return self._json(200, out)

            def _stream(self, handle, prompt_ids, t0, want_lp=False):
                """Newline-delimited JSON: one {"id": ...} event per token
                as the batcher produces it, then a summary event.  No
                Content-Length — the connection closes when done (HTTP/1.0
                framing, matching the stdlib default)."""
                self._last_code = 200
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("X-Accel-Buffering", "no")
                ctx = getattr(self, "trace_ctx", None)
                if ctx is not None:
                    self.send_header("x-trace-id", ctx.trace_id)
                self.end_headers()
                gen_ids = []
                try:
                    for tok in handle:
                        gen_ids.append(tok)
                        event = {"id": tok}
                        if want_lp:
                            event["logprob"] = handle.last_logprob
                        self.wfile.write(
                            (json.dumps(event) + "\n").encode()
                        )
                        self.wfile.flush()
                except OSError:
                    # Client gone mid-stream — a migrating gateway cuts
                    # its upstream leg on purpose (frontend
                    # _cut_live_streams); drain the handle so the slot
                    # retires, and drop the summary nobody will read.
                    for _ in handle:
                        pass
                    return
                dt = time.perf_counter() - t0
                if handle.deadline_expired:
                    summary = {"done": False, "error": "deadline exceeded"}
                elif handle.aborted:
                    # The stream already carries tokens; the terminal event
                    # must say they are a truncation, not a completion.  A
                    # migration cut is distinguishable: the gateway relay
                    # resumes it on the new owner instead of erroring.
                    if handle.migrated:
                        summary = {"done": False, "error": "migrated",
                                   "resume": True}
                    else:
                        summary = {"done": False,
                                   "error": "generation aborted: server "
                                            "shutting down or batcher "
                                            "crashed"}
                else:
                    summary = {
                        "done": True,
                        "text": outer.tokenizer.decode(gen_ids),
                        "prompt_tokens": int(len(prompt_ids)),
                        "generated_tokens": len(gen_ids),
                        "tokens_per_s": round(len(gen_ids) / dt, 2)
                        if dt > 0 else 0.0,
                    }
                    ctx = getattr(self, "trace_ctx", None)
                    if ctx is not None:
                        summary["trace_id"] = ctx.trace_id
                try:
                    self.wfile.write(
                        (json.dumps(summary) + "\n").encode()
                    )
                    self.wfile.flush()
                except OSError:
                    pass

            def _json(self, code: int, payload: dict,
                      headers: dict | None = None) -> None:
                self._last_code = code
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                hdrs = dict(headers or {})
                # Every client-visible outcome carries the trace id so
                # a failure is findable in the fleet waterfall
                # (utils/waterfall.py), not just a success body.
                ctx = getattr(self, "trace_ctx", None)
                if ctx is not None and "x-trace-id" not in hdrs:
                    hdrs["x-trace-id"] = ctx.trace_id
                for k, v in hdrs.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="lm-server", daemon=True
        )

    def readiness(self) -> dict:
        """The /readyz verdict and its evidence — readiness is "can
        serve a NEW request well", four legs ANDed: the batcher's
        scheduler thread is alive (not crashed/stopped), the engine is
        past its first compile (first request would otherwise eat
        seconds of dead air), the replica is not draining, and it is
        not mid-export of its KV state (``migrating`` — new traffic
        routed onto a replica whose warm chains are leaving would
        admit cold AND stall behind the barrier).  The HTTP health
        contract ROADMAP item 1's front-end polls
        (docs/platform/serving.md, 'The health contract')."""
        alive = self.batcher.scheduler_alive
        warmed = self.batcher.past_first_compile
        draining = self._draining
        migrating = self._migrating
        return {
            "ready": alive and warmed and not draining and not migrating,
            "scheduler_alive": alive,
            "warmed": warmed,
            "draining": draining,
            "migrating": migrating,
            # Fleet identity + the drain fast path: a front-end
            # retiring this replica polls ``inflight`` here instead of
            # scraping metrics (serve/frontend.py), and ``replica``
            # lets registration verify it reached the right process.
            "replica": self.name,
            "inflight": self.batcher.inflight_requests,
            # Disagg role (ISSUE 20): the gateway's registration and
            # the ratio controller's reassignment both verify the
            # worker really is in the role they think it is.
            "role": self.batcher.role,
        }

    def chain_state(self) -> dict:
        """The ``GET /debug/chains`` body: this replica's identity,
        its page size, and the sorted hex chain hashes physically warm
        in its paged pool.  The ONE scrape surface the gateway fleet's
        owner-map reconstruction reads (serve/frontend.py) — N
        gateways scraping the same replicas get byte-identical bodies,
        which is what makes independently rebuilt owner maps agree
        without gossip or consensus."""
        return {
            "replica": self.name,
            "page_size": int(self.batcher.page_size),
            "chains": self.batcher.warm_chain_hashes,
        }

    def drain(self) -> None:
        """Flip /readyz to 503 without stopping work: in-flight and
        directly-addressed requests still serve.  FleetRouter.drain()
        calls this through the replica's on_drain hook."""
        self._draining = True

    def undrain(self) -> None:
        self._draining = False

    def start(self) -> "LmServer":
        self.batcher.start()
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)
        self.batcher.stop()
