"""LM serving HTTP surface: the model-serving counterpart of the
Fin-Agent service (reference 智能风控解决方案.md:175-331 serves agents over
FastAPI; here the platform's own LM serves over the same stdlib-HTTP shape
as utils/obs.py).

POST /generate  {"prompt": "text", "max_new_tokens": N}  -> {"text", ...}
POST /tokenize  {"text": "..."}                          -> {"ids": [...]}
GET  /healthz, /readyz

One InferenceEngine (KV-cache decode) + one BpeTokenizer; requests are
served sequentially per process — batching belongs to the engine layer,
and a pod-slice deployment scales replicas behind the platform ingress.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp

from ..data.tokenizer import BpeTokenizer
from .engine import InferenceEngine, SamplingConfig


def _next_pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def _prompt_bucket(n_tokens: int, max_seq: int) -> int | None:
    """Smallest compile bucket >= n_tokens that still leaves decode room.

    Power-of-two buckets up to max_seq/2 keep the compile count
    O(log max_seq); two fixed long-prompt buckets (¾·max_seq and
    max_seq-8) extend serving capacity to max_seq-8 tokens instead of
    silently rejecting everything past max_seq/2.  Returns None when the
    prompt can't fit with at least 8 tokens of decode room — callers
    report max_seq-8 as the true limit.
    """
    candidates = []
    b = 8
    while b <= max_seq // 2:
        candidates.append(b)
        b *= 2
    candidates.append((3 * max_seq // 4) // 8 * 8)
    candidates.append(max_seq - 8)
    for c in sorted(set(candidates)):
        if c >= n_tokens and c < max_seq:
            return c
    return None


class LmServer:
    """port=0 binds an ephemeral port (tests); ``.port`` is the bound one."""

    def __init__(
        self,
        model,
        params,
        tokenizer: BpeTokenizer,
        host: str = "127.0.0.1",
        port: int = 0,
        max_new_tokens_cap: int = 256,
    ):
        self.engine = InferenceEngine(model)
        self.params = params
        self.tokenizer = tokenizer
        self.started_at = time.time()
        self.cap = max_new_tokens_cap
        # The jitted decode graph is shared; serialize device access.
        self._gen_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path == "/healthz":
                    self._json(200, {"ok": True,
                                     "uptime_s": time.time() - outer.started_at})
                elif self.path == "/readyz":
                    self._json(200, {"ready": True})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    return self._json(400, {"error": "invalid JSON body"})
                if not isinstance(body, dict):
                    return self._json(400, {"error": "body must be an object"})
                if self.path == "/generate":
                    return self._generate(body)
                if self.path == "/tokenize":
                    text = body.get("text", "")
                    if not isinstance(text, str):
                        return self._json(400, {"error": "text must be a string"})
                    ids = outer.tokenizer.encode(text)
                    return self._json(200, {"ids": ids.tolist(),
                                            "count": int(ids.size)})
                return self._json(404, {"error": "not found"})

            def _generate(self, body):
                prompt = body.get("prompt", "")
                if not isinstance(prompt, str) or not prompt:
                    return self._json(400, {"error": "prompt (string) required"})
                try:
                    want = int(body.get("max_new_tokens", 32))
                    temperature = float(body.get("temperature", 0.0))
                    seed = int(body.get("seed", 0))
                except (TypeError, ValueError) as e:
                    return self._json(400, {"error": f"bad parameter: {e}"})
                ids = outer.tokenizer.encode(prompt)
                # Bucket prompt length AND decode budget to powers of two:
                # the decode graph's shape is (prompt_bucket, n_new_bucket),
                # so compile count stays O(log² max_seq) instead of one
                # multi-second retrace per distinct prompt length — all
                # while holding the generation lock.
                bucket = _prompt_bucket(int(ids.size), outer.engine.max_seq)
                if bucket is None:
                    return self._json(400, {
                        "error": f"prompt too long ({ids.size} tokens, "
                                 f"max {outer.engine.max_seq - 8})"
                    })
                room = outer.engine.max_seq - bucket
                want = max(1, min(want, outer.cap, room))
                n_new = min(_next_pow2(want), room)
                pad = bucket - int(ids.size)
                padded = jnp.zeros((1, bucket), jnp.int32).at[:, pad:].set(
                    jnp.asarray(ids, jnp.int32)[None, :]
                )
                t0 = time.perf_counter()
                with outer._gen_lock:
                    out = outer.engine.generate(
                        outer.params,
                        padded,
                        max_new_tokens=n_new,
                        sampling=SamplingConfig(temperature=temperature),
                        key=jax.random.PRNGKey(seed),
                        pad_left=pad,
                    )
                    toks = jax.device_get(out.tokens[0])
                    length = min(int(jax.device_get(out.lengths[0])), want)
                dt = time.perf_counter() - t0
                gen_ids = toks[:length].tolist()
                return self._json(200, {
                    "text": outer.tokenizer.decode(gen_ids),
                    "ids": gen_ids,
                    "prompt_tokens": int(ids.size),
                    "generated_tokens": length,
                    "tokens_per_s": round(length / dt, 2) if dt > 0 else 0.0,
                })

            def _json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="lm-server", daemon=True
        )

    def start(self) -> "LmServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)
