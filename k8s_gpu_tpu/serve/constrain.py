"""Regex-constrained decoding: structured generation for the LM server.

The modern serving stacks the reference delegates to (Ollama etc.) grow
grammar-constrained output; here it is first-party and TPU-shaped.  The
pipeline:

    regex ──parse──► AST ──Thompson──► NFA ──subset──► DFA over the
    tokenizer's character alphabet ──token walk──► two arrays:

        next_state [S, V] int32   (-1 = dead)
        allowed    [S, V] bool    (token keeps the string in-language
                                   AND completable by this vocabulary)

Everything data-dependent at decode time is a GATHER on those arrays:
each row carries its DFA state; the state's `allowed` row masks the
logits (additive -inf) before argmax/sampling; the chosen token indexes
`next_state`.  No Python in the loop, no dynamic shapes — the automaton
rides the same `lax.scan` as unconstrained decode.

Supported syntax: literals, escapes (\\d \\w \\s \\. ...), ``.``,
character classes ``[a-z0-9]`` / ``[^...]``, groups, ``|``, ``*``,
``+``, ``?``.  The DFA alphabet is the *concrete* set of characters
appearing in the tokenizer's vocabulary — transitions for characters no
token can produce are never materialized.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

# -- regex parsing (AST: tuples) --------------------------------------------
# node := ("lit", predicate_frozenset | None-for-dot)
#       | ("cat", [nodes]) | ("alt", [nodes]) | ("rep", node, min, max|-1)

_ESCAPES = {
    "d": set("0123456789"),
    "w": set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"),
    "s": set(" \t\n\r\f\v"),
}

# Control-character escapes resolve to the actual character; any OTHER
# alphanumeric escape is an error rather than silently matching the
# literal letter (standard regex engines reserve those).
_CTRL_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "f": "\f", "v": "\v", "0": "\0"}


class RegexError(ValueError):
    pass


def _escape_char(e: str) -> str:
    """Resolve a single-character escape that is not a class shorthand."""
    if e in _CTRL_ESCAPES:
        return _CTRL_ESCAPES[e]
    if e.isalnum():
        raise RegexError(f"unknown escape \\{e}")
    return e


def _parse(pattern: str):
    pos = 0

    def peek():
        return pattern[pos] if pos < len(pattern) else None

    def take():
        nonlocal pos
        c = pattern[pos]
        pos += 1
        return c

    def parse_alt():
        branches = [parse_cat()]
        while peek() == "|":
            take()
            branches.append(parse_cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def parse_cat():
        items = []
        while peek() is not None and peek() not in "|)":
            items.append(parse_rep())
        if not items:
            return ("cat", [])
        return items[0] if len(items) == 1 else ("cat", items)

    def parse_rep():
        node = parse_atom()
        while peek() in ("*", "+", "?"):
            op = take()
            if op == "*":
                node = ("rep", node, 0, -1)
            elif op == "+":
                node = ("rep", node, 1, -1)
            else:
                node = ("rep", node, 0, 1)
        return node

    def parse_class():
        negate = False
        if peek() == "^":
            take()
            negate = True
        chars: set = set()
        prev = None
        while True:
            c = peek()
            if c is None:
                raise RegexError("unterminated character class")
            take()
            if c == "]":
                break
            if c == "\\":
                e = take()
                if e in _ESCAPES:
                    chars |= _ESCAPES[e]
                    prev = None
                else:
                    resolved = _escape_char(e)
                    chars.add(resolved)
                    prev = resolved
            elif c == "-" and prev is not None and peek() not in (None, "]"):
                hi = take()
                chars |= {chr(x) for x in range(ord(prev), ord(hi) + 1)}
                prev = None
            else:
                chars.add(c)
                prev = c
        return ("lit", frozenset(chars), negate)

    def parse_atom():
        c = peek()
        if c is None:
            raise RegexError("unexpected end of pattern")
        if c == "(":
            take()
            node = parse_alt()
            if peek() != ")":
                raise RegexError("unbalanced parenthesis")
            take()
            return node
        if c == "[":
            take()
            return parse_class()
        if c == ".":
            take()
            return ("lit", None, False)  # any char
        if c == "\\":
            take()
            e = take()
            if e in _ESCAPES:
                return ("lit", frozenset(_ESCAPES[e]), False)
            return ("lit", frozenset({_escape_char(e)}), False)
        if c in ")|*+?]":
            raise RegexError(f"unexpected {c!r} at {pos}")
        take()
        return ("lit", frozenset({c}), False)

    node = parse_alt()
    if pos != len(pattern):
        raise RegexError(f"trailing input at {pos}")
    return node


# -- Thompson NFA ------------------------------------------------------------

class _Nfa:
    def __init__(self):
        self.eps: list[list[int]] = []
        # char edges: (state, predicate, negate, target); predicate None = any
        self.edges: list[tuple[int, frozenset | None, bool, int]] = []

    def state(self) -> int:
        self.eps.append([])
        return len(self.eps) - 1


def _build(nfa: _Nfa, node) -> tuple[int, int]:
    kind = node[0]
    if kind == "lit":
        _, pred, neg = node
        a, b = nfa.state(), nfa.state()
        nfa.edges.append((a, pred, neg, b))
        return a, b
    if kind == "cat":
        if not node[1]:
            a = nfa.state()
            return a, a
        first = last = None
        for child in node[1]:
            s, e = _build(nfa, child)
            if first is None:
                first = s
            else:
                nfa.eps[last].append(s)
            last = e
        return first, last
    if kind == "alt":
        a, b = nfa.state(), nfa.state()
        for child in node[1]:
            s, e = _build(nfa, child)
            nfa.eps[a].append(s)
            nfa.eps[e].append(b)
        return a, b
    if kind == "rep":
        _, child, lo, hi = node
        if (lo, hi) == (0, 1):        # ?
            s, e = _build(nfa, child)
            nfa.eps[s].append(e)
            return s, e
        if (lo, hi) == (0, -1):       # *
            a = nfa.state()
            s, e = _build(nfa, child)
            nfa.eps[a].append(s)
            nfa.eps[e].append(a)
            return a, a
        if (lo, hi) == (1, -1):       # +
            s, e = _build(nfa, child)
            nfa.eps[e].append(s)
            return s, e
    raise RegexError(f"unsupported node {node!r}")


def _closure(nfa: _Nfa, states: frozenset) -> frozenset:
    out = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in out:
                out.add(t)
                stack.append(t)
    return frozenset(out)


def _step(nfa: _Nfa, states: frozenset, ch: str) -> frozenset:
    out = set()
    for s, pred, neg, t in nfa.edges:
        if s in states:
            hit = True if pred is None else (ch in pred) != neg
            if hit:
                out.add(t)
    return _closure(nfa, out) if out else frozenset()


# -- DFA + token tables ------------------------------------------------------

@dataclass
class RegexConstraint:
    """Token-level automaton for one pattern + one vocabulary."""
    next_state: jnp.ndarray   # [S, V] int32, -1 = dead
    allowed: jnp.ndarray      # [S, V] bool
    accepting: jnp.ndarray    # [S] bool
    start: int
    pattern: str

    @property
    def n_states(self) -> int:
        return int(self.next_state.shape[0])


class ConstraintBank:
    """A fixed set of named patterns, banked for continuous batching.

    Per-request constraints in one decode program need uniform table
    shapes, so — exactly like the LoRA AdapterBank — patterns are
    compiled up-front and padded to the bank maximum:

        next  [C, S_max, V] int32   allowed [C, S_max, V] bool

    Index 0 is "unconstrained": a single all-permissive self-loop
    state, so unconstrained rows run the same gathers with a mask
    that never masks.  Each decode row carries (cidx, cstate); both
    are data, never shapes.
    """

    def __init__(self, patterns: dict[str, str], token_strings: list[str]):
        self.names = ["__free__"] + sorted(patterns)
        self.compiled = {
            name: compile_constraint(pat, token_strings)
            for name, pat in patterns.items()
        }
        V = len(token_strings)
        S = max(
            [1] + [c.n_states for c in self.compiled.values()]
        )
        C = len(self.names)
        nxt = np.full((C, S, V), -1, np.int32)
        allow = np.zeros((C, S, V), bool)
        # index 0: one state, everything allowed, self-loop
        nxt[0, 0, :] = 0
        allow[0, 0, :] = True
        accepting = np.zeros((C, S), bool)
        accepting[0, 0] = True
        for i, name in enumerate(self.names[1:], start=1):
            c = self.compiled[name]
            s = c.n_states
            nxt[i, :s] = np.asarray(c.next_state)
            allow[i, :s] = np.asarray(c.allowed)
            accepting[i, :s] = np.asarray(c.accepting)
        self.next_state = jnp.asarray(nxt)
        self.allowed = jnp.asarray(allow)
        self.accepting = jnp.asarray(accepting)

    @property
    def banked(self):
        """None when no real patterns — callers skip the gathers."""
        if len(self.names) == 1:
            return None
        return {"next": self.next_state, "allowed": self.allowed}

    def index(self, name: str | None) -> int:
        if name is None:
            return 0
        try:
            return self.names.index(name)
        except ValueError:
            raise KeyError(
                f"unknown constraint {name!r}; serving {self.names[1:]}"
            ) from None


def compile_constraint(pattern: str, token_strings: list[str]) -> RegexConstraint:
    """Build the [S, V] token tables for *pattern* over a vocabulary.

    ``token_strings[v]`` is the text token v decodes to.  A token is
    allowed in state s iff walking its characters stays in-language AND
    the landing state can still reach acceptance via tokens of this
    vocabulary; empty tokens are never allowed (they would stall the
    automaton)."""
    ast = _parse(pattern)
    nfa = _Nfa()
    s0, s_end = _build(nfa, ast)

    alphabet = sorted({c for t in token_strings for c in t})
    start = _closure(nfa, frozenset({s0}))
    # Subset construction over the concrete alphabet.
    states: dict[frozenset, int] = {start: 0}
    order = [start]
    char_next: list[dict[str, int]] = []
    i = 0
    while i < len(order):
        cur = order[i]
        row: dict[str, int] = {}
        for ch in alphabet:
            nxt = _step(nfa, cur, ch)
            if not nxt:
                continue
            if nxt not in states:
                states[nxt] = len(order)
                order.append(nxt)
                if len(order) > 4096:
                    raise RegexError(
                        "constraint DFA exceeds 4096 states; simplify the "
                        "pattern"
                    )
            row[ch] = states[nxt]
        char_next.append(row)
        i += 1

    S, V = len(order), len(token_strings)
    accepting = np.array([s_end in sub for sub in order], bool)
    # Vectorize the token walk over states: T[ch] maps [S]→[S] (with a
    # dead sentinel at index S), so a token's table column is
    # len(token) chained gathers on an [S] vector instead of an
    # S×V×len Python triple loop (minutes-scale for real BPE vocabs).
    DEAD = S
    trans = {}
    for ch in alphabet:
        col = np.full(S + 1, DEAD, np.int32)
        for s in range(S):
            col[s] = char_next[s].get(ch, DEAD)
        trans[ch] = col
    next_state = np.full((S, V), -1, np.int32)
    identity = np.arange(S, dtype=np.int32)
    for v, tok in enumerate(token_strings):
        if not tok:
            # Empty tokens are never allowed — they would stall the
            # automaton (and the decode loop) without consuming input.
            continue
        cur = identity
        for ch in tok:
            cur = trans[ch][cur]
        next_state[:, v] = np.where(cur == DEAD, -1, cur)
    # Prefix-validity is not completability: a token can keep the string
    # in-language while landing in a state no token in THIS vocabulary
    # can ever extend to acceptance (a bare '"' walking into the middle
    # of a property name the tokenizer only carries whole).  The decode
    # loop then dead-ends and retires the row on EOS with an unparseable
    # prefix.  Prune to token-live states — accepting, or with some
    # transition into a live state — as a fixpoint over the TOKEN tables
    # (character-level liveness is not enough: the stranded state above
    # is char-live but token-dead).
    valid = next_state >= 0
    tgt = np.where(valid, next_state, 0)
    live = accepting.copy()
    while True:
        grown = live | (valid & live[tgt]).any(axis=1)
        if (grown == live).all():
            break
        live = grown
    next_state = np.where(valid & live[tgt], next_state, -1)
    return RegexConstraint(
        next_state=jnp.asarray(next_state),
        allowed=jnp.asarray(next_state >= 0),
        accepting=jnp.asarray(accepting),
        start=0,
        pattern=pattern,
    )
