"""Per-tenant weighted-fair admission control for the gateway fleet.

PR 15's front door sheds on raw queue pressure: one hot tenant posting
floods can eat the whole shed budget while a polite tenant starves —
the ``serve_tenant_*`` counters SEE the skew but nothing acts on it.
This module is the acting half (ROADMAP item 3): an
``AdmissionController`` every ``FleetFrontend`` consults at the door,
built from three classic mechanisms composed deterministically:

- **Weighted-fair queueing** — entitlement-vs-service deficits over
  per-tenant queues: every granted token entitles each backlogged
  tenant its weight-share, every grant debits the grantee its cost,
  and each free slot goes to the most underserved tenant — so
  admitted-token throughput converges to the weight ratio no matter
  how lopsided the arrival rates are (a 10:1 flood degrades the
  flooder, not the fleet, and no tenant name order can starve
  anyone).
- **Priority classes** — ``interactive`` strictly precedes ``batch``
  at every round boundary, and when the slot pool is exhausted a
  waiting interactive request PREEMPTS a granted-but-not-yet-running
  batch ticket (the batch work re-queues at the front of its tenant
  queue — delayed, never lost; ``admission_preemptions_total``).
- **Token-rate quotas** — a per-tenant token bucket (rate × burst)
  refilled on the injected clock; an offer the bucket cannot cover is
  throttled at the door (``admission_quota_throttled_total{tenant}``)
  before it can occupy queue space.

The SLO budget plane (PR 14) is the feedback loop: ``burn_source`` (a
zero-arg callable, typically reading ``slo_burn_rate_fast`` off the
fleet registry) decides which class sheds first — at
``burn_shed_batch`` the batch class sheds at the door while
interactive still admits; only past ``burn_shed_interactive`` does
interactive shed too.  Degradation is ordered, never alphabetical.

Determinism is a hard contract (this module is in
``DETERMINISTIC_PLANES``): every decision is a pure function of (offer
sequence, policy table, injected Clock) — tenants iterate in sorted
order, ticket ids are a monotone sequence, and the only time source is
``clock.now()`` — so the WFQ fairness test replays two-run
byte-identical under ``FakeClock``.  The ``threading.Event`` per
ticket exists only for the HTTP path's blocking wait
(``FleetFrontend._generate``); the synchronous ``offer``/``pump``/
``release`` API never touches wall time.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..utils.clock import Clock, RealClock
from ..utils.metrics import MetricsRegistry, global_metrics

# Priority vocabulary, strongest first — the round boundary serves
# classes in exactly this order.
PRIORITY_CLASSES = ("interactive", "batch")

# Ticket lifecycle states (the ``state`` vocabulary):
#   queued     waiting in its tenant queue for a DRR grant
#   granted    holds a slot; not yet running — still preemptible
#   running    work started downstream — immune to preemption
#   done       released; slot returned
#   throttled  quota bucket could not cover the offer (shed at the door)
#   shed       burn-driven or queue-bound shed (never entered a slot)
TICKET_STATES = (
    "queued", "granted", "running", "done", "throttled", "shed",
)


@dataclass
class TenantPolicy:
    """One tenant's admission contract.  ``weight`` scales the
    entitlement share (2.0 admits twice the tokens of 1.0 under
    contention); ``priority`` picks the class; ``quota_tokens_per_s``
    of None means unmetered, and ``quota_burst`` defaults to two
    seconds of rate."""

    weight: float = 1.0
    priority: str = "interactive"
    quota_tokens_per_s: float | None = None
    quota_burst: float | None = None


@dataclass
class Ticket:
    """One admission request.  ``tokens`` is the cost the DRR deficit
    must cover (prompt + requested budget — the quantity quotas meter
    and fairness balances).  ``shed_reason`` explains a terminal
    ``throttled``/``shed`` state."""

    seq: int
    tenant: str
    tokens: int
    priority: str
    t_offer: float
    state: str = "queued"
    shed_reason: str = ""
    preemptions: int = 0
    _event: threading.Event = field(
        default_factory=threading.Event, repr=False, compare=False,
    )

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the next state transition signal (HTTP path
        only — deterministic tests drive pump() synchronously)."""
        return self._event.wait(timeout)


class AdmissionController:
    """Deficit-round-robin admission over per-tenant queues (module
    docstring for the model).  Thread-safe; every offer/pump/release
    serializes on one lock — admission is host-side bookkeeping."""

    # Lock contract (graftcheck lockcheck + utils.faults
    # guard_declared): the policy table, per-tenant queues/deficits/
    # buckets, the granted-slot set, and the share accumulators are
    # shared between every handler thread offering work and every
    # thread releasing it.
    _GUARDED_BY = {
        "_lock": (
            "_policies", "_queues", "_deficits", "_buckets",
            "_held", "_shares", "_seq", "_share_t",
        ),
    }

    def __init__(
        self,
        *,
        slots: int = 8,
        quantum_tokens: float = 64.0,
        clock: Clock | None = None,
        metrics: MetricsRegistry | None = None,
        burn_source=None,
        burn_shed_batch: float = 14.4,
        burn_shed_interactive: float = 28.8,
        max_queue_per_tenant: int = 64,
        share_halflife_s: float = 30.0,
    ):
        """``slots`` bounds concurrently admitted requests (the
        gateway's dispatch width, NOT the replicas' decode slots —
        replicas still shed 429 on their own queue).
        ``quantum_tokens`` is a display-scale knob only (the snapshot
        and ``obs gateways`` surface it for operators reading deficit
        magnitudes); fairness itself is entitlement bookkeeping and
        needs no quantum — see ``_pump_locked``.  ``burn_source``
        is the PR 14 feedback: a zero-arg callable returning the
        current fast burn rate; see the module docstring for the
        two-threshold shed order.  ``share_halflife_s`` is the decay
        of the admitted-token share accumulator behind
        ``admission_tenant_share`` — recent traffic dominates, history
        forgives."""
        self.slots = max(1, int(slots))
        self.quantum = max(1.0, float(quantum_tokens))
        self.clock = clock or RealClock()
        self.metrics = metrics if metrics is not None else global_metrics
        self.burn_source = burn_source
        self.burn_shed_batch = float(burn_shed_batch)
        self.burn_shed_interactive = float(burn_shed_interactive)
        self.max_queue_per_tenant = max(1, int(max_queue_per_tenant))
        self.share_halflife_s = max(1e-3, float(share_halflife_s))
        self._lock = threading.Lock()
        self._policies: dict[str, TenantPolicy] = {}
        self._queues: dict[str, list[Ticket]] = {}
        self._deficits: dict[str, float] = {}
        # tenant -> (level, last_refill_t): the quota token bucket.
        self._buckets: dict[str, tuple[float, float]] = {}
        # seq -> Ticket for every granted/running slot holder.
        self._held: dict[int, Ticket] = {}
        # tenant -> decayed admitted-token accumulator (the share gauge).
        self._shares: dict[str, float] = {}
        self._share_t = self.clock.now()
        self._seq = 0

    # -- policy ------------------------------------------------------------
    def set_tenant(
        self,
        tenant: str,
        *,
        weight: float = 1.0,
        priority: str = "interactive",
        quota_tokens_per_s: float | None = None,
        quota_burst: float | None = None,
    ) -> TenantPolicy:
        """Declare (or replace) a tenant's policy.  Unknown tenants
        admit under the default ``TenantPolicy()`` — admission control
        must never turn 'unconfigured' into 'locked out'."""
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"priority must be one of {PRIORITY_CLASSES}, "
                f"got {priority!r}"
            )
        pol = TenantPolicy(
            weight=max(1e-6, float(weight)),
            priority=priority,
            quota_tokens_per_s=(
                float(quota_tokens_per_s)
                if quota_tokens_per_s is not None else None
            ),
            quota_burst=(
                float(quota_burst) if quota_burst is not None else None
            ),
        )
        with self._lock:
            self._policies[str(tenant)] = pol
            # A policy change resets the bucket to full burst at the
            # change instant — deterministic, and never punishes a
            # tenant for a mid-flight quota edit.
            self._buckets.pop(str(tenant), None)
        return pol

    def policy(self, tenant: str) -> TenantPolicy:
        with self._lock:
            return self._policies.get(tenant) or TenantPolicy()

    # -- the door ----------------------------------------------------------
    def offer(self, tenant: str, tokens: int) -> Ticket:
        """Present ``tokens`` of work for ``tenant``.  Returns a
        Ticket whose state is one of: ``granted`` (slot held — call
        ``try_run`` then ``release``), ``queued`` (wait and re-pump),
        or terminal ``throttled``/``shed`` (the door refused; the
        reason is on the ticket)."""
        tenant = str(tenant)
        tokens = max(1, int(tokens))
        now = self.clock.now()
        with self._lock:
            pol = self._policies.get(tenant) or TenantPolicy()
            self._seq += 1
            t = Ticket(
                seq=self._seq, tenant=tenant, tokens=tokens,
                priority=pol.priority, t_offer=now,
            )
            if not self._quota_take_locked(tenant, pol, tokens, now):
                t.state = "throttled"
                t.shed_reason = "quota"
                t._event.set()
                self.metrics.inc(
                    "admission_quota_throttled_total", tenant=tenant
                )
                return t
            burn = self._burn()
            if burn >= self.burn_shed_interactive or (
                burn >= self.burn_shed_batch and pol.priority == "batch"
            ):
                # The PR 14 feedback loop: budget burning too fast →
                # shed at the door, batch class first.
                t.state = "shed"
                t.shed_reason = "burn"
                t._event.set()
                self.metrics.inc(
                    "admission_sheds_total", reason="burn"
                )
                return t
            q = self._queues.setdefault(tenant, [])
            if len(q) >= self.max_queue_per_tenant:
                t.state = "shed"
                t.shed_reason = "queue_full"
                t._event.set()
                self.metrics.inc(
                    "admission_sheds_total", reason="queue_full"
                )
                return t
            q.append(t)
            self._pump_locked(now)
        return t

    def pump(self) -> None:
        """Run one grant round now — the synchronous hook the
        deterministic tests and the HTTP wait loop drive."""
        with self._lock:
            self._pump_locked(self.clock.now())

    def try_run(self, ticket: Ticket) -> bool:
        """Atomically promote a ``granted`` ticket to ``running``
        (immune to preemption).  False means the grant was preempted
        or shed meanwhile — keep waiting or give up."""
        with self._lock:
            if ticket.state == "granted":
                ticket.state = "running"
                return True
            return False

    def release(self, ticket: Ticket) -> None:
        """Return a granted/running ticket's slot and run a round —
        idempotent, safe on terminal tickets."""
        with self._lock:
            if ticket.state in ("granted", "running"):
                ticket.state = "done"
                self._held.pop(ticket.seq, None)
                self._pump_locked(self.clock.now())

    def cancel(self, ticket: Ticket, reason: str = "timeout") -> None:
        """Withdraw a still-queued ticket (the HTTP wait loop's
        deadline path) — a no-op for any other state."""
        with self._lock:
            if ticket.state != "queued":
                return
            q = self._queues.get(ticket.tenant)
            if q is not None and ticket in q:
                q.remove(ticket)
            ticket.state = "shed"
            ticket.shed_reason = reason
            ticket._event.set()
            self.metrics.inc("admission_sheds_total", reason=reason)

    def await_grant(
        self, ticket: Ticket, deadline: float | None = None,
        poll_s: float = 0.01,
    ) -> bool:
        """The HTTP path's blocking wait: True once ``ticket`` is
        RUNNING (grant won and promoted), False when it terminated or
        ``deadline`` (clock domain) expired — the ticket is cancelled
        so it cannot be granted after the caller walked away."""
        while True:
            with self._lock:
                st = ticket.state
                if st == "granted":
                    ticket.state = "running"
                    return True
                if st in ("throttled", "shed", "done"):
                    return False
                ticket._event.clear()
            if deadline is not None and self.clock.now() >= deadline:
                self.cancel(ticket, reason="timeout")
                return False
            ticket.wait(poll_s)
            self.pump()

    # -- internals ---------------------------------------------------------
    def _burn(self) -> float:
        if self.burn_source is None:
            return 0.0
        try:
            return float(self.burn_source() or 0.0)
        except Exception:
            return 0.0

    def _quota_take_locked(
        self, tenant: str, pol: TenantPolicy, tokens: int, now: float,
    ) -> bool:
        rate = pol.quota_tokens_per_s
        if rate is None:
            return True
        burst = (
            pol.quota_burst if pol.quota_burst is not None
            else 2.0 * rate
        )
        level, last = self._buckets.get(tenant, (burst, now))
        level = min(burst, level + rate * max(0.0, now - last))
        if tokens > level:
            self._buckets[tenant] = (level, now)
            return False
        self._buckets[tenant] = (level - tokens, now)
        return True

    def _grant_locked(self, tenant: str, t: Ticket, now: float) -> None:
        t.state = "granted"
        self._held[t.seq] = t
        self._record_share_locked(tenant, float(t.tokens), now)
        t._event.set()

    def _record_share_locked(
        self, tenant: str, tokens: float, now: float,
    ) -> None:
        """Decay every accumulator to ``now``, add the grant, export
        the normalized per-tenant share gauge."""
        dt = max(0.0, now - self._share_t)
        if dt > 0.0:
            decay = 0.5 ** (dt / self.share_halflife_s)
            for k in list(self._shares):
                self._shares[k] *= decay
            self._share_t = now
        self._shares[tenant] = self._shares.get(tenant, 0.0) + tokens
        total = sum(self._shares.values())
        if total > 0.0:
            for k in sorted(self._shares):
                self.metrics.set_gauge(
                    "admission_tenant_share",
                    self._shares[k] / total, tenant=k,
                )

    def _preempt_locked(self, now: float) -> int:
        """The round-boundary preemption: revoke granted-but-not-
        running BATCH tickets (newest grant first — it lost the least
        progress) to free slots for waiting interactive work.  The
        revoked ticket re-queues at the FRONT of its tenant queue with
        its cost already share-accounted, so it wins its next
        eligible round instead of starving behind the flood."""
        waiting = sum(
            len(q) for t, q in self._queues.items()
            if q and (
                self._policies.get(t) or TenantPolicy()
            ).priority == "interactive"
        )
        if waiting <= 0:
            return 0
        revocable = sorted(
            (
                t for t in self._held.values()
                if t.state == "granted" and t.priority == "batch"
            ),
            key=lambda t: -t.seq,
        )
        n = 0
        for t in revocable:
            if waiting <= 0:
                break
            self._held.pop(t.seq, None)
            t.state = "queued"
            t.preemptions += 1
            t._event.set()
            self._queues.setdefault(t.tenant, []).insert(0, t)
            self.metrics.inc(
                "admission_preemptions_total", **{"class": "batch"}
            )
            waiting -= 1
            n += 1
        return n

    def _pump_locked(self, now: float) -> None:
        """One grant round per priority class, interactive first.
        Weighted fairness is entitlement-vs-service bookkeeping:
        ``_deficits[t]`` is the tokens tenant ``t`` was ENTITLED to
        minus the tokens it was GRANTED.  Every grant of ``C`` tokens
        credits each backlogged tenant in the class its weight-share
        of ``C`` and debits the grantee ``C`` — deficits sum to ~zero,
        and each free slot goes to the most underserved backlogged
        tenant (max deficit; ties break to the sorted-first name), so
        granted-token throughput converges to the weight ratio under
        any arrival skew.  Per-round credit ACCRUAL (textbook DRR)
        does not have that property at this door: slots, not credit,
        are the binding constraint, so a flooder whose credit refills
        every pump stays richest forever and starves the rest — the
        weight-skew regression in test_gateway_ha pins this.  A
        tenant whose queue empties forfeits leftover credit but keeps
        its debt (no hoarding, and no debt amnesty by draining)."""
        free = self.slots - len(self._held)
        if free <= 0:
            free += self._preempt_locked(now)
        for cls in PRIORITY_CLASSES:
            while free > 0:
                backlogged = sorted(
                    t for t, q in self._queues.items()
                    if q and (
                        self._policies.get(t) or TenantPolicy()
                    ).priority == cls
                )
                if not backlogged:
                    break
                best = backlogged[0]
                for t in backlogged[1:]:
                    if (self._deficits.get(t, 0.0)
                            > self._deficits.get(best, 0.0)):
                        best = t
                head = self._queues[best].pop(0)
                cost = float(head.tokens)
                w_all = sum(
                    (self._policies.get(t) or TenantPolicy()).weight
                    for t in backlogged
                )
                for t in backlogged:
                    w = (self._policies.get(t) or TenantPolicy()).weight
                    self._deficits[t] = (
                        self._deficits.get(t, 0.0) + cost * (w / w_all)
                    )
                self._deficits[best] -= cost
                if not self._queues[best]:
                    self._deficits[best] = min(
                        0.0, self._deficits[best]
                    )
                self._grant_locked(best, head, now)
                free -= 1
        for cls in PRIORITY_CLASSES:
            depth = sum(
                len(q) for t, q in self._queues.items()
                if (
                    self._policies.get(t) or TenantPolicy()
                ).priority == cls
            )
            self.metrics.set_gauge(
                "admission_queue_depth", float(depth),
                **{"class": cls},
            )

    # -- read surface ------------------------------------------------------
    def snapshot(self) -> dict:
        """The explain view (``obs gateways`` / ``/admin/admission``):
        per tenant, its policy, DRR deficit, queue depth, quota level,
        and decayed admitted-token share — sorted keys throughout, the
        two-run byte-identity surface."""
        with self._lock:
            tenants = sorted(
                set(self._policies) | set(self._queues)
                | set(self._shares)
            )
            total = sum(self._shares.values())
            held = sorted(
                (t.tenant, t.seq, t.state) for t in self._held.values()
            )
            out = {
                "slots": self.slots,
                "held": len(held),
                "holders": [
                    {"tenant": t, "seq": s, "state": st}
                    for t, s, st in held
                ],
                "quantum": self.quantum,
                "tenants": [],
            }
            for t in tenants:
                pol = self._policies.get(t) or TenantPolicy()
                level = None
                if pol.quota_tokens_per_s is not None:
                    burst = (
                        pol.quota_burst
                        if pol.quota_burst is not None
                        else 2.0 * pol.quota_tokens_per_s
                    )
                    lv, last = self._buckets.get(
                        t, (burst, self._share_t)
                    )
                    level = round(lv, 4)
                out["tenants"].append({
                    "tenant": t,
                    "weight": pol.weight,
                    "priority": pol.priority,
                    "deficit": round(self._deficits.get(t, 0.0), 4),
                    "queued": len(self._queues.get(t, ())),
                    "quota_tokens_per_s": pol.quota_tokens_per_s,
                    "quota_level": level,
                    "share": round(
                        (self._shares.get(t, 0.0) / total)
                        if total > 0 else 0.0, 6,
                    ),
                })
            return out
