"""Multi-LoRA serving: a bank of adapters, one batched decode.

The platform trains LoRA fine-tunes (train/lora.py, the reference's
prescribed PEFT recipe, 模型微调最佳实践.md:19-33); serving them
one-process-per-adapter would waste a chip per tenant.  The bank stacks
every adapter into per-layer arrays so a single decode program serves
base and all adapters at once — each batch row gathers ITS adapter by
index (the S-LoRA/punica idea, XLA-shaped):

- leaves are stacked ``[L, K+1, fin, R]`` / ``[L, K+1, R, fout]`` — the
  layer axis leads so adapters ride the engine's existing layer scan;
- index 0 is the base "adapter": exact zeros, so base rows compute
  ``x@W + (x@0)@0`` — bitwise identical to the un-adapted program;
- heterogeneous ranks zero-pad to the bank max (padding contributes
  exactly zero to the delta);
- each adapter's LoRA scale is folded into its B half at bank build
  (``scale·(xA)B = (xA)(scale·B)``), so runtime needs no per-row scale.

Adding/removing an adapter rebuilds the bank (K changes the array
shapes → one recompile); banks are small — K·L·(fin+fout)·R floats.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..train.lora import LoraConfig

# Engine-supported targets: the attention projections (train/lora.py's
# default recipe).  MLP adapters would follow the same pattern.
SERVABLE_TARGETS = ("wq", "wk", "wv", "wo")


class AdapterBank:
    """names[0] is always "__base__" (the zero adapter)."""

    def __init__(self, adapters: dict[str, tuple[dict, LoraConfig]]):
        """adapters: name → (lora_params from LoraAdapter.init, its
        LoraConfig).  Only attention-projection targets are banked;
        an adapter carrying other targets is rejected loudly rather
        than silently serving a different model than was trained."""
        self.names = ["__base__"] + sorted(adapters)
        for name, (tree, _) in adapters.items():
            extra = [
                t for t in tree.get("blocks", {}) if t not in SERVABLE_TARGETS
            ] + [t for t in tree if t != "blocks"]
            if extra:
                raise ValueError(
                    f"adapter {name!r} adapts {extra}; the serving bank "
                    f"supports {SERVABLE_TARGETS} only"
                )
        if not adapters:
            self.banked = None
            return
        ranks = {
            name: next(iter(tree["blocks"].values()))["a"].shape[-1]
            for name, (tree, _) in adapters.items()
        }
        R = max(ranks.values())
        # Leaf shapes come from whichever adapter carries each target.
        shapes = {}
        for name, (tree, _) in adapters.items():
            for t, ab in tree["blocks"].items():
                L, fin, _ = ab["a"].shape
                fout = ab["b"].shape[-1]
                shapes[t] = (L, fin, fout)
        K = len(self.names)
        banked = {}
        for t, (L, fin, fout) in shapes.items():
            a = np.zeros((L, K, fin, R), np.float32)
            b = np.zeros((L, K, R, fout), np.float32)
            for i, name in enumerate(self.names[1:], start=1):
                tree, cfg = adapters[name]
                ab = tree["blocks"].get(t)
                if ab is None:
                    continue
                r = ab["a"].shape[-1]
                a[:, i, :, :r] = np.asarray(ab["a"], np.float32)
                b[:, i, :r, :] = np.asarray(ab["b"], np.float32) * cfg.scale
            banked[t] = {"a": jnp.asarray(a), "b": jnp.asarray(b)}
        self.banked = banked

    def index(self, name: str | None) -> int:
        if name is None:
            return 0
        try:
            return self.names.index(name)
        except ValueError:
            raise KeyError(
                f"unknown adapter {name!r}; serving {self.names[1:]}"
            ) from None


def lora_delta(inp, ad, idx, dt):
    """Per-row low-rank correction for one layer's target.

    inp [B, S, fin] (the same activation the base matmul consumes,
    flattened on its input dims); ad {"a": [K, fin, R], "b": [K, R,
    fout]} (this layer's bank slice); idx [B] adapter per row.
    Returns [B, S, fout].
    """
    a = ad["a"][idx].astype(dt)   # [B, fin, R]
    b = ad["b"][idx].astype(dt)   # [B, R, fout]
    xa = jnp.einsum("bsf,bfr->bsr", inp, a)
    return jnp.einsum("bsr,bro->bso", xa, b)
