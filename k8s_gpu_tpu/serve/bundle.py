"""Servable model bundles — the export→serve half of the model lifecycle.

The reference's journey is train → ``/output`` → MinIO model asset →
serving workload (GPU调度平台搭建.md:686-697; the Fin-Agent service then
consumes a served model, 智能风控解决方案.md:368-419).  The raw Orbax
checkpoint export (train/checkpoint.py) preserves *training state* but
not the model's identity — nothing could reconstruct the architecture
from it.  A servable bundle is self-describing:

    payload/
      config.json     TransformerConfig fields (+ leaf dtype/shape table)
      params.npz      every param leaf, path-keyed ("blocks/wq", ...)
      tokenizer.json  optional BPE merges

so ``load_servable(store, space, id)`` → (model, params, tokenizer) with
no other context — exactly what a serving pod gets scheduled with.
Quantized trees (serve/quant.py {q,s} leaves) flatten naturally, so an
exported int8 model serves as int8.  bfloat16 leaves ride npz as raw
void bytes (numpy can't tag ml_dtypes) and are re-viewed on load using
the dtype table.
"""

from __future__ import annotations

import dataclasses
import json
import tempfile
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from ..data.tokenizer import BpeTokenizer
from ..models.transformer import TransformerConfig, TransformerLM
from ..platform.assets import Asset, AssetStore


def _flatten(tree: dict, prefix: str = ""):
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            yield from _flatten(v, key)
        else:
            yield key, v


def _unflatten(flat: dict) -> dict:
    out: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


def export_servable(
    store: AssetStore, space: str, asset_id: str,
    model: TransformerLM, params: dict,
    tokenizer: BpeTokenizer | None = None,
) -> Asset:
    """Write a self-describing bundle into the AssetStore (kind 'model')."""
    leaves = dict(_flatten(params))
    with tempfile.TemporaryDirectory() as td:
        d = Path(td)
        cfg = dataclasses.asdict(model.cfg)
        cfg["dtype"] = jnp.dtype(model.cfg.dtype).name
        cfg_doc = {
            "format": "k8s-gpu-tpu-servable-v1",
            "model": "TransformerLM",
            "config": cfg,
            "leaves": {
                k: {"dtype": jnp.dtype(v.dtype).name, "shape": list(v.shape)}
                for k, v in leaves.items()
            },
            "tokenizer": tokenizer is not None,
        }
        (d / "config.json").write_text(json.dumps(cfg_doc))
        np.savez(d / "params.npz",
                 **{k: np.asarray(v) for k, v in leaves.items()})
        if tokenizer is not None:
            tokenizer.save(d / "tokenizer.json")
        return store.import_path(space, "model", asset_id, d)


def load_servable(
    store: AssetStore, space: str, asset_id: str, version: str = "",
):
    """Asset → (TransformerLM, params, tokenizer | None)."""
    import ml_dtypes

    asset = store.get(space, "model", asset_id, version)
    root = Path(asset.path)
    if not root.is_dir() or not (root / "config.json").exists():
        raise ValueError(
            f"{space}/model/{asset_id}@{asset.version} is not a servable "
            "bundle (raw checkpoint exports lack config.json — re-export "
            "with serve.bundle.export_servable)"
        )
    doc = json.loads((root / "config.json").read_text())
    if doc.get("format") != "k8s-gpu-tpu-servable-v1":
        raise ValueError(
            f"{space}/model/{asset_id}@{asset.version} is not a servable "
            "bundle (raw checkpoint exports lack config.json — re-export "
            "with serve.bundle.export_servable)"
        )
    cfg_fields = dict(doc["config"])
    cfg_fields["dtype"] = jnp.dtype(cfg_fields["dtype"]).type
    model = TransformerLM(TransformerConfig(**cfg_fields))
    flat = {}
    with np.load(root / "params.npz") as z:
        for key, meta in doc["leaves"].items():
            a = z[key]
            want = np.dtype(getattr(ml_dtypes, meta["dtype"], None)
                            or meta["dtype"])
            if a.dtype != want:  # bf16 etc. came back as void bytes
                a = a.view(want)
            flat[key] = jnp.asarray(a.reshape(meta["shape"]))
    params = _unflatten(flat)
    tok = None
    if doc.get("tokenizer"):
        tok = BpeTokenizer.load(root / "tokenizer.json")
    return model, params, tok
