"""Int8 weight-only quantization for serving.

Decode is HBM-bandwidth-bound: each step re-streams every weight matrix
from HBM for a [B, 1, D] activation.  Storing weights as int8 with
per-output-channel float scales halves the bytes per step versus
bfloat16; XLA fuses the ``int8 → bf16 multiply-by-scale`` dequant into
the matmul's operand read, so the MXU still computes in bf16 and no
full-precision copy ever materializes (the reason quantization happens
*inside* the traced computation, not as a preprocessing pass).

A quantized leaf is the pytree ``{"q": int8[...], "s": f32[broadcastable]}``
— ``models.transformer.wt`` transparently dequantizes it wherever a
weight is read, so the same ``InferenceEngine`` (and the pipeline-free
training forward, if anyone wants QAT-style eval) consumes either form.
Scales are per-output-channel: the max-abs over each weight's
*contraction* axes, so quantization error stays relative per channel.

The reference has no quantization story (it sizes VRAM for fp16 and
mentions TensorRT only as prose, GPU选型与优化指南.md:33-50); this is
part of the serving stack that replaces its Ollama delegation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Contraction axes per stacked weight leaf (models/transformer.py:init):
# the scale keeps every *other* axis, so each output channel (and each
# layer / expert along the stacked axes) gets its own scale.
_CONTRACT_AXES = {
    "wq": (1,),        # [L, D, H, Dh] — contract D
    "wk": (1,),
    "wv": (1,),
    "wo": (1, 2),      # [L, H, Dh, D] — contract H, Dh
    "wi_gate": (1,),   # [L, D, F]
    "wi_up": (1,),
    "wo_mlp": (1,),    # [L, F, D]
    "e_wi_gate": (2,),  # [L, E, D, F]
    "e_wi_up": (2,),
    "e_wo": (2,),      # [L, E, F, D]
}
_TOP_LEVEL = {
    "head": (0,),      # [D, V] — contract D
    "embed": (1,),     # [V, D] — per-row scale (gather, not matmul)
}


def _quantize_leaf(w, axes):
    s = jnp.max(jnp.abs(w), axis=axes, keepdims=True) / 127.0
    s = jnp.where(s == 0, 1.0, s).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def quantize_params(params: dict, *, quantize_embed: bool = True) -> dict:
    """Return a serving param tree with matmul weights as int8+scale.

    Norm gains (`ln1`, `ln2`, `final_norm`) and the MoE router (`gate`)
    stay float — they are tiny and precision-sensitive.  Leaves the
    input tree untouched.
    """
    out = dict(params)
    blocks = dict(params["blocks"])
    for name, axes in _CONTRACT_AXES.items():
        if name in blocks:
            blocks[name] = _quantize_leaf(blocks[name], axes)
    out["blocks"] = blocks
    for name, axes in _TOP_LEVEL.items():
        if name == "embed" and not quantize_embed:
            continue
        out[name] = _quantize_leaf(params[name], axes)
    return out


def quantize_act(x):
    """x [..., K] → (int8 values, f32 per-row scale [...]): symmetric
    absmax over the contraction axis — the activation half of an int8 ×
    int8 matmul.  Dynamic (computed inside the trace per step): decode
    activations are [B, 1, D]-tiny, so the absmax costs nothing next to
    the weight stream it unlocks."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def int8_dot(x, leaf, out_dtype):
    """True int8 matmul against a quantized leaf: quantize ``x`` per row,
    contract int8 × int8 → int32 on the device's integer path, rescale by
    (activation scale ⊗ per-channel weight scale).  Versus the ``wt``
    dequant-into-matmul form this also halves the *compute* width — the
    draft model's whole reason to exist is being cheap, and speculative
    acceptance tolerates draft quantization error (the verify pass is
    exact regardless of what the draft proposes).

    ``leaf`` is a per-layer slice of the ``{"q", "s"}`` form: contraction
    axes are the leading axes of ``q`` (the ones ``s`` keeps at 1).  ``x``
    contracts its trailing axes against them (e.g. [B, S, H, Dh] against
    wo's [H, Dh, D]); output keeps x's leading axes + the weight's output
    axes."""
    w, s = leaf["q"], leaf["s"]
    n_c = sum(1 for i in range(w.ndim) if s.shape[i] == 1 and w.shape[i] > 1)
    n_c = max(n_c, 1)
    k_tot = 1
    for d in w.shape[:n_c]:
        k_tot *= d
    # Collapse trailing x axes until the contraction width matches.
    n_x, prod = 0, 1
    while prod < k_tot:
        n_x += 1
        prod *= x.shape[-n_x]
    assert prod == k_tot, (x.shape, w.shape)
    lead = x.shape[:-n_x]
    xq, ax = quantize_act(x.reshape(*lead, k_tot))
    y = jax.lax.dot_general(
        xq.reshape(-1, k_tot), w.reshape(k_tot, -1),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ).astype(jnp.float32)
    y = y * ax.reshape(-1, 1) * s.reshape(1, -1)
    return y.reshape(*lead, *w.shape[n_c:]).astype(out_dtype)


def quantized_bytes(params: dict) -> tuple[int, int]:
    """(quantized_total, bf16_equivalent) parameter bytes — the HBM
    traffic ratio a decode step sees.

    The numerator is what the quantized tree actually streams (int8
    weights + their f32 scales + the float leaves kept at full
    precision); the denominator is what the SAME weights cost served
    bf16 (2 bytes each, no scale tensors — a float model has none)."""

    def walk(node):
        if isinstance(node, dict) and set(node) == {"q", "s"}:
            actual = (node["q"].size * node["q"].dtype.itemsize
                      + node["s"].size * node["s"].dtype.itemsize)
            return actual, node["q"].size * 2
        if isinstance(node, dict):
            pairs = [walk(v) for v in node.values()]
            return sum(a for a, _ in pairs), sum(b for _, b in pairs)
        return node.size * node.dtype.itemsize, node.size * 2

    return walk(params)
