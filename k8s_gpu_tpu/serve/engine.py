"""KV-cache inference engine for TransformerLM — TPU-first decode loop.

Design (vs. the reference, which delegates all inference to Ollama,
智能风控解决方案.md:196, 250-266):

- **Static shapes everywhere.** The cache is pre-allocated at
  ``[L, B, H, max_seq, Dh]``; prefill writes the prompt's K/V with one
  ``dynamic_update_slice`` per layer, decode writes one position per step.
  The whole generate loop is a single ``lax.scan`` over ``max_new_tokens``
  — one trace, one XLA program, MXU-friendly bf16 compute.
- **Layers ride the scan axis.** Params are stacked ``[L, ...]`` (see
  models/transformer.py); decode scans blocks with the per-layer cache as
  a scanned carry, so one traced block serves every layer.
- **EOS via masking, not control flow.** Finished rows keep decoding but
  their outputs are masked to ``pad_id`` — no data-dependent shapes under
  jit.

The cache-aware attention here is a different compute pattern from the
training forward (query length 1 against a masked cache), so it is
implemented fresh rather than reusing the training path.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer import (
    TransformerConfig, TransformerLM, emb_lookup, wt,
)
from ..ops.paged_attention import paged_attention
from .lora_bank import lora_delta
from .quant import int8_dot


@dataclass(frozen=True)
class SamplingConfig:
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0            # 0 = full vocab
    top_p: float = 0.0        # 0 or 1 = off; else nucleus sampling
    eos_id: int = -1          # -1 = never stop early
    pad_id: int = 0


@dataclass
class DecodeOutput:
    tokens: jnp.ndarray        # [B, max_new_tokens] generated ids (pad after EOS)
    lengths: jnp.ndarray       # [B] number of tokens generated before EOS/budget
    prompt_logits: jnp.ndarray  # [B, V] logits at the last prompt position


def nucleus_mask(scaled, top_p):
    """Nucleus (top-p) mask on temperature-scaled logits — THE single
    implementation (warp_logits for one-shot/speculative, the batcher
    for per-row serving; divergent copies would let the server's
    distribution drift from the accept-ratio math).

    ``top_p`` scalar or [B]; values outside (0, 1) keep everything.  A
    token survives iff the mass of strictly-better tokens is below
    top_p, so the nucleus always contains the argmax; -inf entries
    (constraint masks) sort to the tail with zero mass."""
    top_p = jnp.asarray(top_p, jnp.float32)
    eff = jnp.where((top_p > 0.0) & (top_p < 1.0), top_p, 1.0)
    srt = jnp.sort(scaled, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(srt, axis=-1)
    before = jnp.cumsum(probs, axis=-1) - probs
    keep = before < eff[..., None]
    n_keep = keep.sum(axis=-1, keepdims=True)
    thresh = jnp.take_along_axis(srt, n_keep - 1, axis=-1)
    masked = jnp.where(scaled < thresh, -jnp.inf, scaled)
    # Rows with top_p off must be BIT-IDENTICAL whether or not a
    # co-scheduled request uses top-p: float cumsum can reach 1.0 before
    # the tail, so `before < 1.0` alone may clip it.  Bypass explicitly.
    return jnp.where(eff[..., None] < 1.0, masked, scaled)


def _empty_cache(cfg: TransformerConfig, batch: int, max_seq: int,
                 kv_quant: bool = False):
    # kv_heads, not n_heads: under GQA the cache is the whole point —
    # it shrinks by the query-group factor.
    shape = (cfg.n_layers, batch, cfg.kv_heads, max_seq, cfg.d_head)
    if kv_quant:
        # Int8 KV with one f32 scale per (layer, row, head, position):
        # the cache — serving's HBM ceiling (VERDICT r3 weak #4) — drops
        # to 1 byte/elem + 4/d_head ≈ 0.53× of bf16, and decode's
        # bandwidth-bound cache reads stream half the bytes.  Scales ride
        # a parallel tree leaf so every splice/scan/donate path treats
        # the pair as one pytree.
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_s": jnp.zeros(shape[:-1], jnp.float32),
            "v_s": jnp.zeros(shape[:-1], jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _empty_cache_paged(cfg: TransformerConfig, n_blocks: int, page: int,
                       kv_quant: bool = False):
    """Paged KV pool: physical blocks of ``page`` positions shared by all
    slots through per-slot page tables (the vLLM PagedAttention memory
    model, XLA-shaped).  A slot's cache bytes scale with the tokens it
    USES — ceil(len/page) blocks — instead of reserving max_seq
    (VERDICT r4 weak #6: the dense pool wastes proportionally on
    mixed-length traffic).  Block 0 is the TRASH block: page-table rows
    of retired slots point at it, so garbage in-flight writes land
    somewhere harmless instead of corrupting a reused block."""
    shape = (cfg.n_layers, n_blocks, cfg.kv_heads, page, cfg.d_head)
    if kv_quant:
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_s": jnp.zeros(shape[:-1], jnp.float32),
            "v_s": jnp.zeros(shape[:-1], jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _quantize_kv(x):
    """x [..., Dh] → (int8 values, f32 scale [...]): symmetric per-vector
    absmax quantization — the head-dim vector at one (row, head,
    position) shares one scale, the grain attention consumes it at."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


class InferenceEngine:
    """Prefill + decode for a TransformerLM.

    ``generate`` is the user surface; ``prefill``/``decode_step`` are exposed
    for servers that interleave requests.  All three are jittable; generate
    jits itself on first use and re-traces only when the (B, S, max_new)
    shape bucket changes.
    """

    def __init__(
        self,
        model: TransformerLM,
        max_seq: int | None = None,
        mesh: Mesh | None = None,
        kv_quant: bool = False,
        attn_impl: str | None = None,
        int8_compute: bool = False,
    ):
        """``mesh``: shard serving over devices — heads ('tp') on the KV
        cache and, via the params' own shardings, the projection matmuls;
        batch rows over 'dp'.  XLA propagates the annotations through the
        decode scan, so tp-sharded serving is the same program with
        sharding constraints attached (the GSPMD idiom, not a rewrite).

        ``kv_quant``: store the KV cache int8 with per-(head, position)
        f32 scales (_quantize_kv) — ~1.9× the slot capacity at fixed HBM
        and half the bytes on every bandwidth-bound decode cache read;
        weights stay whatever ``params`` carries (serve/quant.py is the
        weight side).

        ``attn_impl``: how paged decode/verify reads attention —
        ``"gather"`` (materialize the first t_hi pages row-contiguously,
        the default) or ``"paged_kernel"`` (the fused Pallas kernel in
        ops/paged_attention.py consumes the page tables in-kernel; falls
        back to gather automatically when shapes don't tile).  ``None``
        defers to ``cfg.attn_impl``.  Dense caches are untouched either
        way.

        ``int8_compute``: run the q/k/v/o, MLP and head matmuls as true
        int8 × int8 → int32 (quant.int8_dot: dynamic per-row activation
        quantization against the leaf's per-channel scales) wherever the
        param leaf is quantized.  Meant for the speculative DRAFT engine
        — draft quantization error only moves the acceptance rate, never
        correctness — so MoE params are unsupported here."""
        self.model = model
        self.cfg = model.cfg
        self.max_seq = max_seq or self.cfg.max_seq
        self.mesh = mesh
        self.kv_quant = bool(kv_quant)
        self.attn_impl = attn_impl or getattr(self.cfg, "attn_impl", "gather")
        if self.attn_impl not in ("gather", "paged_kernel"):
            raise ValueError(
                f"attn_impl={self.attn_impl!r} — expected 'gather' or "
                "'paged_kernel'"
            )
        self.int8_compute = bool(int8_compute)
        if self.int8_compute and self.cfg.moe:
            raise ValueError(
                "int8_compute targets dense draft models — MoE dispatch "
                "keeps the wt() dequant path"
            )
        if mesh is not None:
            tp = mesh.shape.get("tp", 1)
            if tp > 1 and self.cfg.kv_heads % tp != 0:
                raise ValueError(
                    f"n_kv_heads={self.cfg.kv_heads} must be a multiple of "
                    f"tp={tp} — the KV cache's head axis shards over 'tp'"
                )
        self._generate_jit = jax.jit(
            self._generate,
            static_argnames=("max_new_tokens", "sampling"),
        )

    def _constrain_cache(self, cache):
        """KV cache [L, B, H, T, Dh]: batch over dp, heads over tp.
        Quant scales [L, B, H, T] shard the same way minus the head-dim
        axis."""
        if self.mesh is None:
            return cache

        def one(x):
            spec = (
                P(None, "dp", "tp", None, None) if x.ndim == 5
                else P(None, "dp", "tp", None)
            )
            return jax.lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(self.mesh, spec)
            )

        return jax.tree.map(one, cache)

    # -- cache-aware blocks ------------------------------------------------
    def _attend_cached(self, q, k_cache, v_cache, kv_len_mask,
                       k_scale=None, v_scale=None):
        """q: [B, Sq, H, Dh]; caches [B, KH, T, Dh]; kv_len_mask
        [B, Sq, T] True where attention is allowed.  GQA (KH < H) groups
        the query heads against their shared K/V head via a reshape —
        no repeat of the cache ever materializes.

        ``k_scale``/``v_scale`` [B, KH, T] (kv_quant): the caches arrive
        int8 and dequantize HERE, on the way into the score/value
        matmuls — XLA fuses the convert+scale into the dot read, so HBM
        traffic stays int8-sized."""
        if k_scale is not None:
            k_cache = k_cache.astype(q.dtype) * k_scale[..., None].astype(q.dtype)
            v_cache = v_cache.astype(q.dtype) * v_scale[..., None].astype(q.dtype)
        cfg = self.cfg
        scale = cfg.d_head ** -0.5
        H, KH = cfg.n_heads, cfg.kv_heads
        if H == KH:
            s = jnp.einsum("bqhd,bhkd->bhqk", q, k_cache) * scale
            s = jnp.where(kv_len_mask[:, None], s, -1e30)
            p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
            return jnp.einsum("bhqk,bhkd->bqhd", p, v_cache)
        B, Sq = q.shape[0], q.shape[1]
        G = H // KH
        qg = q.reshape(B, Sq, KH, G, cfg.d_head)
        s = jnp.einsum("bqhgd,bhtd->bhgqt", qg, k_cache) * scale
        s = jnp.where(kv_len_mask[:, None, None], s, -1e30)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        o = jnp.einsum("bhgqt,bhtd->bqhgd", p, v_cache)
        return o.reshape(B, Sq, H, cfg.d_head)

    @staticmethod
    def _cache_store(arr, val, start, sq, layer=None):
        """Write ``val`` [B, KH, Sq, *rest] into ``arr`` [B, KH, T, *rest]
        at ``start`` — the single owner of the three write geometries
        (rank-generic so int8 values and their rank-3 scales share it):

        - scalar start: all rows at one offset (prefill, uniform decode);
        - [B] start, Sq == 1: per-row scatter (continuous batching);
        - [B] start, Sq == W: per-row window (the extend_multi verify;
          out-of-range garbage-row writes drop by scatter semantics).

        ``layer`` (static int): ``arr`` is the full stacked
        [L, B, KH, T, *rest] cache and the write lands at arr[layer] —
        the unrolled-decode path scatters straight into the big buffer so
        XLA updates it in place.  The layer-scan path would instead copy
        the whole cache through the scan's stacked-output buffer every
        decode step (~1 GB/step on the flagship pool — measured 10 ms vs
        2 ms per step on v5e)."""
        if layer is None:
            if jnp.ndim(start) == 0:
                idx = (0, 0, start) + (0,) * (arr.ndim - 3)
                return jax.lax.dynamic_update_slice(
                    arr, val.astype(arr.dtype), idx
                )
            if sq == 1:
                rows = jnp.arange(arr.shape[0])
                return arr.at[rows, :, start].set(
                    val[:, :, 0].astype(arr.dtype)
                )
            B, W = val.shape[0], sq
            rows = jnp.arange(B)[:, None]                   # [B, 1]
            cols = start[:, None] + jnp.arange(W)[None]     # [B, W]
            # Advanced indices split by the ':' slice put the [B, W] index
            # dims first, so the update takes [B, W, KH, ...] layout.
            return arr.at[rows, :, cols].set(
                jnp.moveaxis(val, 2, 1).astype(arr.dtype)
            )
        if jnp.ndim(start) == 0:
            idx = (layer, 0, 0, start) + (0,) * (arr.ndim - 4)
            return jax.lax.dynamic_update_slice(
                arr, val[None].astype(arr.dtype), idx
            )
        if sq == 1:
            rows = jnp.arange(arr.shape[1])
            return arr.at[layer, rows, :, start].set(
                val[:, :, 0].astype(arr.dtype)
            )
        B, W = val.shape[0], sq
        rows = jnp.arange(B)[:, None]                       # [B, 1]
        cols = start[:, None] + jnp.arange(W)[None]         # [B, W]
        return arr.at[layer, rows, :, cols].set(
            jnp.moveaxis(val, 2, 1).astype(arr.dtype)
        )

    @staticmethod
    def _paged_store(arr, val, pages, pos, page: int, layer: int):
        """Scatter ``val`` [B, KH, Sq, *rest] into the paged pool
        ``arr`` [L, NB, KH, page, *rest] through per-row page tables
        ``pages`` [B, MP] at positions ``pos`` ([B] when Sq == 1, else
        the window starts).  Logical position p of row b lives at
        physical (pages[b, p // page], p % page).

        Positions past the table (p >= MP*page) route to block 0, the
        trash block — NOT clamped to the last entry.  Garbage rows of
        retired-but-unnoticed slots keep advancing their positions
        (speculative rounds advance up to K+1 per sub-round), and XLA's
        clamped gather would otherwise alias their writes onto the
        table's LAST mapped block — for a max-length tenant that is a
        live (possibly shared) block.  This guard is what makes paged
        KV safe under speculative decode's rollback/overrun behavior."""
        B, _, sq = val.shape[0], val.shape[1], val.shape[2]
        mp = pages.shape[1]
        rows = jnp.arange(B)
        if sq == 1:
            p_idx = pos // page
            blk = jnp.where(
                p_idx < mp, pages[rows, jnp.minimum(p_idx, mp - 1)], 0
            )                                       # [B]
            off = pos % page                        # [B]
            return arr.at[layer, blk, :, off].set(
                val[:, :, 0].astype(arr.dtype)
            )
        q_pos = pos[:, None] + jnp.arange(sq, dtype=jnp.int32)[None]  # [B,W]
        p_idx = q_pos // page
        blk = jnp.where(
            p_idx < mp,
            pages[rows[:, None], jnp.minimum(p_idx, mp - 1)], 0,
        )                                           # [B, W]
        off = q_pos % page                          # [B, W]
        return arr.at[layer, blk, :, off].set(
            jnp.moveaxis(val, 2, 1).astype(arr.dtype)
        )

    @staticmethod
    def _paged_read(arr, tbl, layer: int):
        """Gather a row-contiguous view [B, KH, P*page, *rest] of the
        pages in ``tbl`` [B, P].  ``tbl`` is the page table already
        sliced to the read bound (``pages[:, :p_hi]``) — the caller
        hoists the bound ONCE so all four pool leaves (k/v + scales
        under kv_quant) gather through the same sliced-table operand
        and none of them touches entries past ``p_hi``."""
        sel = arr[layer][tbl]                       # [B, P, KH, page, *rest]
        sel = jnp.moveaxis(sel, 2, 1)               # [B, KH, P, page, *rest]
        return sel.reshape(
            sel.shape[0], sel.shape[1], sel.shape[2] * sel.shape[3],
            *sel.shape[4:]
        )

    def _block_cached(self, x, lp, lc, positions, start, mask,
                      moe_full_capacity=None, lp_ad=None, adapter_idx=None,
                      layer=None, pages=None, page: int = 0,
                      kv_start=None):
        """One transformer block over query slice x [B,Sq,D] with the K/V for
        the slice written into the layer cache ``lc`` (k/v [+ k_s/v_s
        when kv_quant]) at ``start``.  Returns (x_out, new_lc).

        ``start`` is a scalar (all rows write at the same offset — prefill
        and uniform decode) or a [B] vector (each row writes at its own
        position — continuous batching; requires Sq == 1).

        ``moe_full_capacity``: None = full capacity only at Sq == 1 (the
        decode default); extend_multi passes True so a W-wide verify
        routes experts exactly like the width-1 decode it stands in for.

        ``layer`` (static int, unrolled-decode path): ``lc`` holds the
        FULL stacked [L, ...] cache arrays; writes scatter into
        lc[...][layer] in place and attention reads the [layer] slice —
        see _cache_store for why this beats the layer scan at decode."""
        m = self.model
        dt = self.cfg.dtype
        h = m._rmsnorm(x, lp["ln1"])
        if self.int8_compute and isinstance(lp["wq"], dict):
            q = int8_dot(h, lp["wq"], dt)
            k = int8_dot(h, lp["wk"], dt)
            v = int8_dot(h, lp["wv"], dt)
        else:
            q = jnp.einsum("bsd,dhk->bshk", h, wt(lp["wq"], dt))
            k = jnp.einsum("bsd,dhk->bshk", h, wt(lp["wk"], dt))
            v = jnp.einsum("bsd,dhk->bshk", h, wt(lp["wv"], dt))
        if lp_ad is not None:
            # Per-row LoRA deltas (serve/lora_bank.py): same inputs the
            # base matmuls consume, low-rank path gathered by row index.
            B_, Sq_ = x.shape[0], x.shape[1]
            hd = (B_, Sq_, self.cfg.n_heads, self.cfg.d_head)
            kvd = (B_, Sq_, self.cfg.kv_heads, self.cfg.d_head)
            if "wq" in lp_ad:
                q = q + lora_delta(h, lp_ad["wq"], adapter_idx, dt).reshape(hd)
            if "wk" in lp_ad:
                k = k + lora_delta(h, lp_ad["wk"], adapter_idx, dt).reshape(kvd)
            if "wv" in lp_ad:
                v = v + lora_delta(h, lp_ad["wv"], adapter_idx, dt).reshape(kvd)
        q = m._rope(q, positions)
        k = m._rope(k, positions)
        k = k.transpose(0, 2, 1, 3)  # [B,H,Sq,Dh]
        v = v.transpose(0, 2, 1, 3)
        sq = x.shape[1]
        lc = dict(lc)
        if pages is not None:
            if self.kv_quant:
                kq, ks = _quantize_kv(k)
                vq, vs = _quantize_kv(v)
                lc["k"] = self._paged_store(lc["k"], kq, pages, start, page, layer)
                lc["v"] = self._paged_store(lc["v"], vq, pages, start, page, layer)
                lc["k_s"] = self._paged_store(lc["k_s"], ks, pages, start, page, layer)
                lc["v_s"] = self._paged_store(lc["v_s"], vs, pages, start, page, layer)
            else:
                lc["k"] = self._paged_store(lc["k"], k, pages, start, page, layer)
                lc["v"] = self._paged_store(lc["v"], v, pages, start, page, layer)
            T_eff = mask.shape[-1]
            if (self.attn_impl == "paged_kernel" and kv_start is not None
                    and jnp.ndim(start) == 1):
                # Fused path (ops/paged_attention.py): the kernel walks
                # the page tables itself — no gathered K/V copy.  The
                # per-row mask is rebuilt in-kernel from start/kv_start,
                # the same formula decode_step_multi/extend_multi used
                # to build ``mask``; shapes that don't tile fall back to
                # the gather oracle inside the wrapper.
                o = paged_attention(
                    q, lc["k"][layer], lc["v"][layer], pages,
                    start, kv_start, page=page, t_hi=T_eff,
                    k_scale=lc["k_s"][layer] if "k_s" in lc else None,
                    v_scale=lc["v_s"][layer] if "v_s" in lc else None,
                )
            else:
                p_hi = T_eff // page
                tbl = pages[:, :p_hi]  # bound hoisted: one slice, 4 gathers
                k_read = self._paged_read(lc["k"], tbl, layer)
                v_read = self._paged_read(lc["v"], tbl, layer)
                ks_read = (self._paged_read(lc["k_s"], tbl, layer)
                           if "k_s" in lc else None)
                vs_read = (self._paged_read(lc["v_s"], tbl, layer)
                           if "v_s" in lc else None)
                o = self._attend_cached(
                    q, k_read, v_read, mask,
                    k_scale=ks_read, v_scale=vs_read,
                )
            return self._block_epilogue(
                x, o, lp, lp_ad, adapter_idx, mask, moe_full_capacity
            ), lc
        if self.kv_quant:
            kq, ks = _quantize_kv(k)
            vq, vs = _quantize_kv(v)
            lc["k"] = self._cache_store(lc["k"], kq, start, sq, layer)
            lc["v"] = self._cache_store(lc["v"], vq, start, sq, layer)
            lc["k_s"] = self._cache_store(lc["k_s"], ks, start, sq, layer)
            lc["v_s"] = self._cache_store(lc["v_s"], vs, start, sq, layer)
        else:
            lc["k"] = self._cache_store(lc["k"], k, start, sq, layer)
            lc["v"] = self._cache_store(lc["v"], v, start, sq, layer)
        # The mask's trailing dim is the attention-read bound (t_hi): the
        # cache READ shrinks to it while writes target the full buffer —
        # a decode step at position ~50 streams 256 slots, not max_seq.
        T_eff = mask.shape[-1]
        if layer is None:
            k_read = lc["k"][:, :, :T_eff]
            v_read = lc["v"][:, :, :T_eff]
            ks_read = lc["k_s"][:, :, :T_eff] if "k_s" in lc else None
            vs_read = lc["v_s"][:, :, :T_eff] if "v_s" in lc else None
        else:
            k_read = lc["k"][layer, :, :, :T_eff]
            v_read = lc["v"][layer, :, :, :T_eff]
            ks_read = lc["k_s"][layer, :, :, :T_eff] if "k_s" in lc else None
            vs_read = lc["v_s"][layer, :, :, :T_eff] if "v_s" in lc else None
        o = self._attend_cached(
            q, k_read, v_read, mask,
            k_scale=ks_read, v_scale=vs_read,
        )
        return self._block_epilogue(
            x, o, lp, lp_ad, adapter_idx, mask, moe_full_capacity
        ), lc

    def _block_epilogue(self, x, o, lp, lp_ad, adapter_idx, mask,
                        moe_full_capacity):
        """Attention output projection + MLP — shared by the dense and
        paged cache branches of _block_cached."""
        m = self.model
        dt = self.cfg.dtype
        int8 = self.int8_compute and isinstance(lp["wo"], dict)
        if int8:
            attn_out = int8_dot(o, lp["wo"], dt)
        else:
            attn_out = jnp.einsum("bshk,hkd->bsd", o, wt(lp["wo"], dt))
        if lp_ad is not None and "wo" in lp_ad:
            o_flat = o.reshape(o.shape[0], o.shape[1], -1)
            attn_out = attn_out + lora_delta(
                o_flat, lp_ad["wo"], adapter_idx, dt
            )
        x = x + attn_out
        h2 = m._rmsnorm(x, lp["ln2"])
        if self.cfg.moe:
            # Full capacity only at decode (query length 1): there G = B and
            # capacity dropping would couple independent requests.  Prefill
            # keeps the training forward's capped dispatch — same logits,
            # same [G, E, cap] memory footprint.  Padded query rows (their
            # attention mask is all-False) are excluded from routing so they
            # can't consume expert capacity ahead of real tokens.
            full = (x.shape[1] == 1 if moe_full_capacity is None
                    else moe_full_capacity)
            y, _ = m._moe_mlp(
                h2, lp, full_capacity=full,
                token_mask=mask.any(-1),
            )
            x = x + y
        elif int8:
            g = int8_dot(h2, lp["wi_gate"], dt)
            u = int8_dot(h2, lp["wi_up"], dt)
            x = x + int8_dot(jax.nn.silu(g) * u, lp["wo_mlp"], dt)
        else:
            x = x + m._dense_mlp(h2, lp)
        return x

    def _run_blocks(self, params, x, cache, positions, start, mask,
                    moe_full_capacity=None, adapters=None, adapter_idx=None,
                    unroll_layers=False, pages=None, page: int = 0,
                    kv_start=None):
        """``unroll_layers``: decode paths set True — a Python loop over
        layers scatters each K/V write straight into the stacked cache
        (in-place under XLA aliasing), where the layer scan would round-
        trip the whole pool cache through the scan's stacked-output
        buffer every step.  Prefill keeps the scan: its program is large
        (full-sequence attention per block) and one traced block keeps
        compile time O(1) in depth, while its per-call cache copy is
        amortized over the whole prompt."""
        if unroll_layers:
            new_cache = cache
            for l in range(self.cfg.n_layers):
                lp = jax.tree.map(lambda a: a[l], params["blocks"])
                lp_ad = (
                    jax.tree.map(lambda a: a[l], adapters)
                    if adapters is not None else None
                )
                x, new_cache = self._block_cached(
                    x, lp, new_cache, positions, start, mask,
                    moe_full_capacity=moe_full_capacity,
                    lp_ad=lp_ad, adapter_idx=adapter_idx, layer=l,
                    pages=pages, page=page, kv_start=kv_start,
                )
            return self._head(params, x), new_cache
        assert pages is None, "paged KV requires the unrolled decode path"
        if adapters is None:
            def scan_fn(carry, layer):
                lp, lc = layer
                y, lc = self._block_cached(
                    carry, lp, lc, positions, start, mask,
                    moe_full_capacity=moe_full_capacity,
                )
                return y, lc

            xs = (params["blocks"], cache)
        else:
            def scan_fn(carry, layer):
                lp, lc, lp_ad = layer
                y, lc = self._block_cached(
                    carry, lp, lc, positions, start, mask,
                    moe_full_capacity=moe_full_capacity,
                    lp_ad=lp_ad, adapter_idx=adapter_idx,
                )
                return y, lc

            xs = (params["blocks"], cache, adapters)
        x, new_cache = jax.lax.scan(scan_fn, x, xs)
        return self._head(params, x), new_cache

    def _head(self, params, x):
        """Shared epilogue for both _run_blocks paths: final RMSNorm +
        vocabulary projection in f32."""
        x = self.model._rmsnorm(x, params["final_norm"])
        if self.int8_compute and isinstance(params["head"], dict):
            logits = int8_dot(x, params["head"], self.cfg.dtype)
        else:
            logits = jnp.einsum(
                "bsd,dv->bsv", x, wt(params["head"], self.cfg.dtype)
            )
        return logits.astype(jnp.float32)

    # -- public jittable pieces -------------------------------------------
    def prefill(self, params, tokens, pad_left=0, adapters=None,
                adapter_idx=None):
        """tokens [B, S] → (cache, last_logits [B, V]).  S must be ≤ max_seq.

        ``pad_left`` (scalar, may be traced): number of leading positions
        that are padding.  Callers bucket prompts to a few lengths and
        left-pad — pad_left rides through the trace, so prompts of any true
        length share one compiled program per bucket.  Padded slots are
        excluded from attention and RoPE starts at the first real token.
        """
        B, S = tokens.shape
        pad_left = jnp.asarray(pad_left, jnp.int32)
        cache = self._constrain_cache(
            _empty_cache(self.cfg, B, self.max_seq, self.kv_quant)
        )
        x = emb_lookup(params["embed"], tokens, self.cfg.dtype)
        q_idx = jnp.arange(S)
        positions = jnp.maximum(q_idx - pad_left, 0)  # RoPE positions
        # Attention reads only the first S cache slots (the mask width is
        # the read bound — _block_cached): prompt K/V land at [0, S) and
        # the rest of the max_seq cache is untouched zeros.
        t = jnp.arange(S)
        mask = (
            (t[None, :] <= q_idx[:, None])
            & (t[None, :] >= pad_left)
        )
        mask = jnp.broadcast_to(mask, (B, S, S))
        logits, cache = self._run_blocks(
            params, x, cache, positions, 0, mask,
            adapters=adapters, adapter_idx=adapter_idx,
        )
        return cache, logits[:, -1]

    def decode_step(self, params, cache, pos, token, rope_pos=None,
                    kv_start=0, t_hi=None):
        """token [B] at cache position pos (scalar) → (cache, logits [B,V]).
        ``rope_pos`` is the rotary position (defaults to pos; differs when
        the prompt was left-padded); ``kv_start`` masks cache slots below it.
        ``t_hi`` (static): attention-read bound — generate passes
        S + max_new_tokens so a short generation never streams the full
        max_seq cache per step.
        """
        B = token.shape[0]
        x = emb_lookup(params["embed"], token, self.cfg.dtype)[:, None]  # [B,1,D]
        pos = jnp.asarray(pos, jnp.int32).reshape(())
        rope = pos if rope_pos is None else jnp.asarray(rope_pos, jnp.int32).reshape(())
        kv_start = jnp.asarray(kv_start, jnp.int32)
        T = t_hi if t_hi is not None else self.max_seq
        t = jnp.arange(T)
        mask = jnp.broadcast_to(
            ((t <= pos) & (t >= kv_start))[None, None], (B, 1, T)
        )
        logits, cache = self._run_blocks(
            params, x, cache, rope[None], pos, mask, unroll_layers=True
        )
        return cache, logits[:, 0]

    def decode_step_multi(self, params, cache, token, pos, rope_pos,
                          kv_start, adapters=None, adapter_idx=None,
                          t_hi=None, pages=None, page: int = 0):
        """One decode step where every batch row sits at its *own* cache
        position — the continuous-batching kernel.

        token [B]; pos/rope_pos/kv_start [B] int32.  Row b attends to cache
        slots [kv_start[b], pos[b]] and writes its new K/V at pos[b].
        Returns (cache, logits [B, V]).  Idle rows are the caller's business:
        their outputs are valid numbers that simply go unused.

        ``t_hi`` (static): upper bound on every LIVE row's pos — the
        attention read covers cache[..., :t_hi] only (the scheduler
        buckets it pow2 from its host position mirror), cutting decode's
        bandwidth-bound cache traffic by max_seq/t_hi at short contexts.

        ``pages`` [B, MP] int32 + ``page`` (static): paged-KV mode —
        ``cache`` leaves are the [L, NB, KH, page, ...] physical pool
        and row b's logical position p lives at block pages[b, p//page].
        t_hi rounds up to a page multiple (the read gathers whole
        pages)."""
        B = token.shape[0]
        x = emb_lookup(params["embed"], token, self.cfg.dtype)[:, None]  # [B,1,D]
        pos = jnp.asarray(pos, jnp.int32)
        T = t_hi if t_hi is not None else self.max_seq
        if pages is not None:
            T = -(-T // page) * page  # whole pages only
        t = jnp.arange(T)
        mask = (
            (t[None, :] <= pos[:, None]) & (t[None, :] >= kv_start[:, None])
        )[:, None, :]  # [B, 1, T]
        logits, cache = self._run_blocks(
            params, x, cache, jnp.asarray(rope_pos, jnp.int32)[:, None], pos,
            mask, adapters=adapters, adapter_idx=adapter_idx,
            unroll_layers=True, pages=pages, page=page,
            kv_start=jnp.asarray(kv_start, jnp.int32),
        )
        return cache, logits[:, 0]

    def extend_multi(self, params, cache, tokens, start, rope_start,
                     kv_start, adapters=None, adapter_idx=None,
                     t_hi=None, pages=None, page: int = 0):
        """Multi-token cached forward where every row writes its *own*
        window — the speculative-decoding verify kernel, and (with
        ``pages``) the paged pool's prefill/suffix-extend kernel.

        tokens [B, W]; start/rope_start/kv_start [B] int32.  Row b writes
        K/V for its W tokens at cache positions start[b]..start[b]+W-1 and
        each query position start[b]+j attends to cache slots
        [kv_start[b], start[b]+j] (causal within the window, full prefix
        before it).  Returns (cache, logits [B, W, V]): logits[:, j]
        predicts the token after tokens[:, j].

        Rollback is free: a later round that re-writes positions ≤ p and
        masks t ≤ p never sees the stale K/V a rejected draft left behind
        (same property decode_step relies on across requeued slots).

        ``pages`` [B, MP] int32 + ``page`` (static): paged-KV mode —
        ``cache`` leaves are the [L, NB, KH, page, ...] physical pool;
        window writes scatter through the page tables (_paged_store's
        window branch; out-of-table positions land in the trash block)
        and reads gather whole pages, so t_hi rounds up to a page
        multiple.  This is what makes speculative verify — and shared-
        prefix admission — run directly on the paged pool."""
        B, W = tokens.shape
        start = jnp.asarray(start, jnp.int32)
        q_pos = start[:, None] + jnp.arange(W, dtype=jnp.int32)[None]  # [B, W]
        T = t_hi if t_hi is not None else self.max_seq
        if pages is not None:
            T = -(-T // page) * page  # whole pages only
        t = jnp.arange(T)
        mask = (
            (t[None, None, :] <= q_pos[:, :, None])
            & (t[None, None, :] >= jnp.asarray(kv_start, jnp.int32)[:, None, None])
        )  # [B, W, T]
        x = emb_lookup(params["embed"], tokens, self.cfg.dtype)  # [B, W, D]
        rope = (
            jnp.asarray(rope_start, jnp.int32)[:, None]
            + jnp.arange(W, dtype=jnp.int32)[None]
        )
        # moe_full_capacity=True: the verify stands in for W width-1
        # decode steps, whose routing never capacity-drops — a capped
        # dispatch here would make verify logits diverge from the decode
        # path and break speculative greedy-exactness for MoE targets.
        logits, cache = self._run_blocks(
            params, x, cache, rope, start, mask, moe_full_capacity=True,
            adapters=adapters, adapter_idx=adapter_idx,
            unroll_layers=True, pages=pages, page=page,
            kv_start=jnp.asarray(kv_start, jnp.int32),
        )
        return cache, logits

    # -- sampling ----------------------------------------------------------
    @staticmethod
    def warp_logits(logits, sampling: SamplingConfig):
        """Temperature + top-k as one logits transform.  The single source
        of truth for the sampling distribution: ``_sample`` draws from it
        and speculative decoding softmaxes it into the explicit p/q
        probabilities its accept-ratio math needs — sharing the warp is
        what makes the rejection-sampling exactness guarantee structural
        rather than a convention two code paths must remember."""
        l = logits.astype(jnp.float32) / sampling.temperature
        if sampling.top_k > 0:
            top, _ = jax.lax.top_k(l, sampling.top_k)
            l = jnp.where(l < top[..., -1:], -jnp.inf, l)
        if 0.0 < sampling.top_p < 1.0:
            l = nucleus_mask(l, sampling.top_p)
        return l

    @staticmethod
    def _sample(logits, key, sampling: SamplingConfig):
        if sampling.temperature <= 0:
            return jnp.argmax(logits, axis=-1)
        return jax.random.categorical(
            key, InferenceEngine.warp_logits(logits, sampling), axis=-1
        )

    # -- generate ----------------------------------------------------------
    def _generate(self, params, prompt, key, pad_left, *,
                  max_new_tokens: int, sampling: SamplingConfig):
        B, S = prompt.shape
        cache, last_logits = self.prefill(params, prompt, pad_left)
        key, k0 = jax.random.split(key)
        first = self._sample(last_logits, k0, sampling)
        valid0 = first != sampling.eos_id
        done0 = ~valid0

        t_hi = min(S + max_new_tokens, self.max_seq)

        def step(carry, i):
            cache, token, done, k = carry
            k, sub = jax.random.split(k)
            cache, logits = self.decode_step(
                params, cache, S + i, token,
                rope_pos=S + i - pad_left, kv_start=pad_left, t_hi=t_hi,
            )
            nxt = self._sample(logits, sub, sampling)
            valid = ~done & (nxt != sampling.eos_id)
            feed = jnp.where(done, sampling.pad_id, nxt)
            done = done | (nxt == sampling.eos_id)
            return (cache, feed, done, k), (
                jnp.where(valid, nxt, sampling.pad_id), valid,
            )

        emitted0 = jnp.where(valid0, first, sampling.pad_id)
        if max_new_tokens > 1:
            _, (rest, valids) = jax.lax.scan(
                step,
                (cache, jnp.where(done0, sampling.pad_id, first), done0, key),
                jnp.arange(max_new_tokens - 1),
            )
            toks = jnp.concatenate([emitted0[:, None], rest.T], axis=1)
            lengths = valid0.astype(jnp.int32) + valids.T.sum(axis=1, dtype=jnp.int32)
        else:
            toks = emitted0[:, None]
            lengths = valid0.astype(jnp.int32)
        # dict, not DecodeOutput: jit outputs must be pytrees.
        return {"tokens": toks, "lengths": lengths, "prompt_logits": last_logits}

    # -- constrained generation -------------------------------------------
    def _generate_constrained(self, params, prompt, key, pad_left, tables,
                              start, *, max_new_tokens: int,
                              sampling: SamplingConfig):
        nxt_tab, allow_tab, accepting = (
            tables["next"], tables["allowed"], tables["accepting"],
        )
        B, S = prompt.shape
        cache, last_logits = self.prefill(params, prompt, pad_left)
        state = jnp.full((B,), start, jnp.int32)
        done = jnp.zeros((B,), bool)

        def pick(logits, st, dn, k):
            mask = allow_tab[st] & ~dn[:, None]
            any_ok = mask.any(-1)
            masked = jnp.where(mask, logits, -jnp.inf)
            tok = self._sample(masked, k, sampling)
            # Invalid rows (all -inf) sample garbage; pad-and-freeze them.
            tok = jnp.where(any_ok, tok, sampling.pad_id).astype(jnp.int32)
            # EOS retires a row here exactly as the batcher's constrained
            # path does — same stopping semantics on both surfaces.  The
            # EOS token itself is not emitted and the DFA state stays put
            # (``accepted`` reflects the string BEFORE the stop token).
            if sampling.eos_id >= 0:
                hit_eos = any_ok & ~dn & (tok == sampling.eos_id)
            else:
                hit_eos = jnp.zeros_like(any_ok)
            valid = any_ok & ~dn & ~hit_eos
            emit = jnp.where(valid, tok, sampling.pad_id).astype(jnp.int32)
            new_state = jnp.where(valid, nxt_tab[st, emit], st)
            return emit, valid, new_state, dn | ~any_ok | hit_eos

        key, k0 = jax.random.split(key)
        tok0, valid0, state, done = pick(last_logits, state, done, k0)

        t_hi = min(S + max_new_tokens, self.max_seq)

        def step(carry, i):
            cache, token, st, dn, k = carry
            k, sub = jax.random.split(k)
            cache, logits = self.decode_step(
                params, cache, S + i, token,
                rope_pos=S + i - pad_left, kv_start=pad_left, t_hi=t_hi,
            )
            # pick() already pads invalid rows, so tok doubles as the
            # feed token and the emitted value.
            tok, valid, st, dn = pick(logits, st, dn, sub)
            return (cache, tok, st, dn, k), (tok, valid)

        if max_new_tokens > 1:
            (cache, _, state, done, _), (rest, valids) = jax.lax.scan(
                step, (cache, tok0, state, done, key),
                jnp.arange(max_new_tokens - 1),
            )
            toks = jnp.concatenate([tok0[:, None], rest.T], axis=1)
            lengths = valid0.astype(jnp.int32) + valids.T.sum(
                axis=1, dtype=jnp.int32
            )
        else:
            toks = tok0[:, None]
            lengths = valid0.astype(jnp.int32)
        return {
            "tokens": toks, "lengths": lengths,
            "prompt_logits": last_logits,
            "accepted": accepting[state],
        }

    def generate_constrained(self, params, prompt, constraint, *,
                             max_new_tokens: int = 32,
                             sampling: SamplingConfig = SamplingConfig(),
                             key=None, pad_left: int = 0):
        """Generate under a RegexConstraint (serve/constrain.py).

        Each row carries a DFA state; the state's ``allowed`` row masks
        the logits (additive -inf) and the chosen token gathers its next
        state — pure gathers, same scan as unconstrained decode.  A row
        stops at a dead end (no token keeps the string in-language) or
        on ``sampling.eos_id`` — the same stopping rule as the batcher's
        constrained path;
        greedy decoding is maximal-munch (it continues from accepting
        states that still have continuations).  Returns the generate
        dict + ``accepted`` [B]: whether each row stopped in an
        accepting state (its emitted string matches the pattern).
        """
        B, S = prompt.shape
        if S + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt {S} + max_new {max_new_tokens} exceeds max_seq "
                f"{self.max_seq}"
            )
        if constraint.allowed.shape[1] != self.cfg.vocab_size:
            raise ValueError(
                f"constraint built for vocab {constraint.allowed.shape[1]}, "
                f"model has {self.cfg.vocab_size}"
            )
        if key is None:
            key = jax.random.PRNGKey(0)
        tables = {
            "next": constraint.next_state,
            "allowed": constraint.allowed,
            "accepting": constraint.accepting,
        }
        if not hasattr(self, "_constrained_jit"):
            self._constrained_jit = jax.jit(
                self._generate_constrained,
                static_argnames=("max_new_tokens", "sampling"),
            )
        return self._constrained_jit(
            params, prompt, key, jnp.asarray(pad_left, jnp.int32), tables,
            jnp.int32(constraint.start),
            max_new_tokens=max_new_tokens, sampling=sampling,
        )

    def generate(self, params, prompt, *, max_new_tokens: int = 32,
                 sampling: SamplingConfig = SamplingConfig(),
                 key=None, pad_left: int = 0) -> DecodeOutput:
        """prompt [B, S] int32 → DecodeOutput.  Requires
        S + max_new_tokens ≤ max_seq.  ``pad_left``: leading padding count
        when the caller bucketed the prompt (see prefill)."""
        B, S = prompt.shape
        if S + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt {S} + max_new {max_new_tokens} exceeds max_seq "
                f"{self.max_seq}"
            )
        if key is None:
            key = jax.random.PRNGKey(0)
        out = self._generate_jit(
            params, prompt, key, jnp.asarray(pad_left, jnp.int32),
            max_new_tokens=max_new_tokens, sampling=sampling,
        )
        return DecodeOutput(**out)
